"""Process-wide telemetry runtime: spans, counters, gauges, events.

This module is the single registry behind ``heat_tpu.telemetry`` — every
instrumented hot path (the ``jitted()`` replay wrapper, ``ht.fuse`` build
and replay, the communication layer's reshards and collectives, the
compressed rings' wire-byte accounting, guard incidents, checkpoint
save/load/resume) reports here, and every exporter (``snapshot()``, the
JSONL sink, the Perfetto trace writer in :mod:`heat_tpu.telemetry.export`)
reads from here.

Overhead contract
-----------------
Telemetry is off by default and *disabled mode costs one predicate per
site*: instrumented library code guards every report with
``if _core.enabled:`` — a module-attribute load and a branch, no object
allocation, no lock, no clock read.  Enabling flips one module-level
flag; nothing is registered with the compile-cache key context, so
toggling telemetry can never change what a cached program means or force
a retrace (asserted by tests/test_telemetry.py).

The one always-on piece of state is the *dispatch counter*: it predates
telemetry (tier-1 dispatch-count gates consume it through the
:mod:`heat_tpu.core._tracing` shim) and keeps counting with telemetry
disabled.  It is guarded by the registry lock, so threaded serving does
not lose increments.

Determinism
-----------
``enable(deterministic=True)`` replaces the wall clock with a monotone
integer sequence: every ``clock()`` read returns the next integer, so
span timestamps and durations become pure functions of the event order
and two identical runs (after ``reset()``) produce bitwise-identical
event streams.  ``set_clock()`` injects an arbitrary clock — the
resilience incident log stamps its records through :func:`clock`, so
chaos-lane runs can pin time entirely.

Kept free of jax imports (like :mod:`heat_tpu.core._tracing`) so every
core module can import it without ordering constraints.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .hist import Histogram

__all__ = [
    "enabled",
    "enable",
    "disable",
    "is_enabled",
    "is_deterministic",
    "clock",
    "set_clock",
    "span",
    "inc",
    "gauge",
    "observe",
    "histogram",
    "record_event",
    "account_bytes",
    "events",
    "snapshot",
    "reset",
    "set_jsonl",
    "jsonl_path",
    "set_max_events",
    "trace_ctx",
    "current_trace",
    "record_dispatch",
    "dispatch_count",
    "reset_dispatch_count",
    "counting_dispatches",
]

#: THE module-level flag.  Instrumented hot paths read this attribute
#: directly (``if _core.enabled:``); everything else in this module is
#: behind that predicate.
enabled: bool = False

_lock = threading.RLock()
_deterministic = False
_det_seq = 0
_wall: Callable[[], float] = time.monotonic  # injectable via set_clock()

_counters: Dict[str, int] = {}
_gauges: Dict[str, float] = {}
#: per-site span aggregates: site -> [count, total_seconds]
_spans: Dict[str, List[float]] = {}
#: streaming histograms (telemetry.hist.Histogram) fed by observe()
_hists: Dict[str, Histogram] = {}
#: the bounded event list (newest last); spans append one event at exit
_events: List[dict] = []
_MAX_EVENTS = 1 << 16

#: the flight recorder's always-on ring append, registered by
#: :mod:`heat_tpu.telemetry.flight` at import so _emit never has to
#: import it (None until that module loads)
_flight_append: Optional[Callable[[dict], None]] = None

#: the ambient request-trace ids (tentpole: request-scoped tracing).
#: A contextvar, not a threading.local: the serve engine re-establishes
#: it per micro-batch from the Request records, so worker threads and
#: async callers both see the right ids.
_trace_var: "contextvars.ContextVar[Tuple[str, ...]]" = contextvars.ContextVar(
    "heat_tpu_trace_ids", default=()
)

#: optional JSONL sink: every event is also appended to this file
_jsonl = None  # type: Optional[Any]
_jsonl_path: Optional[str] = None

#: Perfetto trace-event buffer; managed by telemetry.export.  Lives here
#: so span/event emission never has to import the exporter.
_trace_buf: Optional[List[dict]] = None

#: thread ids -> small stable indices (first-seen order), so exported
#: ``tid`` values are deterministic in single-threaded runs
_tids: Dict[int, int] = {}


# --------------------------------------------------------------------- #
# clock                                                                 #
# --------------------------------------------------------------------- #
def clock() -> float:
    """The telemetry timestamp source (seconds, monotonic).

    In deterministic mode every read returns the next integer of a
    monotone sequence instead of a wall-clock value; :func:`reset`
    rewinds the sequence, making event streams bitwise replayable.
    The resilience incident log (:mod:`heat_tpu.resilience.incidents`)
    stamps its records through this function, so a test can pin incident
    timestamps with :func:`set_clock` or deterministic mode.
    """
    global _det_seq
    if _deterministic:
        with _lock:
            t = float(_det_seq)
            _det_seq += 1
        return t
    return _wall()


def set_clock(fn: Optional[Callable[[], float]]) -> None:
    """Inject a replacement wall clock (``None`` restores
    ``time.monotonic``).  Ignored while deterministic mode is active."""
    global _wall
    _wall = time.monotonic if fn is None else fn


# --------------------------------------------------------------------- #
# enable / disable                                                      #
# --------------------------------------------------------------------- #
def enable(deterministic: bool = False) -> None:
    """Turn telemetry collection on.

    ``deterministic=True`` switches :func:`clock` to the monotone
    integer sequence (see the module docstring)."""
    global enabled, _deterministic, _det_seq
    with _lock:
        _deterministic = bool(deterministic)
        if _deterministic:
            _det_seq = 0
        enabled = True


def disable() -> None:
    """Turn telemetry collection off (recorded data stays until
    :func:`reset`; :func:`snapshot` answers ``{}`` while disabled)."""
    global enabled, _deterministic
    with _lock:
        enabled = False
        _deterministic = False


def is_enabled() -> bool:
    return enabled


def is_deterministic() -> bool:
    return _deterministic


def reset() -> None:
    """Drop all recorded counters, gauges, span aggregates, and events,
    and rewind the deterministic sequence.  The dispatch counter is NOT
    touched — it predates telemetry and tests scope it with
    :func:`counting_dispatches` instead."""
    global _det_seq
    with _lock:
        _counters.clear()
        _gauges.clear()
        _spans.clear()
        _hists.clear()
        _events.clear()
        _tids.clear()
        if _trace_buf is not None:
            _trace_buf.clear()
        _det_seq = 0


# --------------------------------------------------------------------- #
# emission                                                              #
# --------------------------------------------------------------------- #
def _tid() -> int:
    ident = threading.get_ident()
    t = _tids.get(ident)
    if t is None:
        t = len(_tids) + 1
        _tids[ident] = t
    return t


def _emit(ev: dict) -> None:
    """Append one event under the lock: bounded in-memory list, JSONL
    sink, the flight-recorder ring, and the Perfetto buffer when a trace
    is being collected.

    Overflow of the bounded list is NEVER silent: the drop is counted
    under ``telemetry.events.dropped`` — surfaced by ``snapshot()`` and
    the ``/metrics`` endpoint — so a long-running server that outlives
    the buffer shows exactly how much of the stream it lost.  The JSONL
    sink, flight ring, and trace buffer still receive the event (each is
    bounded or externally drained on its own)."""
    with _lock:
        if len(_events) < _MAX_EVENTS:
            _events.append(ev)
        else:
            _counters["telemetry.events.dropped"] = (
                _counters.get("telemetry.events.dropped", 0) + 1
            )
        if _jsonl is not None:
            _jsonl.write(json.dumps(ev, sort_keys=True, default=str) + "\n")
        if _flight_append is not None:
            _flight_append(ev)
        if _trace_buf is not None:
            _trace_buf.append(_trace_event(ev))


def set_max_events(n: Optional[int]) -> int:
    """Cap the bounded in-memory event list at ``n`` (``None`` restores
    the default 2**16); returns the previous cap.  Tests shrink the cap
    to exercise the ``telemetry.events.dropped`` overflow accounting
    without emitting 65k events."""
    global _MAX_EVENTS
    with _lock:
        prev = _MAX_EVENTS
        _MAX_EVENTS = (1 << 16) if n is None else int(n)
    return prev


def _trace_event(ev: dict) -> dict:
    """Map one telemetry event onto the Chrome/Perfetto trace_event
    schema (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU):
    spans become complete ("X") slices, everything else an instant."""
    ts = int(ev.get("ts", 0.0) * 1e6)
    args = {
        k: v for k, v in ev.items() if k not in ("type", "site", "ts", "dur")
    }
    out = {
        "name": ev.get("site", ev.get("type", "event")),
        "cat": ev.get("type", "event"),
        "ts": ts,
        "tid": ev.get("tid", 0),
    }
    if ev.get("type") == "span":
        out["ph"] = "X"
        out["dur"] = int(ev.get("dur", 0.0) * 1e6)
    else:
        out["ph"] = "i"
        out["s"] = "t"
    if args:
        out["args"] = args
    return out


def record_event(etype: str, site: str = "", **fields) -> None:
    """Record one instant event (guard incidents, checkpoint saves,
    compile-cache misses …) of type ``etype``.  No-op while disabled.
    Events emitted inside a :func:`trace_ctx` carry the active request
    ids under ``rid``."""
    if not enabled:
        return
    ev = {"type": etype, "site": site, "ts": clock(), "tid": _tid()}
    rids = _trace_var.get()
    if rids:
        ev["rid"] = list(rids)
    ev.update(fields)
    _emit(ev)


def inc(name: str, n: int = 1) -> None:
    """Add ``n`` to a named counter.  No-op while disabled."""
    if not enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def gauge(name: str, value: float) -> None:
    """Set a named gauge to ``value``.  No-op while disabled.

    While a Perfetto trace is being collected the update also lands on
    the timeline as a counter ("C") event, so live gauges — e.g. the
    exact-vs-wire compression ratio — render as a graph over time."""
    if not enabled:
        return
    with _lock:
        _gauges[name] = value
        if _trace_buf is not None:
            _trace_buf.append(
                {
                    "name": name,
                    "ph": "C",
                    "ts": int(clock() * 1e6),
                    "tid": 0,
                    "args": {"value": value},
                }
            )


def observe(name: str, value: float) -> None:
    """Record one observation into the named streaming histogram
    (:class:`heat_tpu.telemetry.hist.Histogram` — fixed memory,
    log-bucketed, quantiles within the documented ~4.4% relative bound).
    No-op while disabled; the histogram appears in ``snapshot()`` under
    ``hists`` and on ``/metrics`` as a Prometheus histogram."""
    if not enabled:
        return
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = Histogram()
        h.record(value)


def histogram(name: str) -> Optional[Histogram]:
    """The live histogram registered under ``name`` (None if nothing has
    been observed there).  The object is shared — copy() before mutating."""
    with _lock:
        return _hists.get(name)


# --------------------------------------------------------------------- #
# request-scoped trace context                                          #
# --------------------------------------------------------------------- #
@contextlib.contextmanager
def trace_ctx(*request_ids):
    """Tag everything telemetry records in this context with request ids.

    The tentpole of request-scoped observability: ``trace_ctx("rq-17")``
    installs the id in a contextvar, and every span and instant event
    that closes inside the context carries ``rid=[...]`` — on the event
    stream, in the JSONL sink, in the flight-recorder ring, and in the
    Perfetto export (as ``args.rid``), so one slow request can be walked
    from its reply back through the micro-batch's ``serve:*`` span and
    any nested ``comm:*`` spans to the device dispatch that served it.

    Nested contexts ACCUMULATE: a micro-batch context carrying every
    coalesced request's id may sit inside (or around) a single request's
    context, and the union is what lands on the events.  Ids may be
    strings or anything ``str()``-able; an iterable argument is
    flattened one level so ``trace_ctx(ids_list)`` works.

    Cost: one contextvar set/reset per ``with`` block — no predicate on
    the telemetry flag, because the context must already be installed
    when collection is enabled mid-request; the per-site disabled cost
    contract is untouched (sites still guard on ``_core.enabled``).

    Host-side only: inside a jit/shard_map/fuse-traced body the context
    manager runs at *trace* time and tags nothing at run time — spmdlint
    rule SPMD210 flags that misuse.
    """
    flat: List[str] = []
    for rid in request_ids:
        if isinstance(rid, (list, tuple, set, frozenset)):
            flat.extend(str(r) for r in rid)
        else:
            flat.append(str(rid))
    token = _trace_var.set(_trace_var.get() + tuple(flat))
    try:
        yield tuple(flat)
    finally:
        _trace_var.reset(token)


def current_trace() -> Tuple[str, ...]:
    """The active request ids (empty tuple outside any trace_ctx)."""
    return _trace_var.get()


def account_bytes(op: str, mode: str, exact_bytes: int, wire_bytes: int) -> None:
    """Credit one collective's traffic to the exact-vs-wire ledger.

    ``exact_bytes`` is what the payload would cost on the wire as exact
    f32 (the common denominator the bench suite already reports in);
    ``wire_bytes`` what the resolved precision mode actually ships.  The
    per-mode compression ratio is maintained as a live gauge
    ``comm.wire_ratio.<mode>`` — for ``int8_block`` ring traffic it sits
    at ``(BLOCK + 4) / (4 * BLOCK)`` = 0.258x (see heat_tpu.comm).
    No-op while disabled."""
    if not enabled:
        return
    with _lock:
        _counters[f"comm.collectives.{op}"] = (
            _counters.get(f"comm.collectives.{op}", 0) + 1
        )
        for name, val in (
            (f"comm.exact_bytes.{mode}", exact_bytes),
            (f"comm.wire_bytes.{mode}", wire_bytes),
            ("comm.exact_bytes", exact_bytes),
            ("comm.wire_bytes", wire_bytes),
        ):
            _counters[name] = _counters.get(name, 0) + int(val)
        exact = _counters[f"comm.exact_bytes.{mode}"]
        if exact:
            _gauges[f"comm.wire_ratio.{mode}"] = (
                _counters[f"comm.wire_bytes.{mode}"] / exact
            )
        total_exact = _counters["comm.exact_bytes"]
        if total_exact:
            _gauges["comm.wire_ratio"] = _counters["comm.wire_bytes"] / total_exact


# --------------------------------------------------------------------- #
# spans                                                                 #
# --------------------------------------------------------------------- #
class _Span:
    """One ``telemetry.span("site")`` — context manager and decorator.

    Enter/exit are each a single predicate when telemetry is disabled.
    On exit the span lands twice: in the per-site aggregate (count +
    total seconds, what ``snapshot()`` reports) and as one event on the
    stream (what the JSONL sink and the Perfetto exporter consume).
    Exceptions propagate; the span still records, tagged with the
    exception type."""

    __slots__ = ("site", "fields", "_t0")

    def __init__(self, site: str, fields: Optional[dict] = None):
        self.site = site
        self.fields = fields or None
        self._t0 = None

    def __enter__(self):
        if enabled:
            self._t0 = clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is None:
            return False
        t1 = clock()
        dur = t1 - self._t0
        ev = {
            "type": "span",
            "site": self.site,
            "ts": self._t0,
            "dur": dur,
            "tid": _tid(),
        }
        rids = _trace_var.get()
        if rids:
            ev["rid"] = list(rids)
        if self.fields:
            ev.update(self.fields)
        if exc_type is not None:
            ev["error"] = exc_type.__name__
        with _lock:
            agg = _spans.get(self.site)
            if agg is None:
                _spans[self.site] = [1, dur]
            else:
                agg[0] += 1
                agg[1] += dur
            _emit(ev)
        self._t0 = None
        return False

    def __call__(self, fn):
        """Decorator form: ``@telemetry.span("site")``.  The wrapper
        re-checks the flag per call, so decoration at import time with
        telemetry disabled still records once it is enabled."""
        site, fields = self.site, self.fields

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not enabled:
                return fn(*args, **kwargs)
            with _Span(site, fields):
                return fn(*args, **kwargs)

        wrapper.__telemetry_site__ = site
        return wrapper


def span(site: str, **fields) -> _Span:
    """A host-side timing span — use as a ``with`` block or a decorator.

    NOTE: spans are host-side by construction.  Inside a ``jax.jit`` /
    ``shard_map`` / ``ht.fuse``-traced function a span measures *trace*
    time, not run time — spmdlint rule SPMD205 flags that misuse; put
    spans around the eager call site instead.
    """
    return _Span(site, fields or None)


# --------------------------------------------------------------------- #
# reading                                                               #
# --------------------------------------------------------------------- #
def events() -> Tuple[dict, ...]:
    """Snapshot of the recorded event stream (oldest first)."""
    with _lock:
        return tuple(_events)


def snapshot() -> dict:
    """The in-memory export: counters, gauges, and per-site span totals.

    Empty dict while telemetry is disabled — the cheap way for callers
    to branch on "was anything collected"."""
    if not enabled:
        return {}
    with _lock:
        return {
            "counters": dict(_counters),
            "gauges": dict(_gauges),
            "spans": {
                site: {"count": int(c), "total_s": t}
                for site, (c, t) in sorted(_spans.items())
            },
            "hists": {name: _hists[name].state() for name in sorted(_hists)},
            "events": len(_events),
        }


# --------------------------------------------------------------------- #
# JSONL sink                                                            #
# --------------------------------------------------------------------- #
def set_jsonl(path: Optional[str]) -> None:
    """Stream every subsequent event to ``path`` as one JSON object per
    line (``None`` closes the sink)."""
    global _jsonl, _jsonl_path
    with _lock:
        if _jsonl is not None:
            _jsonl.close()
            _jsonl = None
            _jsonl_path = None
        if path is not None:
            _jsonl = open(path, "a", buffering=1)
            _jsonl_path = str(path)


def jsonl_path() -> Optional[str]:
    return _jsonl_path


# --------------------------------------------------------------------- #
# dispatch counter (the _tracing shim's backing store)                  #
# --------------------------------------------------------------------- #
_dispatches = 0


def record_dispatch() -> None:
    """Count one device program launch.  Always on (tier-1 dispatch-count
    gates read it through :mod:`heat_tpu.core._tracing` with telemetry
    disabled); the increment is lock-guarded, so threaded serving does
    not lose launches.  With telemetry enabled the launch also lands on
    the ``dispatches`` registry counter."""
    global _dispatches
    with _lock:
        _dispatches += 1
        if enabled:
            _counters["dispatches"] = _counters.get("dispatches", 0) + 1


def dispatch_count() -> int:
    """Device program launches recorded since the last reset."""
    return _dispatches


def reset_dispatch_count() -> None:
    global _dispatches
    with _lock:
        _dispatches = 0


class _DispatchWindow:
    """Handle yielded by :func:`counting_dispatches`: ``.count`` is the
    number of dispatches since the window opened."""

    __slots__ = ("_base",)

    def __init__(self, base: int):
        self._base = base

    @property
    def count(self) -> int:
        return _dispatches - self._base


@contextlib.contextmanager
def counting_dispatches():
    """Scoped dispatch counting.

    Yields a window whose ``.count`` property reads the launches made
    since entry — a baseline diff, not a global reset, so concurrent
    tests (or nested windows) never leak counter state into each other::

        with counting_dispatches() as d:
            fused_pipeline(x)
        assert d.count == 1
    """
    yield _DispatchWindow(_dispatches)
