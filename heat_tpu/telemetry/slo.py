"""Burn-rate SLO monitoring over streaming latency observations.

An SLO here is the serving form: "``p`` of requests answer under
``target_ms``" — e.g. *99% of predicts under 20 ms*.  The error budget
is ``1 - objective`` (1% of requests may exceed the target), and the
**burn rate** is how fast the budget is being spent:

    ``burn = error_ratio / (1 - objective)``

``burn == 1`` consumes exactly the budget (the SLO holds with nothing to
spare); ``burn == 14.4`` exhausts a 30-day budget in ~2 days — the
classic SRE-workbook page-worthy threshold this module defaults to.

Multi-window discipline: a single window either pages too slowly (long
window) or flaps on noise (short window), so :class:`SloMonitor` tracks
the error ratio over a SHORT and a LONG window simultaneously and
alerts only when **both** burn above the threshold — the short window
proves the problem is happening *now*, the long window proves it is not
a blip.  Each window is a fixed wheel of ``SLOTS`` time buckets
(good/bad counts), so memory is constant regardless of traffic, and
time comes from :func:`heat_tpu.telemetry.clock` — monotonic in
production, the injectable/deterministic sequence in tests, so burn
alerts are replayable under ``enable(deterministic=True)``.

Outputs ride the existing rails: every observation refreshes
``slo.<name>.*`` gauges (burn rates, error ratio, alert flag) through
the one-predicate telemetry guard, and a burn crossing publishes a
structured **incident** through :mod:`heat_tpu.resilience.incidents` —
which means it lands in the incident log, on the event stream, AND
triggers a flight-recorder postmortem dump, exactly like a guard
intervention or a device loss.  The monitor itself is always-on like
the flight recorder: observing with telemetry disabled still tracks the
windows (a latency SLO that only counts when someone is watching is not
an SLO), it just skips the gauges.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from . import _core

__all__ = ["SloMonitor"]

#: time buckets per window wheel — fixed memory per monitor
SLOTS = 60


class _Wheel:
    """One fixed window: ``SLOTS`` buckets of ``window_s / SLOTS``
    seconds each, good/bad counts, stale buckets invalidated lazily by
    an epoch stamp (no timer thread)."""

    __slots__ = ("window_s", "res", "good", "bad", "stamp")

    def __init__(self, window_s: float):
        self.window_s = float(window_s)
        self.res = self.window_s / SLOTS
        self.good = [0] * SLOTS
        self.bad = [0] * SLOTS
        self.stamp: List[int] = [-1] * SLOTS

    def add(self, t: float, ok: bool) -> None:
        epoch = int(t / self.res)
        i = epoch % SLOTS
        if self.stamp[i] != epoch:
            self.stamp[i] = epoch
            self.good[i] = 0
            self.bad[i] = 0
        if ok:
            self.good[i] += 1
        else:
            self.bad[i] += 1

    def totals(self, t: float) -> tuple:
        """(good, bad) over the live window ending at ``t``."""
        lo = int(t / self.res) - SLOTS + 1
        g = b = 0
        for i in range(SLOTS):
            if self.stamp[i] >= lo:
                g += self.good[i]
                b += self.bad[i]
        return g, b


class SloMonitor:
    """One latency SLO: ``objective`` of observations under ``target_ms``
    (see module docs for the burn-rate model).

    Parameters
    ----------
    name : str — gauge/incident namespace (``slo.<name>.*``).
    target_ms : float — the per-observation latency target.
    objective : float in (0, 1) — fraction that must meet the target.
    short_s / long_s : the two burn windows (seconds of telemetry-clock
        time; the deterministic clock makes these event-count windows).
    burn_threshold : float — alert when BOTH windows burn at or above
        this multiple of budget spend.
    min_events : int — no alert before this many observations sit in the
        long window (cold-start guard).
    """

    def __init__(
        self,
        name: str,
        *,
        target_ms: float,
        objective: float = 0.99,
        short_s: float = 60.0,
        long_s: float = 3600.0,
        burn_threshold: float = 14.4,
        min_events: int = 32,
    ):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        if not 0.0 < short_s < long_s:
            raise ValueError(
                f"need 0 < short_s < long_s, got {short_s}/{long_s}"
            )
        self.name = str(name)
        self.target_ms = float(target_ms)
        self.objective = float(objective)
        self.budget = 1.0 - self.objective
        self.burn_threshold = float(burn_threshold)
        self.min_events = int(min_events)
        self._short = _Wheel(short_s)
        self._long = _Wheel(long_s)
        self._lock = threading.Lock()
        self._alerting = False
        self.n_alerts = 0

    # ------------------------------------------------------------------ #
    def observe(self, latency_ms: float) -> None:
        """Record one latency observation and refresh the burn state.
        Host-side only (SPMD210): call it where the latency was measured,
        never inside a traced body."""
        ok = float(latency_ms) <= self.target_ms
        t = _core.clock()
        with self._lock:
            self._short.add(t, ok)
            self._long.add(t, ok)
            state = self._state_locked(t)
            fired = self._maybe_alert_locked(state)
        if _core.enabled:
            pre = f"slo.{self.name}"
            _core.gauge(f"{pre}.burn_rate_short", state["burn_short"])
            _core.gauge(f"{pre}.burn_rate_long", state["burn_long"])
            _core.gauge(f"{pre}.error_ratio_short", state["error_ratio_short"])
            _core.gauge(f"{pre}.alerting", 1.0 if state["alerting"] else 0.0)
            _core.observe(f"{pre}.latency_ms", latency_ms)
        if fired is not None:
            # outside our lock: incidents -> telemetry event + flight dump
            from ..resilience import incidents as _incidents

            _incidents.record(
                "slo-burn",
                f"slo:{self.name}",
                f"objective={self.objective:g}",
                "alert",
                detail=(
                    f"burn short={fired['burn_short']:.2f}x "
                    f"long={fired['burn_long']:.2f}x >= "
                    f"{self.burn_threshold:g}x of the {self.budget:g} error "
                    f"budget (target {self.target_ms:g} ms)"
                ),
            )

    # ------------------------------------------------------------------ #
    def _burn(self, good: int, bad: int) -> float:
        n = good + bad
        if n == 0:
            return 0.0
        return (bad / n) / self.budget

    def _state_locked(self, t: float) -> Dict[str, float]:
        gs, bs = self._short.totals(t)
        gl, bl = self._long.totals(t)
        return {
            "burn_short": self._burn(gs, bs),
            "burn_long": self._burn(gl, bl),
            "error_ratio_short": (bs / (gs + bs)) if (gs + bs) else 0.0,
            "error_ratio_long": (bl / (gl + bl)) if (gl + bl) else 0.0,
            "events_long": gl + bl,
            "alerting": self._alerting,
        }

    def _maybe_alert_locked(self, state: Dict[str, float]) -> Optional[dict]:
        burning = (
            state["events_long"] >= self.min_events
            and state["burn_short"] >= self.burn_threshold
            and state["burn_long"] >= self.burn_threshold
        )
        if burning and not self._alerting:
            self._alerting = True
            state["alerting"] = True
            self.n_alerts += 1
            return dict(state)
        if not burning and self._alerting and state["burn_short"] < self.burn_threshold:
            # burn cleared: re-arm (gauge flips; clearing is not an incident)
            self._alerting = False
            state["alerting"] = False
        return None

    # ------------------------------------------------------------------ #
    @property
    def alerting(self) -> bool:
        return self._alerting

    def state(self) -> Dict[str, float]:
        """Current burn/ratio snapshot (the ``/varz`` form)."""
        t = _core.clock()
        with self._lock:
            s = self._state_locked(t)
        s.update(
            name=self.name,
            target_ms=self.target_ms,
            objective=self.objective,
            burn_threshold=self.burn_threshold,
            n_alerts=self.n_alerts,
        )
        return s
