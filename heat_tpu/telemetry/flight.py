"""The always-on flight recorder: a bounded ring of recent events.

Telemetry proper is opt-in (one predicate per site while disabled,
docs/design.md §13) — which means that when an incident fires in a
process that never enabled collection, there is *no* surrounding context
for the postmortem: no events before the guard tripped, no counters, no
idea what the engine was doing.  The flight recorder closes that gap the
way a real flight recorder does: a small, bounded, lock-guarded ring of
recent events that is **on by default** and cheap enough to stay on —
recording one note costs one module-flag predicate, one clock read, one
dict, and one deque append (the deque's ``maxlen`` does the eviction, so
there is no growth and no compaction pause).  The ring holds the last
``capacity`` (default 256) events and nothing else, so its memory is
bounded by construction; ``tests/test_obs.py`` measures the per-note
cost and pins the bound.

Two feeds:

- with telemetry ENABLED, every event `_core._emit` handles (spans,
  instants, incidents) is mirrored into the ring via the
  ``_core._flight_append`` hook this module registers at import — the
  ring is then simply the tail of the full stream;
- with telemetry DISABLED, instrumented sites record nothing (their
  contract), but *critical* paths — the resilience incident log, the
  serve degrade path — call :func:`note` directly, so the ring always
  holds at least the incident-adjacent history.

Postmortems: whenever :mod:`heat_tpu.resilience.incidents` records an
incident it calls :func:`on_incident`, which snapshots the ring plus the
live counters/gauges/histograms/dispatch count into one deterministic
JSON artifact (canonical key order, stable field set).  With a dump
directory configured (``set_dump_dir`` or ``HEAT_FLIGHT_DIR``) the
artifact is written atomically as ``postmortem-<seq>-<kind>.json``;
otherwise it is retained in memory (:func:`last_dump`).  Under
``telemetry.enable(deterministic=True)`` every timestamp in the
artifact comes from the monotone sequence clock, so two runs of the
same seeded chaos scenario produce **byte-identical** dumps — the
replayability contract the chaos lane asserts.

Like the rest of :mod:`heat_tpu.telemetry`, this module is jax-free and
registers nothing with the compile-cache key context: toggling the
recorder can never retrace a program.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Any, Dict, Optional, Tuple

from . import _core

__all__ = [
    "note",
    "enable",
    "disable",
    "is_enabled",
    "ring",
    "clear",
    "capacity",
    "set_capacity",
    "set_dump_dir",
    "dump_dir",
    "postmortem",
    "dump_postmortem",
    "on_incident",
    "last_dump",
    "last_dump_path",
    "encode",
]

#: THE module flag — :func:`note` is a no-op when False.  On by default:
#: the recorder is the part of observability that must not need turning on.
_active: bool = True

_lock = threading.Lock()
_ring: "collections.deque[dict]" = collections.deque(maxlen=256)
_dump_dir: Optional[str] = None
_last_dump: Optional[dict] = None
_last_dump_path: Optional[str] = None
_n_dumps = 0


def _append(ev: dict) -> None:
    """The `_core._emit` mirror hook: called under _core's lock with the
    already-built event; the deque append is itself thread-safe but the
    flight lock also serializes against ring() snapshots."""
    if not _active:
        return
    with _lock:
        _ring.append(ev)


# register the mirror: every telemetry event also lands on the ring
_core._flight_append = _append


def note(etype: str, site: str = "", **fields) -> None:
    """Record one event on the ring regardless of the telemetry flag.

    This is the always-on entry point for critical paths (incidents,
    degrades): one predicate, one clock read, one dict, one bounded
    append.  Events noted inside a :func:`heat_tpu.telemetry.trace_ctx`
    carry the active request ids under ``rid``."""
    if not _active:
        return
    ev: Dict[str, Any] = {"type": etype, "site": site, "ts": _core.clock()}
    rids = _core.current_trace()
    if rids:
        ev["rid"] = list(rids)
    if fields:
        ev.update(fields)
    with _lock:
        _ring.append(ev)


def enable() -> None:
    global _active
    _active = True


def disable() -> None:
    """Turn the recorder off (for A/B overhead measurements; production
    keeps it on — that is the point of a flight recorder)."""
    global _active
    _active = False


def is_enabled() -> bool:
    return _active


def ring() -> Tuple[dict, ...]:
    """Snapshot of the ring, oldest first."""
    with _lock:
        return tuple(_ring)


def clear() -> None:
    with _lock:
        _ring.clear()


def capacity() -> int:
    return _ring.maxlen or 0


def set_capacity(n: int) -> None:
    """Resize the ring to hold the last ``n`` events (keeps the newest
    tail of the current contents)."""
    global _ring
    n = int(n)
    if n < 1:
        raise ValueError(f"flight ring needs capacity >= 1, got {n}")
    with _lock:
        _ring = collections.deque(_ring, maxlen=n)


def set_dump_dir(path: Optional[str]) -> None:
    """Directory postmortem artifacts are written to (``None`` keeps
    dumps in memory only; ``HEAT_FLIGHT_DIR`` sets this at import)."""
    global _dump_dir
    _dump_dir = None if path is None else str(path)


def dump_dir() -> Optional[str]:
    return _dump_dir


# --------------------------------------------------------------------- #
# postmortem artifacts
# --------------------------------------------------------------------- #
def encode(doc: dict) -> str:
    """THE canonical serialization for postmortem artifacts: sorted keys,
    fixed separators, ``str()`` fallback — byte-stable for any given
    document, which is what makes dump determinism assertable."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":"), default=str)


def postmortem(incident: Optional[Any] = None) -> dict:
    """Build the postmortem document: the ring, the live telemetry
    counters/gauges/histograms (straight off the registry — present even
    while ``snapshot()`` answers ``{}`` because collection is disabled;
    they are then simply empty), the incident log tail, and the
    triggering incident when given."""
    from ..resilience import incidents as _incidents

    with _lock:
        ring_events = list(_ring)
    with _core._lock:
        counters = dict(_core._counters)
        gauges = dict(_core._gauges)
        hists = {name: _core._hists[name].state() for name in sorted(_core._hists)}
    doc: Dict[str, Any] = {
        "schema": 1,
        "kind": "heat_tpu-flight-postmortem",
        "ring": ring_events,
        "ring_capacity": capacity(),
        "counters": counters,
        "gauges": gauges,
        "hists": hists,
        "dispatches": _core.dispatch_count(),
        "telemetry_enabled": _core.is_enabled(),
        "deterministic": _core.is_deterministic(),
        "chaos_seed": os.environ.get("HEAT_CHAOS_SEED"),
        "incident_log": [inc.render() for inc in _incidents.incident_log()],
    }
    if incident is not None:
        doc["incident"] = {
            "seq": incident.seq,
            "kind": incident.kind,
            "site": incident.site,
            "policy": incident.policy,
            "action": incident.action,
            "detail": incident.detail,
            "timestamp": incident.timestamp,
        }
    return doc


def dump_postmortem(incident: Optional[Any] = None) -> Optional[str]:
    """Build and persist one postmortem.  Returns the artifact path, or
    ``None`` when no dump directory is configured (the document is still
    retained — :func:`last_dump`).  Writes are same-dir-temp +
    ``os.replace``, the atomic-save discipline of ``core/io.py``."""
    global _last_dump, _last_dump_path, _n_dumps
    doc = postmortem(incident)
    _last_dump = doc
    _n_dumps += 1
    if _dump_dir is None:
        _last_dump_path = None
        return None
    os.makedirs(_dump_dir, exist_ok=True)
    seq = incident.seq if incident is not None else _n_dumps
    kind = incident.kind if incident is not None else "manual"
    name = f"postmortem-{seq:04d}-{kind}.json"
    path = os.path.join(_dump_dir, name)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(encode(doc))
        fh.write("\n")
    os.replace(tmp, path)
    _last_dump_path = path
    return path


def on_incident(incident, *, already_streamed: bool = False) -> Optional[str]:
    """The hook :mod:`heat_tpu.resilience.incidents` calls for every
    recorded incident: note it on the ring (skipped when telemetry is
    enabled and the incident event already arrived via the `_emit`
    mirror — ``already_streamed``) and dump the postmortem artifact."""
    if not _active:
        return None
    if not already_streamed:
        note(
            "incident",
            site=incident.site,
            kind=incident.kind,
            policy=incident.policy,
            action=incident.action,
            detail=incident.detail,
            seq=incident.seq,
        )
    return dump_postmortem(incident)


def last_dump() -> Optional[dict]:
    """The most recent postmortem document (None before any dump)."""
    return _last_dump


def last_dump_path() -> Optional[str]:
    return _last_dump_path
