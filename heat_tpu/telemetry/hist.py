"""Fixed-memory streaming histograms with log-spaced buckets.

The serving stack needs percentiles over unbounded observation streams
— per-request latencies, batch occupancies, queue depths — without
retaining every sample (PR 10's loadgen kept a Python list per run and
called ``np.percentile`` on it, which is O(n) memory and undefined on an
empty run).  A :class:`Histogram` is the replacement: observations land
in logarithmically spaced buckets, so the whole structure is a bounded
dict of integer counts no matter how many values stream through, any
quantile is recoverable within a *documented multiplicative error
bound*, and two histograms merge by adding counts — an associative,
commutative operation, so per-thread (or per-replica) histograms combine
into the global one in any order.

Bucket scheme (``log8``)
------------------------
``BUCKETS_PER_OCTAVE = 8`` sub-buckets per power of two: a positive
value ``v`` lands in bucket ``k = floor(8 * log2(v))``, which covers the
half-open interval ``[2**(k/8), 2**((k+1)/8))`` — a growth factor of
``2**(1/8) ≈ 1.0905`` per bucket.  Quantiles report the bucket's
*geometric midpoint* ``2**((k + 0.5)/8)``, so the estimate is off from
the true sample by at most a factor of ``2**(1/16)`` in either
direction: the relative error bound is

    ``REL_ERROR = 2**(1/16) - 1 ≈ 4.4%``

independent of the value's magnitude (that is the point of log spacing —
a 2 ms p50 and a 900 ms p99 carry the same relative precision).  Values
``<= 0`` (and exact zeros, common for "no wait" latencies) are counted
in a dedicated zero bucket whose representative is ``0.0``; bucket
indices clamp to ``[K_MIN, K_MAX]`` (≈ 2.3e-10 .. 4.3e9 at 8/octave), so
memory is bounded by the fixed index range even for adversarial inputs.

Determinism: bucketing a value is a pure function of the value (no
clocks, no randomness), iteration orders are sorted, and ``state()``
emits a canonically ordered dict — two runs observing the same stream
produce byte-identical serialized states, which is what lets the flight
recorder's postmortem dumps embed histograms and stay replayable.

Kept free of numpy and jax so :mod:`heat_tpu.telemetry._core` (jax-free
by contract) can host a registry of these.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Histogram"]

#: sub-buckets per power of two (the "log8" scheme)
_BPO = 8
#: clamp range for bucket indices: 2**(-256/8) = 2**-32 .. 2**32
_K_MIN = -256
_K_MAX = 256


class Histogram:
    """One fixed-memory log-bucketed histogram (see module docs).

    ``record`` / ``quantile`` / ``merge`` are **not** internally locked —
    the telemetry registry serializes access under its own lock, and a
    thread-private histogram needs none.  Merging is associative and
    commutative over the bucket counts, so sharded recording composes.
    """

    #: buckets per octave of the log2 scheme — merge requires equality
    BUCKETS_PER_OCTAVE = _BPO
    #: documented multiplicative quantile error: the geometric-midpoint
    #: estimate is within a factor 2**(1/(2*BPO)) of the true sample
    REL_ERROR = 2.0 ** (1.0 / (2 * _BPO)) - 1.0

    __slots__ = ("counts", "zero", "count", "sum", "min", "max")

    def __init__(self):
        self.counts: Dict[int, int] = {}
        self.zero = 0  # observations <= 0 (representative value 0.0)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    # ------------------------------------------------------------------ #
    # recording
    # ------------------------------------------------------------------ #
    @staticmethod
    def bucket_index(value: float) -> int:
        """The bucket of a positive ``value``: ``floor(8*log2(v))``,
        clamped to the fixed index range."""
        k = math.floor(_BPO * math.log2(value))
        return _K_MIN if k < _K_MIN else (_K_MAX if k > _K_MAX else k)

    @staticmethod
    def bucket_bounds(k: int) -> Tuple[float, float]:
        """``[lo, hi)`` interval of bucket ``k``."""
        return 2.0 ** (k / _BPO), 2.0 ** ((k + 1) / _BPO)

    @staticmethod
    def bucket_mid(k: int) -> float:
        """Geometric midpoint of bucket ``k`` — the quantile
        representative, within ``REL_ERROR`` of any member."""
        return 2.0 ** ((k + 0.5) / _BPO)

    def record(self, value: float) -> None:
        """Observe one value."""
        value = float(value)
        if value != value:  # NaN: count it (the stream saw it) as zero-
            # bucket poison is wrong; drop into min/max-neutral zero slot
            self.zero += 1
            self.count += 1
            return
        self.count += 1
        self.sum += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value
        if value <= 0.0:
            self.zero += 1
            return
        k = self.bucket_index(value)
        self.counts[k] = self.counts.get(k, 0) + 1

    # ------------------------------------------------------------------ #
    # merging
    # ------------------------------------------------------------------ #
    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other``'s counts into ``self`` (in place; returns self).

        Associative and commutative over bucket counts and extrema;
        ``sum`` is a float accumulation, exact whenever the observed
        values are (e.g. dyadic rationals), otherwise within rounding.
        """
        if other.BUCKETS_PER_OCTAVE != self.BUCKETS_PER_OCTAVE:
            raise ValueError("cannot merge histograms of different schemes")
        for k, c in other.counts.items():
            self.counts[k] = self.counts.get(k, 0) + c
        self.zero += other.zero
        self.count += other.count
        self.sum += other.sum
        if other.min is not None and (self.min is None or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None or other.max > self.max):
            self.max = other.max
        return self

    def copy(self) -> "Histogram":
        h = Histogram()
        h.counts = dict(self.counts)
        h.zero, h.count, h.sum = self.zero, self.count, self.sum
        h.min, h.max = self.min, self.max
        return h

    # ------------------------------------------------------------------ #
    # quantiles
    # ------------------------------------------------------------------ #
    def quantile(self, q: float) -> float:
        """The ``q``-quantile (``0 <= q <= 1``) as the geometric midpoint
        of the bucket holding the nearest-rank sample — within
        ``REL_ERROR`` of that sample.  An empty histogram answers
        ``0.0`` (the guard the serving percentiles rely on)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile needs 0 <= q <= 1, got {q}")
        if self.count == 0:
            return 0.0
        # nearest-rank (0-indexed): the ceil(q*n)-th smallest observation
        rank = max(0, min(self.count - 1, math.ceil(q * self.count) - 1))
        if rank < self.zero:
            return 0.0
        cum = self.zero
        for k in sorted(self.counts):
            cum += self.counts[k]
            if rank < cum:
                return self.bucket_mid(k)
        return self.bucket_mid(max(self.counts))  # pragma: no cover

    def percentile(self, p: float) -> float:
        """``quantile(p / 100)`` — the numpy-flavoured spelling."""
        return self.quantile(p / 100.0)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    # ------------------------------------------------------------------ #
    # export
    # ------------------------------------------------------------------ #
    def state(self) -> dict:
        """Canonical serializable state: sorted buckets, stable keys —
        the form the flight recorder embeds in postmortem dumps and
        ``telemetry.snapshot()`` reports under ``hists``."""
        return {
            "scheme": f"log{_BPO}",
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "zero": self.zero,
            "buckets": {str(k): self.counts[k] for k in sorted(self.counts)},
            "p50": self.quantile(0.5),
            "p99": self.quantile(0.99),
        }

    def prom_buckets(self) -> List[Tuple[float, int]]:
        """Cumulative ``(le, count)`` pairs for the Prometheus histogram
        exposition: one boundary per occupied bucket's upper edge (the
        zero bucket maps to ``le=0``), plus the implicit ``+Inf`` total
        the exporter appends."""
        out: List[Tuple[float, int]] = []
        cum = 0
        if self.zero:
            cum += self.zero
            out.append((0.0, cum))
        for k in sorted(self.counts):
            cum += self.counts[k]
            out.append((self.bucket_bounds(k)[1], cum))
        return out

    @classmethod
    def from_state(cls, state: dict) -> "Histogram":
        """Rebuild a histogram from a ``state()`` dict — the inverse of
        :meth:`state`, used to merge histograms shipped across a process
        boundary (replica RPC frames carry states, never objects).

        ``from_state(h.state()).state() == h.state()`` holds exactly:
        everything a state carries round-trips, so merging rebuilt
        replica histograms is byte-for-byte the same as merging the
        originals."""
        scheme = state.get("scheme")
        if scheme != f"log{_BPO}":
            raise ValueError(f"cannot rebuild scheme {scheme!r} (want 'log{_BPO}')")
        h = cls()
        h.count = int(state["count"])
        h.sum = float(state["sum"])
        h.min = None if state["min"] is None else float(state["min"])
        h.max = None if state["max"] is None else float(state["max"])
        h.zero = int(state["zero"])
        h.counts = {int(k): int(c) for k, c in state["buckets"].items()}
        return h

    @classmethod
    def of(cls, values: Iterable[float]) -> "Histogram":
        """Build a histogram from an iterable (test/report convenience)."""
        h = cls()
        for v in values:
            h.record(v)
        return h

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"Histogram(count={self.count}, p50={self.quantile(0.5):.4g}, "
            f"p99={self.quantile(0.99):.4g})"
        )
