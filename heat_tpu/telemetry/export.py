"""Chrome/Perfetto trace export for the telemetry event stream.

``start_trace(path)`` begins buffering every span, event, and gauge
update as a Chrome ``trace_event`` record; ``stop_trace()`` writes the
buffered timeline as trace-event JSON (``{"traceEvents": [...]}``) that
chrome://tracing and https://ui.perfetto.dev load directly.  Host spans
carry ``ph="X"`` (complete slices), instant events ``ph="i"``, gauges
``ph="C"`` (counter tracks) — so one timeline shows the Python
orchestration layer: compile misses, fuse replays, reshards, ring
collectives, checkpoint ticks.

Pass ``device_trace_dir=...`` to also run :func:`jax.profiler.trace`
for the same window: jax writes its own Perfetto file with the XLA
device timeline under that directory, and loading both into the
Perfetto UI lines Python orchestration up over device execution.  The
jax import happens lazily and failures degrade to host-only capture —
this module stays importable without jax.

``HEAT_TELEMETRY=1`` in the environment enables collection at import
time; ``HEAT_TELEMETRY_JSONL=<path>`` opens the JSONL sink and
``HEAT_TELEMETRY_TRACE=<path>`` starts a trace that is flushed at
process exit — the hooks the CI telemetry lane
(scripts/run_test_matrix.sh) uses to archive artifacts from an
otherwise unmodified test run.  ``HEAT_FLIGHT_DIR=<dir>`` points the
always-on flight recorder's postmortem dumps at a directory (the
recorder itself needs no flag — it is on by default).
"""

from __future__ import annotations

import atexit
import json
import os
import warnings
from typing import Optional

from . import _core

__all__ = ["start_trace", "stop_trace", "trace_active"]

_trace_path: Optional[str] = None
_device_tracing = False


def trace_active() -> bool:
    return _trace_path is not None


def start_trace(path: str, device_trace_dir: Optional[str] = None) -> None:
    """Begin collecting a Chrome/Perfetto trace into ``path``.

    Implicitly enables telemetry (a trace of nothing is useless); the
    enabled flag stays on after ``stop_trace`` — call
    :func:`heat_tpu.telemetry.disable` to turn collection back off.
    """
    global _trace_path, _device_tracing
    if _trace_path is not None:
        raise RuntimeError(f"a trace is already being collected into {_trace_path}")
    if not _core.enabled:
        _core.enable()
    _trace_path = str(path)
    with _core._lock:
        _core._trace_buf = []
    if device_trace_dir is not None:
        try:
            import jax

            jax.profiler.start_trace(str(device_trace_dir))
            _device_tracing = True
        except Exception as e:  # pragma: no cover - depends on jax build
            warnings.warn(f"device trace capture unavailable ({e}); host-only trace")
            _device_tracing = False


def stop_trace() -> Optional[str]:
    """Stop collecting and write the trace-event JSON; returns the path
    (``None`` when no trace was active)."""
    global _trace_path, _device_tracing
    if _device_tracing:
        _device_tracing = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover
            warnings.warn(f"device trace stop failed ({e})")
    if _trace_path is None:
        return None
    path = _trace_path
    _trace_path = None
    with _core._lock:
        buf, _core._trace_buf = _core._trace_buf, None
    doc = {
        "traceEvents": [dict(ev, pid=os.getpid()) for ev in (buf or [])],
        "displayTimeUnit": "ms",
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)  # atomic like every other heat_tpu save
    return path


def _env_autostart() -> None:
    """The CI-lane hooks (see module docstring)."""
    if os.environ.get("HEAT_TELEMETRY") == "1":
        _core.enable()
    jsonl = os.environ.get("HEAT_TELEMETRY_JSONL")
    if jsonl:
        _core.enable()
        _core.set_jsonl(jsonl)
    trace = os.environ.get("HEAT_TELEMETRY_TRACE")
    if trace:
        start_trace(trace)
        atexit.register(stop_trace)
    flight_dir = os.environ.get("HEAT_FLIGHT_DIR")
    if flight_dir:
        from . import flight

        flight.set_dump_dir(flight_dir)


_env_autostart()
