"""``heat_tpu.telemetry`` — unified runtime observability.

One registry for everything the runtime can tell you about itself:

- **spans** — ``telemetry.span("site")`` (context manager + decorator),
  emitted automatically by the hot paths: ``jitted()`` replay vs
  first-compile (miss events split trace+lower vs compile time),
  ``ht.fuse`` program build/replay, communication-layer reshards and
  collectives, checkpoint saves, estimator ``fit``/``predict``;
- **counters & gauges** — device dispatches, compile-cache hits /
  misses / size, collective invocations with exact-vs-wire byte
  accounting per precision mode (the compression ratio is the live
  gauge ``comm.wire_ratio.<mode>``), guard incidents, checkpoint
  save/load/resume events;
- **exporters** — ``snapshot()`` (in-memory dict), a JSONL sink
  (``set_jsonl(path)``), and Chrome/Perfetto trace-event JSON
  (``start_trace(path)`` / ``stop_trace()``, optionally interleaved
  with ``jax.profiler`` device capture);
- **request tracing** — ``trace_ctx("req-1")`` tags every span and
  event emitted inside the context with the active request ids
  (``rid``), which is how a serve request is walked from the loadgen
  reply through the ``serve:batch`` span into the Perfetto timeline
  and the flight-recorder postmortem;
- **streaming histograms & SLOs** — ``observe(name, value)`` feeds a
  fixed-memory log-bucketed :class:`~heat_tpu.telemetry.hist.Histogram`
  (quantiles within a documented ~4.4% relative bound, mergeable across
  threads); :class:`~heat_tpu.telemetry.slo.SloMonitor` turns a latency
  stream into multi-window burn-rate gauges and a structured incident
  when the error budget burns;
- **flight recorder** — :mod:`heat_tpu.telemetry.flight`, an always-on
  bounded ring of recent events that dumps a deterministic postmortem
  JSON whenever an incident records;
- **live endpoint** — :class:`~heat_tpu.telemetry.httpz.MetricsServer`,
  a loopback-only ``/metrics`` (Prometheus text) + ``/healthz`` +
  ``/varz`` listener (``ServeEngine.start_metrics_server``).

Disabled (the default) it costs one predicate per instrumented site and
contributes nothing to compile-cache keys; ``enable(deterministic=True)``
swaps timestamps for a monotone sequence so tests can assert on event
streams bitwise.  ``HEAT_TELEMETRY=1`` enables collection from the
environment.  See docs/design.md ("Observability") and the tutorial
walkthrough for a worked example.
"""

from ._core import (
    account_bytes,
    clock,
    counting_dispatches,
    disable,
    dispatch_count,
    enable,
    events,
    gauge,
    inc,
    is_deterministic,
    is_enabled,
    current_trace,
    histogram,
    jsonl_path,
    observe,
    record_dispatch,
    record_event,
    reset,
    reset_dispatch_count,
    set_clock,
    set_jsonl,
    set_max_events,
    snapshot,
    span,
    trace_ctx,
)
from .export import start_trace, stop_trace, trace_active
from .hist import Histogram
from .slo import SloMonitor
from . import flight
from .httpz import MetricsServer, prometheus_text

__all__ = [
    "enable",
    "disable",
    "is_enabled",
    "is_deterministic",
    "enabled",
    "clock",
    "set_clock",
    "span",
    "inc",
    "gauge",
    "record_event",
    "account_bytes",
    "events",
    "snapshot",
    "reset",
    "set_jsonl",
    "jsonl_path",
    "record_dispatch",
    "dispatch_count",
    "reset_dispatch_count",
    "counting_dispatches",
    "start_trace",
    "stop_trace",
    "trace_active",
    "trace_ctx",
    "current_trace",
    "observe",
    "histogram",
    "set_max_events",
    "Histogram",
    "SloMonitor",
    "flight",
    "MetricsServer",
    "prometheus_text",
]


def __getattr__(name):
    # `telemetry.enabled` must track the live flag; a from-import at
    # package init would freeze the boolean at its import-time value
    if name == "enabled":
        from . import _core

        return _core.enabled
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
