"""spmdlint: static SPMD-correctness analysis for the heat_tpu tree.

Importable API (the CLI lives in :mod:`heat_tpu.analysis.cli`, exposed as
``scripts/spmdlint.py``)::

    from heat_tpu.analysis import analyze_file, analyze_paths, all_rules

Deliberately jax-free: the analyzer runs on a bare Python install so the
CI gate never depends on an accelerator runtime.
"""

from .baseline import load_baseline, partition, write_baseline
from .core import FileContext, analyze_file, analyze_paths, iter_py_files
from .rules import RULES, Finding, Rule, all_rules

# importing checkers registers every rule in RULES
from . import checkers  # noqa: E402,F401

__all__ = [
    "FileContext",
    "Finding",
    "RULES",
    "Rule",
    "all_rules",
    "analyze_file",
    "analyze_paths",
    "iter_py_files",
    "load_baseline",
    "partition",
    "write_baseline",
]
