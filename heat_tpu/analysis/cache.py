"""Findings cache: skip re-analyzing files that have not changed.

One JSON entry per analyzed file under ``.spmdlint-cache/`` (repo root by
default), keyed on ``(absolute path, mtime_ns, size)`` plus everything
that changes what a run would produce: the dynamic flag, the requested
rule subset, the set of registered file-scope rules, and a format
version.  A stale key is simply recomputed — the cache never needs
invalidation tooling, deleting the directory is always safe.

Only FILE-scope findings are cached.  Program-scope (splitflow) rules
are interprocedural — editing one file can change findings in another —
so :func:`~heat_tpu.analysis.core.analyze_contexts` always recomputes
them; they cost one pass over already-parsed trees.

``hits``/``misses`` counters feed the lint lane's cold/warm wall-time
report.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import List, Optional, Sequence

from .rules import RULES, Finding

__all__ = ["DEFAULT_CACHE_DIR", "FindingsCache"]

DEFAULT_CACHE_DIR = ".spmdlint-cache"

_FORMAT_VERSION = 1


class FindingsCache:
    def __init__(self, cache_dir: str = DEFAULT_CACHE_DIR):
        self.cache_dir = cache_dir
        self.hits = 0
        self.misses = 0

    # ------------------------------------------------------------------ #
    def _entry_path(self, ctx) -> str:
        digest = hashlib.sha256(
            os.path.abspath(ctx.path).encode("utf-8")
        ).hexdigest()[:24]
        return os.path.join(self.cache_dir, f"{digest}.json")

    @staticmethod
    def _key(ctx, dynamic: bool, rules: Optional[Sequence[str]]) -> Optional[list]:
        try:
            st = os.stat(ctx.path)
        except OSError:
            return None
        file_rules = sorted(r.id for r in RULES.values() if r.scope == "file")
        return [
            _FORMAT_VERSION,
            os.path.abspath(ctx.path),
            st.st_mtime_ns,
            st.st_size,
            bool(dynamic),
            sorted(rules) if rules is not None else None,
            file_rules,
        ]

    # ------------------------------------------------------------------ #
    def get(self, ctx, dynamic: bool, rules: Optional[Sequence[str]]
            ) -> Optional[List[Finding]]:
        key = self._key(ctx, dynamic, rules)
        if key is None:
            self.misses += 1
            return None
        try:
            with open(self._entry_path(ctx), "r", encoding="utf-8") as f:
                entry = json.load(f)
        except (OSError, ValueError):  # spmdlint: disable=SPMD207 -- unreadable or corrupt cache entries ARE misses; analysis recomputes and overwrites them
            self.misses += 1
            return None
        if entry.get("key") != key:
            self.misses += 1
            return None
        self.hits += 1
        return [Finding.from_dict(d) for d in entry.get("findings", [])]

    def put(self, ctx, dynamic: bool, rules: Optional[Sequence[str]],
            findings: Sequence[Finding]) -> None:
        key = self._key(ctx, dynamic, rules)
        if key is None:
            return
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            tmp = self._entry_path(ctx) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {"key": key, "findings": [x.to_dict() for x in findings]},
                    f,
                )
            os.replace(tmp, self._entry_path(ctx))
        except OSError:  # spmdlint: disable=SPMD207 -- a cache that cannot write is just a cache that always misses; linting must not fail over it
            pass

    def stats(self) -> str:
        return f"{self.hits} hit, {self.misses} miss"
