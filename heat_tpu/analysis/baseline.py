"""Committed-baseline handling: the gate is *incremental*.

The baseline is a JSON file of finding fingerprints (rule + path +
line-insensitive context).  A lint run fails only on findings NOT in the
baseline, so adopting a new rule never blocks unrelated PRs — you commit
the baseline with the rule and burn it down separately.  Stale entries
(baselined findings that no longer fire) are reported so the file shrinks
monotonically; ``--update-baseline`` rewrites it from the current tree.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

from .rules import Finding

__all__ = ["load_baseline", "write_baseline", "partition"]

DEFAULT_BASELINE = "spmdlint-baseline.json"


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry metadata.  Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [
        {
            "fingerprint": f.fingerprint(),
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
        }
        for f in sorted(findings, key=lambda f: f.fingerprint())
    ]
    # dedupe while keeping order (two hits of one rule on one normalized
    # line share a fingerprint on purpose)
    seen = set()
    unique = []
    for e in entries:
        if e["fingerprint"] not in seen:
            seen.add(e["fingerprint"])
            unique.append(e)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": unique}, f, indent=2, sort_keys=True)
        f.write("\n")


def partition(
    findings: Sequence[Finding], baseline: Dict[str, dict]
) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Split into (new, baselined, stale-fingerprints)."""
    new: List[Finding] = []
    old: List[Finding] = []
    hit = set()
    for f in findings:
        fp = f.fingerprint()
        if fp in baseline:
            old.append(f)
            hit.add(fp)
        else:
            new.append(f)
    stale = sorted(fp for fp in baseline if fp not in hit)
    return new, old, stale
