"""Rule registry and the Finding record.

A rule is a function ``check(ctx) -> Iterable[Finding]`` registered under a
stable id.  Ids are grouped by family so suppressions and docs stay legible:

=========  ===============================================================
SPMD001    inline suppression of a reason-required rule needs a reason
SPMD101    ppermute permutations must be valid (partial) bijections
SPMD102    collective axis names must match the enclosing shard_map mesh
SPMD201    trace purity: no host effects inside jit/shard_map/pallas fns
SPMD202    no host-sync coercions (float()/.item()/np.asarray) on traced values
SPMD203    quantized collectives must not carry integer/exact-dtype payloads
SPMD204    quantized collectives in guard-disabled regions need suppression
SPMD205    host timing (time.*, telemetry.span) inside traced functions
SPMD206    monolithic split→split resplit inside a loop body
SPMD207    silent broad except around dispatch/collective/io sites
SPMD208    unbucketed dynamic batch shape entering a compiled program in a loop
SPMD209    serialized ring body: ppermute result consumed in the same round
SPMD210    request-scoped observability inside traced functions
SPMD211    retry loop without a deadline around a compiled/guarded call
SPMD212    blocking host read inside a loop that dispatches compiled programs
SPMD301    Pallas BlockSpec tiles must respect the hardware tile grid
SPMD302    pallas_call grids must be static (no traced values)
SPMD401    jitted() cache keys: hashable, identity-stable parts only
SPMD501    implicit resplit: binary operand splits disagree (hidden wire)
SPMD502    redundant resplit chain: intermediate layout is never used
SPMD503    split axis statically out of range (guaranteed runtime error)
SPMD504    layout collective on a value inferred replicated (no-op)
SPMD505    hand-placed resplit inside an autoshard-wrapped function
=========  ===============================================================

SPMD501–505 are **program-scope** rules (``Rule.scope == "program"``):
they run once over the whole analyzed tree on the splitflow
interprocedural sharding-dataflow engine
(:mod:`heat_tpu.analysis.splitflow`) instead of per file.

The catalog with fix guidance lives in docs/lint.md; each checker's
docstring is the source of truth for its exact conditions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List

__all__ = [
    "Finding", "REASON_REQUIRED", "Rule", "RULES", "rule", "all_rules",
]

#: rule ids whose inline suppression must carry a ``-- reason`` tail
#: (``# spmdlint: disable=SPMD204 -- bench harness, guards off by design``):
#: both silence checks that exist to make a risky pattern *deliberate*, so
#: a bare suppression defeats the purpose.  Enforced by SPMD001.
REASON_REQUIRED = frozenset({"SPMD204", "SPMD207"})


@dataclass(frozen=True)
class Finding:
    """One analyzer hit: ``path:line  rule  message`` plus a fix hint."""

    rule: str
    path: str  # repo/package-relative where possible
    line: int
    message: str
    hint: str = ""
    #: stable identity for the baseline: deliberately line-insensitive
    #: (enclosing def + normalized source snippet), so findings survive
    #: unrelated edits above them
    context: str = ""

    def fingerprint(self) -> str:
        return f"{self.rule}::{self.path}::{self.context}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message, "hint": self.hint,
            "context": self.context, "fingerprint": self.fingerprint(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(
            rule=d["rule"], path=d["path"], line=d["line"],
            message=d["message"], hint=d.get("hint", ""),
            context=d.get("context", ""),
        )

    def render(self) -> str:
        out = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclass
class Rule:
    id: str
    title: str
    check: Callable  # (FileContext) -> Iterable[Finding]  [file scope]
    #: rules that execute snippets of the analyzed source (perm builders)
    #: are skipped under --no-dynamic
    dynamic: bool = False
    #: "file" rules get one FileContext per call; "program" rules run ONCE
    #: per analysis over the splitflow Program (every FileContext plus the
    #: interprocedural sharding-dataflow results)
    scope: str = "file"


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, title: str, dynamic: bool = False, scope: str = "file"):
    """Register a checker under ``rule_id``."""

    def deco(fn):
        RULES[rule_id] = Rule(rule_id, title, fn, dynamic=dynamic, scope=scope)
        return fn

    return deco


def all_rules() -> List[Rule]:
    return [RULES[k] for k in sorted(RULES)]
