"""Per-rule checkers for the SPMD-correctness analyzer.

Each checker walks one :class:`~heat_tpu.analysis.core.FileContext` and
yields findings.  Rule SPMD101 is *hybrid* static/dynamic: permutation
builders are fixed at trace time (the whole point — ppermute perms are
compile-time metadata), so the checker extracts the builder expression and
EVALUATES it for every mesh size 1..8, checking that each result is a
valid partial bijection.  The evaluation sandbox executes only
module-level ``def`` source from the analyzed file plus arithmetic
builtins — never imports, never jax.
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .core import _FUNC_TYPES, FileContext
from .rules import Finding, rule

__all__ = [
    "MESH_SIZES",
    "check_partial_bijection",
    "verify_ring_schedule",
    "verify_zigzag_builders",
]

#: every perm builder is evaluated for these mesh sizes — 1 (degenerate),
#: powers of two (real TPU slices), and the awkward primes the test
#: matrix also sweeps
MESH_SIZES = tuple(range(1, 9))

_SIZE_NAMES = {"size", "p", "n", "world_size", "num_devices", "mesh_size"}

_SAFE_BUILTINS = {
    k: getattr(builtins, k)
    for k in (
        "range", "len", "min", "max", "abs", "enumerate", "zip", "sum",
        "list", "tuple", "sorted", "reversed", "int", "divmod",
    )
}


# --------------------------------------------------------------------- #
# permutation ground truth (shared with the runtime property tests)      #
# --------------------------------------------------------------------- #
def check_partial_bijection(perm, size: int) -> Optional[str]:
    """Validate one ppermute permutation for mesh ``size``: pairs of ints
    in range, no duplicated source, no duplicated destination (partial
    perms are legal — absent destinations receive zeros).  Returns an
    error string or None."""
    try:
        pairs = [(int(s), int(d)) for s, d in perm]
    except (TypeError, ValueError):
        return f"not a sequence of (src, dst) pairs: {perm!r}"
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    bad = [x for x in srcs + dsts if not 0 <= x < size]
    if bad:
        return f"index {bad[0]} out of range for mesh size {size}"
    if len(set(srcs)) != len(srcs):
        dup = sorted(s for s in set(srcs) if srcs.count(s) > 1)
        return f"duplicate source(s) {dup} at mesh size {size}"
    if len(set(dsts)) != len(dsts):
        dup = sorted(d for d in set(dsts) if dsts.count(d) > 1)
        return f"duplicate destination(s) {dup} at mesh size {size} (collision: two shards write one slot)"
    return None


def verify_ring_schedule(ring_source, sizes: Sequence[int] = MESH_SIZES) -> Optional[str]:
    """Check ``ring_source(position, round, size)`` against the +1 ring
    rotation it documents: simulate ``[(i, (i+1) % size)]`` applied
    ``round`` times and compare origins."""
    for s in sizes:
        origins = list(range(s))
        for r in range(s):
            for pos in range(s):
                if ring_source(pos, r, s) != origins[pos]:
                    return (
                        f"ring_source({pos}, {r}, {s}) = {ring_source(pos, r, s)}"
                        f" but the +1 rotation delivers block {origins[pos]}"
                    )
            origins = [origins[(pos - 1) % s] for pos in range(s)]
    return None


def verify_zigzag_builders(
    zigzag_perms=None,
    zigzag_inverse_perms=None,
    zigzag_chunk_owner=None,
    sizes: Sequence[int] = MESH_SIZES,
) -> Optional[str]:
    """Full-bijection + round-trip checks for the zig-zag resplit
    schedules.  Each stream perm must be a TOTAL bijection (every device
    sends and receives exactly once), and forward-then-inverse must
    restore the contiguous chunk layout."""
    for s in sizes:
        streams = {}
        if zigzag_perms is not None:
            streams["zigzag_perms"] = zigzag_perms(s)
        if zigzag_inverse_perms is not None:
            streams["zigzag_inverse_perms"] = zigzag_inverse_perms(s)
        for name, perms in streams.items():
            for k, perm in enumerate(perms):
                err = check_partial_bijection(perm, s)
                if err is None and len({d for _, d in perm}) != s:
                    err = f"stream does not cover every device at size {s}"
                if err:
                    return f"{name}({s}) stream {k}: {err}"
        if zigzag_perms is not None and zigzag_chunk_owner is not None:
            fwd = zigzag_perms(s)
            for i in range(s):
                for k in (0, 1):
                    dst = dict(fwd[k])[i]
                    want = zigzag_chunk_owner(2 * i + k, s)
                    if dst != want:
                        return (
                            f"zigzag_perms({s}) sends chunk {2 * i + k} to "
                            f"{dst}, zigzag_chunk_owner says {want}"
                        )
        if zigzag_perms is not None and zigzag_inverse_perms is not None:
            # forward then inverse must restore the contiguous layout:
            # chunk c starts at device c // 2, comes home to c // 2
            fwd, inv = zigzag_perms(s), zigzag_inverse_perms(s)
            for c in range(2 * s):
                home = dict(fwd[c % 2])[c // 2]
                # at its zig-zag home the chunk is the low half iff c < s;
                # low halves ride the even-chunk stream of the inverse
                stream = inv[0] if (c < s) == (home % 2 == 0) else inv[1]
                back = dict(stream)[home]
                if back != c // 2:
                    return (
                        f"zig-zag round trip broken at size {s}: chunk {c} "
                        f"returns to device {back}, expected {c // 2}"
                    )
    return None


# --------------------------------------------------------------------- #
# sandboxed evaluation of perm expressions                               #
# --------------------------------------------------------------------- #
class _Unresolvable(Exception):
    pass


def _module_def_env(ctx: FileContext) -> Dict[str, object]:
    """Exec every module-level ``def`` from source into one shared env.
    Definition never runs the body, so jax-using helpers exec fine and
    only fail (NameError) if a perm expression actually calls them —
    which we catch and treat as unverifiable."""
    env: Dict[str, object] = {"__builtins__": _SAFE_BUILTINS}
    for st in ctx.tree.body:
        if isinstance(st, ast.FunctionDef):
            src = ast.get_source_segment(ctx.source, st)
            if src is None:
                continue
            try:
                exec(compile(ast.parse(src), f"<{ctx.relpath}>", "exec"), env)
            except Exception:
                continue
    return env


def _free_names(expr: ast.AST) -> List[str]:
    bound = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.comprehension):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    bound.add(t.id)
        elif isinstance(node, ast.Lambda):
            bound.update(a.arg for a in node.args.args)
    out = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id not in bound and node.id not in _SAFE_BUILTINS:
                out.append(node.id)
    return out


def _eval_expr(ctx: FileContext, expr: ast.AST, at: ast.AST, size: int,
               env: Dict[str, object], depth: int = 0):
    """Evaluate ``expr`` with mesh-size variables bound to ``size``.
    Free names resolve through (in order): the module-def env, nearest
    assignment (constants, ``*.size`` attributes, recursively evaluable
    expressions), parameter defaults, and the size-name convention."""
    if depth > 6:
        raise _Unresolvable("resolution too deep")
    local: Dict[str, object] = {}
    params = {}
    for fn in ctx.enclosing_functions(at):
        if isinstance(fn, ast.Lambda):
            args = fn.args
        else:
            args = fn.args
        names = [a.arg for a in args.args + args.kwonlyargs]
        defaults = list(args.defaults)
        for name, default in zip(reversed(args.args), reversed(defaults)):
            params.setdefault(name.arg, default)
        for name in names:
            params.setdefault(name, None)
    for name in _free_names(expr):
        if name in env or name in local:
            continue
        rec = ctx.lookup(name, at)
        if rec is not None and rec[0] == "expr":
            val = rec[1]
            if isinstance(val, ast.Constant):
                local[name] = val.value
                continue
            if isinstance(val, ast.Attribute) and val.attr == "size":
                local[name] = size
                continue
            try:
                local[name] = _eval_expr(ctx, val, at, size, env, depth + 1)
                continue
            except _Unresolvable:
                pass
        if name in params:
            default = params[name]
            if name in _SIZE_NAMES:
                local[name] = size
                continue
            if isinstance(default, ast.Constant) and default.value is not None:
                local[name] = default.value
                continue
            raise _Unresolvable(f"parameter {name!r}")
        if name in _SIZE_NAMES:
            local[name] = size
            continue
        raise _Unresolvable(f"name {name!r}")
    code = compile(ast.Expression(body=_strip_locations(expr)), "<perm>", "eval")
    merged = dict(env)
    merged.update(local)
    try:
        return eval(code, merged)
    except _UnresolvableErrors as e:
        raise _Unresolvable(str(e))


_UnresolvableErrors = (NameError, AttributeError, TypeError, ValueError, IndexError, KeyError)


def _strip_locations(expr: ast.AST) -> ast.AST:
    import copy

    new = copy.deepcopy(expr)
    return ast.fix_missing_locations(
        ast.copy_location(new, ast.Expr(lineno=1, col_offset=0))
    )


#: builders whose results SPMD101 verifies whenever the analyzed file
#: defines them — the schedule metadata of the zig-zag causal ring
_BUILDER_NAMES = ("zigzag_perms", "zigzag_inverse_perms", "zigzag_chunk_owner", "ring_source")


@rule("SPMD101", "ppermute permutations must be statically-valid bijections", dynamic=True)
def check_ppermute_bijection(ctx: FileContext) -> Iterable[Finding]:
    """Every ``jax.lax.ppermute`` perm that is visible as a comprehension,
    a literal, or a call into a local builder is evaluated for mesh sizes
    1..8 and validated as a partial bijection (distinct sources, distinct
    destinations, indices in range).  Files defining the zig-zag /ring
    schedule builders additionally get their cycle structure verified
    against simulation."""
    env = None  # built lazily: most files have no ppermute at all

    def get_env():
        nonlocal env
        if env is None:
            env = _module_def_env(ctx)
        return env

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.resolves_to(node.func, "ppermute"):
            continue
        perm_expr = None
        if len(node.args) >= 3:
            perm_expr = node.args[2]
        else:
            for kw in node.keywords:
                if kw.arg == "perm":
                    perm_expr = kw.value
        if perm_expr is None:
            continue
        expr, at = perm_expr, node
        if isinstance(expr, ast.Name):
            rec = ctx.lookup(expr.id, node)
            if rec is None:
                continue  # parameter or unknown: checked at its builder
            if rec[0] == "expr":
                expr = rec[1]
            else:  # tuple-unpack from a builder call
                call, idx = rec[1], rec[2]
                expr = ast.Subscript(
                    value=call, slice=ast.Constant(value=idx), ctx=ast.Load()
                )
        if isinstance(expr, ast.Name):
            continue  # parameter-fed perms are validated at the builder
        for size in MESH_SIZES:
            try:
                perm = _eval_expr(ctx, expr, at, size, get_env())
            except _Unresolvable:
                break  # not statically evaluable here: builder-site duty
            err = check_partial_bijection(perm, size)
            if err:
                yield ctx.finding(
                    "SPMD101", node,
                    f"ppermute perm is not a valid permutation: {err}",
                    hint="every (src, dst) pair needs distinct sources and "
                    "distinct destinations in [0, mesh size); rebuild the "
                    "perm from the mesh size, not from data",
                )
                break

    # schedule builders defined here: verify cycle structure by simulation
    defs = {
        name: ctx.module_function(name)
        for name in _BUILDER_NAMES
        if ctx.module_function(name) is not None
    }
    if defs:
        env = get_env()
        have = {k: env.get(k) for k in defs if callable(env.get(k))}
        err = None
        if "ring_source" in have:
            err = verify_ring_schedule(have["ring_source"])
            anchor = defs["ring_source"]
        if err is None and ("zigzag_perms" in have or "zigzag_inverse_perms" in have):
            err = verify_zigzag_builders(
                zigzag_perms=have.get("zigzag_perms"),
                zigzag_inverse_perms=have.get("zigzag_inverse_perms"),
                zigzag_chunk_owner=have.get("zigzag_chunk_owner"),
            )
            anchor = defs.get("zigzag_perms") or defs.get("zigzag_inverse_perms")
        if err:
            yield ctx.finding(
                "SPMD101", anchor,
                f"schedule builder fails simulation: {err}",
                hint="the perm-builder contract is checked for mesh sizes "
                "1..8 against a direct simulation of the ring/zig-zag "
                "layout; see tests/test_spmdlint.py for the ground truth",
            )


# --------------------------------------------------------------------- #
# SPMD102: collective axis names vs the enclosing shard_map              #
# --------------------------------------------------------------------- #
#: collective leaf name -> positional index of its axis-name argument
_COLLECTIVES = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "ppermute": 1,
    "all_gather": 1, "all_to_all": 1, "psum_scatter": 1, "pshuffle": 1,
    "pbroadcast": 1, "pcast": 1, "axis_index": 0,
    # heat_tpu.comm.compressed ring collectives (in-kernel forms)
    "ring_allreduce_q": 1, "ring_allreduce_q_ef": 2, "ring_allgather_q": 1,
    "allreduce_q": 6,
}


def _axis_exprs_of_collective(call: ast.Call, leaf: str) -> List[ast.AST]:
    idx = _COLLECTIVES[leaf]
    expr = None
    if len(call.args) > idx:
        expr = call.args[idx]
    else:
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axes", "axis"):
                expr = kw.value
    if expr is None:
        return []
    if isinstance(expr, (ast.Tuple, ast.List)):
        return list(expr.elts)
    return [expr]


def _is_axis_name_binding(ctx: FileContext, name: str, at: ast.AST) -> bool:
    rec = ctx.lookup(name, at)
    return (
        rec is not None
        and rec[0] == "expr"
        and isinstance(rec[1], ast.Attribute)
        and rec[1].attr == "axis_name"
    )


@rule("SPMD102", "collective axis names must match the enclosing shard_map mesh axis")
def check_axis_names(ctx: FileContext) -> Iterable[Finding]:
    """Inside each ``shard_map`` kernel, every collective's axis-name
    argument must be (a) one of the axis expressions named by the
    PartitionSpecs of the shard_map's in/out specs, (b) a variable bound
    from some ``*.axis_name``, or (c) a parameter (the helper-function
    pass-through, validated at its call sites).  Anything else is a
    mesh/axis mismatch waiting for a different mesh to crash on."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.resolves_to(node.func, "shard_map"):
            continue
        kernel = ctx._fn_node_of(node.args[0], node) if node.args else None
        if kernel is None:
            for kw in node.keywords:
                if kw.arg == "f":
                    kernel = ctx._fn_node_of(kw.value, node)
        if kernel is None:
            continue
        spec_tokens: set = set()
        spec_strings: set = set()
        for kw in node.keywords:
            if kw.arg not in ("in_specs", "out_specs"):
                continue
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Call) and ctx.resolves_to(
                    sub.func, "PartitionSpec", "P"
                ):
                    for a in sub.args:
                        if isinstance(a, ast.Constant):
                            if isinstance(a.value, str):
                                spec_strings.add(a.value)
                        elif isinstance(a, (ast.Name, ast.Attribute)):
                            spec_tokens.add(ast.dump(_strip_locations(a)))

        kernel_params = {a.arg for a in kernel.args.args + kernel.args.kwonlyargs}
        for sub in ast.walk(kernel):
            if not isinstance(sub, ast.Call):
                continue
            dotted = ctx.resolve(sub.func) or ""
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf not in _COLLECTIVES:
                continue
            if not (
                "jax" in dotted
                or "lax" in dotted
                or dotted == leaf
                or "_jax_compat" in dotted
                or "compressed" in dotted
            ):
                continue
            for expr in _axis_exprs_of_collective(sub, leaf):
                if isinstance(expr, ast.Constant):
                    if expr.value is None:
                        continue
                    if spec_strings and expr.value in spec_strings:
                        continue
                    if not spec_strings and not spec_tokens:
                        continue  # specs not statically visible
                    yield ctx.finding(
                        "SPMD102", sub,
                        f"collective {leaf!r} names axis {expr.value!r}, "
                        f"not an axis of the enclosing shard_map "
                        f"({sorted(spec_strings) or 'symbolic specs'})",
                        hint="use the mesh axis named in the shard_map's "
                        "PartitionSpecs (conventionally the variable bound "
                        "from comm.axis_name)",
                    )
                    continue
                if isinstance(expr, ast.Name):
                    enclosing_params = set(kernel_params)
                    for fn in ctx.enclosing_functions(sub):
                        enclosing_params.update(
                            a.arg for a in fn.args.args + fn.args.kwonlyargs
                        )
                    if expr.id in enclosing_params:
                        continue  # pass-through: call sites carry the proof
                    if ast.dump(_strip_locations(expr)) in spec_tokens:
                        continue
                    if _is_axis_name_binding(ctx, expr.id, sub):
                        continue
                    yield ctx.finding(
                        "SPMD102", sub,
                        f"collective {leaf!r} axis {expr.id!r} does not "
                        "match the enclosing shard_map's mesh axis",
                        hint="bind the axis once (`name = comm.axis_name`) "
                        "and use that same variable in the PartitionSpecs "
                        "and every collective",
                    )
                elif isinstance(expr, ast.Attribute):
                    if expr.attr == "axis_name":
                        continue
                    if ast.dump(_strip_locations(expr)) in spec_tokens:
                        continue
                    yield ctx.finding(
                        "SPMD102", sub,
                        f"collective {leaf!r} axis expression is not the "
                        "enclosing shard_map's mesh axis",
                        hint="pass the axis name bound from comm.axis_name",
                    )


# --------------------------------------------------------------------- #
# SPMD201: trace purity                                                  #
# --------------------------------------------------------------------- #
_BANNED_CALLS = {
    "time.time": "wall-clock reads bake one value into the compiled program",
    "time.perf_counter": "wall-clock reads bake one value into the compiled program",
    "time.monotonic": "wall-clock reads bake one value into the compiled program",
    "time.sleep": "host sleeps are invisible to the compiled program",
    "print": "host print runs at TRACE time only (once, with tracers)",
    "open": "file I/O at trace time runs once, not per call",
    "input": "blocking host I/O inside a traced function",
    "breakpoint": "debugger traps do not survive tracing",
}
_BANNED_PREFIXES = {
    "numpy.random.": "numpy RNG is host state: traced once, frozen forever "
    "— use jax.random with an explicit key",
    "random.": "stdlib RNG is host state: traced once, frozen forever — "
    "use jax.random with an explicit key",
}


@rule("SPMD201", "no host effects inside jit/shard_map/pallas-traced functions")
def check_trace_purity(ctx: FileContext) -> Iterable[Finding]:
    """Functions handed to ``jit``/``shard_map``/``pallas_call`` (or
    defined inside an op-engine ``jitted`` factory) run ONCE at trace
    time; host effects inside them silently freeze (RNG, clocks) or
    vanish (print, I/O), and ``global`` writes make the cached executable
    depend on hidden state."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and ctx.in_traced_context(node):
            dotted = ctx.resolve(node.func)
            if dotted is None:
                continue
            if dotted in _BANNED_CALLS:
                yield ctx.finding(
                    "SPMD201", node,
                    f"host effect {dotted!r} inside a traced function",
                    hint=_BANNED_CALLS[dotted],
                )
                continue
            for prefix, why in _BANNED_PREFIXES.items():
                if dotted.startswith(prefix) and not dotted.startswith("jax."):
                    yield ctx.finding(
                        "SPMD201", node,
                        f"host RNG {dotted!r} inside a traced function",
                        hint=why,
                    )
                    break
        elif isinstance(node, ast.Global) and ctx.in_traced_context(node):
            yield ctx.finding(
                "SPMD201", node,
                f"global-variable write ({', '.join(node.names)}) inside a "
                "traced function",
                hint="traced functions must be pure: thread state through "
                "arguments/carries, or move the mutation outside the jit",
            )


# --------------------------------------------------------------------- #
# SPMD202: host-sync coercions on traced values                          #
# --------------------------------------------------------------------- #
#: method calls that materialize a device value on the host
_SYNC_METHODS = {"item", "tolist", "numpy"}
#: numpy entry points that pull a traced array back to host memory
_NP_MATERIALIZERS = {"asarray", "array", "ascontiguousarray", "asfortranarray"}
#: scalar coercions that force a device→host sync when fed a traced value
_COERCIONS = {"float", "int", "bool", "complex"}
#: attribute leaves that are compile-time metadata, not device values —
#: coercing these is free and legitimate (``int(x.shape[0])``)
_STATIC_ATTRS = {
    "shape", "gshape", "lshape", "ndim", "size", "split", "itemsize",
    "dtype", "balanced",
}
#: array-method reductions whose results are device values
_REDUCTION_METHODS = {
    "sum", "max", "min", "mean", "prod", "norm", "argmax", "argmin",
    "all", "any", "std", "var", "dot", "astype",
}


def _is_static_expr(ctx: FileContext, expr: ast.AST, at: ast.AST, depth: int = 0) -> bool:
    """True when ``expr`` is visibly compile-time metadata (shape/ndim
    arithmetic, constants, ``len()``) — coercing it never touches the
    device."""
    if depth > 5:
        return False
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Attribute):
        return expr.attr in _STATIC_ATTRS
    if isinstance(expr, ast.Subscript):
        return _is_static_expr(ctx, expr.value, at, depth + 1)
    if isinstance(expr, ast.BinOp):
        return _is_static_expr(ctx, expr.left, at, depth + 1) and _is_static_expr(
            ctx, expr.right, at, depth + 1
        )
    if isinstance(expr, ast.UnaryOp):
        return _is_static_expr(ctx, expr.operand, at, depth + 1)
    if isinstance(expr, ast.Call):
        return isinstance(expr.func, ast.Name) and expr.func.id == "len"
    if isinstance(expr, ast.Name):
        rec = ctx.lookup(expr.id, at)
        if rec is not None and rec[0] == "expr":
            return _is_static_expr(ctx, rec[1], at, depth + 1)
    return False


def _is_device_value_expr(ctx: FileContext, expr: ast.AST) -> bool:
    """True when ``expr`` visibly produces a device value: any ``jax.*``
    call, an array-method reduction, or a ``.larray``/``._buffer``
    access anywhere inside it."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            dotted = ctx.resolve(sub.func) or ""
            if dotted.startswith("jax.") or dotted == "jax":
                return True
            if isinstance(sub.func, ast.Attribute) and sub.func.attr in _REDUCTION_METHODS:
                return True
        elif isinstance(sub, ast.Attribute) and sub.attr in ("larray", "_buffer"):
            return True
    return False


@rule("SPMD202", "no host-sync coercions of traced values inside traced functions")
def check_host_sync(ctx: FileContext) -> Iterable[Finding]:
    """Inside functions traced by ``jit``/``shard_map``/``fuse`` (or
    nested in an op-engine ``jitted`` factory), value-forcing operations —
    ``.item()``/``.tolist()``/``.numpy()``, ``np.asarray``/``np.array``,
    and ``float()``/``int()``/``bool()``/``complex()`` of device values —
    either crash on the tracer (``TracerConversionError`` / heat_tpu's
    ``FuseTraceError``) or, worse, silently freeze a trace-time constant
    into the compiled program.  Coercions of static metadata
    (``int(x.shape[0])``) are exempt; a bare-name coercion is flagged only
    when its assignment visibly produced a device value, so python-int
    loop bookkeeping never trips it."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.in_traced_context(node):
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
            yield ctx.finding(
                "SPMD202", node,
                f"host-sync method .{node.func.attr}() inside a traced function",
                hint="the result is a tracer, not a value: keep the "
                "computation on-device (jnp.where / lax.cond) or move "
                "this step outside the traced function",
            )
            continue
        dotted = ctx.resolve(node.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        if leaf in _NP_MATERIALIZERS and dotted.startswith("numpy."):
            yield ctx.finding(
                "SPMD202", node,
                f"numpy materialization {dotted!r} inside a traced function",
                hint="np.asarray on a tracer forces a host copy (or "
                "crashes); use jnp equivalents so the value stays in the "
                "compiled program",
            )
            continue
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in _COERCIONS
            and node.func.id not in ctx.aliases  # shadowed by an import
            and len(node.args) == 1
        ):
            arg = node.args[0]
            if _is_static_expr(ctx, arg, node):
                continue
            flagged = _is_device_value_expr(ctx, arg)
            if not flagged and isinstance(arg, ast.Name):
                rec = ctx.lookup(arg.id, node)
                flagged = (
                    rec is not None
                    and rec[0] == "expr"
                    and _is_device_value_expr(ctx, rec[1])
                )
            if flagged:
                yield ctx.finding(
                    "SPMD202", node,
                    f"scalar coercion {node.func.id}() of a device value "
                    "inside a traced function",
                    hint="this blocks on device→host transfer per call (or "
                    "raises under fuse); keep the decision on-device with "
                    "jnp.where / lax.cond, or hoist the sync out of the "
                    "traced region",
                )


# --------------------------------------------------------------------- #
# SPMD203: quantized collectives must carry inexact payloads             #
# --------------------------------------------------------------------- #
#: quantized-collective leaf name -> positional index of its payload
_QUANTIZED_COLLECTIVES = {
    "ring_allreduce_q": 0, "ring_allreduce_q_ef": 0, "ring_allgather_q": 0,
    "allreduce_q": 0, "allgather_q": 0, "quantize_blocks": 0,
}
#: dtype leaves whose values must survive a collective bit-exactly
_EXACT_DTYPE_LEAVES = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "bool_", "bool", "integer", "signedinteger",
}


def _exact_dtype_expr(ctx: FileContext, expr: ast.AST) -> Optional[str]:
    """The integer/bool dtype named by ``expr`` (``jnp.int32``,
    ``"int64"``, ...), or None when it is not visibly exact."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value if expr.value in _EXACT_DTYPE_LEAVES else None
    dotted = ctx.resolve(expr) or ""
    leaf = dotted.rsplit(".", 1)[-1]
    return leaf if leaf in _EXACT_DTYPE_LEAVES else None


def _visibly_exact_payload(
    ctx: FileContext, expr: ast.AST, at: ast.AST, depth: int = 0
) -> Optional[str]:
    """The exact dtype ``expr`` visibly carries, or None.  Follows
    ``.astype(...)`` tails, ``dtype=`` keywords of constructors, and
    single-assignment name bindings (same lookup discipline as SPMD202's
    device-value tracking)."""
    if depth > 5:
        return None
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Attribute) and expr.func.attr == "astype":
            if expr.args:
                return _exact_dtype_expr(ctx, expr.args[0])
            for kw in expr.keywords:
                if kw.arg == "dtype":
                    return _exact_dtype_expr(ctx, kw.value)
            return None
        for kw in expr.keywords:
            if kw.arg == "dtype":
                return _exact_dtype_expr(ctx, kw.value)
        return None
    if isinstance(expr, ast.Name):
        rec = ctx.lookup(expr.id, at)
        if rec is not None and rec[0] == "expr":
            return _visibly_exact_payload(ctx, rec[1], at, depth + 1)
    return None


@rule("SPMD203", "quantized collectives must not carry integer/exact-dtype payloads")
def check_quantized_payload_dtype(ctx: FileContext) -> Iterable[Finding]:
    """Block-scaled quantized collectives (``ring_allreduce_q`` and
    friends) round their payload to int8-with-scales: floats degrade
    gracefully, but integer/bool payloads — indices, counts, masks,
    labels — silently corrupt, because a count that comes back 79.6
    instead of 80 is not "less precise", it is wrong.  Flags any quantized
    collective whose payload expression visibly carries an exact dtype
    (``.astype(jnp.int32)``, a ``dtype=jnp.int64`` constructor, or a name
    bound to one).  Exact payloads belong on ``jax.lax.psum`` — the
    runtime twin of this rule is ``reduce_mode``'s TypeError on explicit
    compression of exact dtypes."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.resolve(node.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        if leaf not in _QUANTIZED_COLLECTIVES:
            continue
        if not ("compressed" in dotted or "comm" in dotted or dotted == leaf):
            continue
        idx = _QUANTIZED_COLLECTIVES[leaf]
        if len(node.args) <= idx:
            continue
        dt = _visibly_exact_payload(ctx, node.args[idx], node)
        if dt is not None:
            yield ctx.finding(
                "SPMD203", node,
                f"quantized collective {leaf!r} payload visibly has exact "
                f"dtype {dt!r}",
                hint="int8 block-scaling rounds the payload: integer/bool "
                "values (counts, indices, masks) corrupt silently.  Keep "
                "exact dtypes on jax.lax.psum, or cast to float only if "
                "approximate results are genuinely acceptable",
            )


# --------------------------------------------------------------------- #
# SPMD204: quantized collectives in guard-disabled regions               #
# --------------------------------------------------------------------- #
def _guard_off_call(ctx: FileContext, expr: ast.AST, leaf_name: str) -> bool:
    """True when ``expr`` is a ``guard("off")`` / ``set_guard_policy("off")``
    call (positionally or via ``policy=``) from the resilience layer (or a
    bare name, the fixture/test spelling)."""
    if not isinstance(expr, ast.Call):
        return False
    dotted = ctx.resolve(expr.func) or ""
    if dotted.rsplit(".", 1)[-1] != leaf_name:
        return False
    if not (
        dotted == leaf_name
        or "resilience" in dotted
        or "guards" in dotted
        or "heat_tpu" in dotted
    ):
        return False
    policy = expr.args[0] if expr.args else None
    if policy is None:
        for kw in expr.keywords:
            if kw.arg == "policy":
                policy = kw.value
    return isinstance(policy, ast.Constant) and policy.value == "off"


@rule("SPMD204", "quantized collectives in guard-disabled regions need an explicit suppression")
def check_guard_disabled_collectives(ctx: FileContext) -> Iterable[Finding]:
    """A quantized collective under ``guard("off")`` runs with its
    numerical health checks stripped: non-finite or saturated payloads
    pass through the int8 ring unchallenged, which is precisely the
    failure mode the guards exist to catch.  Flags any quantized
    collective call (``allreduce_q`` and friends, the SPMD203 set) that
    is lexically inside a ``with guard("off")`` block or follows a
    ``set_guard_policy("off")`` call in the same scope, unless the line
    carries ``# spmdlint: disable=SPMD204`` — disabling guards around a
    compressed collective must be a visible, deliberate decision."""
    off_sets: List[Tuple[ast.AST, int]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _guard_off_call(ctx, node, "set_guard_policy"):
            encl = ctx.enclosing_functions(node)
            off_sets.append((encl[0] if encl else ctx.tree, node.lineno))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.resolve(node.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        if leaf not in _QUANTIZED_COLLECTIVES:
            continue
        if not ("compressed" in dotted or "comm" in dotted or dotted == leaf):
            continue
        reason = None
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = ctx.parents.get(cur)
            if isinstance(cur, ast.With):
                for item in cur.items:
                    if _guard_off_call(ctx, item.context_expr, "guard"):
                        reason = 'a `with guard("off")` block'
                        break
            if reason:
                break
        if reason is None:
            encl = ctx.enclosing_functions(node)
            scope = encl[0] if encl else ctx.tree
            for s, ln in off_sets:
                if s is scope and ln < node.lineno:
                    reason = 'a set_guard_policy("off") call above it'
                    break
        if reason:
            yield ctx.finding(
                "SPMD204", node,
                f"quantized collective {leaf!r} runs inside {reason} "
                "with numerical health guards disabled",
                hint="compressed collectives silently propagate non-finite "
                "or saturated payloads when unguarded; re-enable guards "
                "(policy 'raise'/'warn'/'degrade'), or mark the call with "
                "`# spmdlint: disable=SPMD204` if running unguarded is a "
                "deliberate, reviewed decision",
            )


# --------------------------------------------------------------------- #
# SPMD205: host timing inside traced functions                           #
# --------------------------------------------------------------------- #
#: host clocks whose reading inside a traced body is a trace-time
#: constant — including the `_ns` variants SPMD201 does not list
_TIMING_CALLS = {
    "time.time", "time.time_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.process_time", "time.process_time_ns",
}
#: dotted-suffix forms of the telemetry span entry point (`from heat_tpu
#: import telemetry` and the internal `from ..telemetry import _core`)
_SPAN_SUFFIXES = ("telemetry.span", "telemetry._core.span")


@rule("SPMD205", "host-side timing inside traced functions measures trace time, not run time")
def check_trace_timing(ctx: FileContext) -> Iterable[Finding]:
    """A traced body runs ONCE, at trace time, with abstract tracers: a
    ``time.*`` read or a ``telemetry.span`` opened inside it brackets the
    *tracing* of the program — microseconds of Python — not the compiled
    execution it stands for, and the measured value is frozen into the
    cache.  Deliberately overlaps SPMD201 on the wall-clock reads (either
    finding alone should stop the commit) and extends the set with the
    ``_ns``/``process_time`` variants and the telemetry span API, whose
    timing intent makes the trace/run confusion easy to miss."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and ctx.in_traced_context(node)):
            continue
        dotted = ctx.resolve(node.func)
        if dotted is None:
            continue
        if dotted in _TIMING_CALLS:
            yield ctx.finding(
                "SPMD205", node,
                f"host clock {dotted!r} read inside a traced function",
                hint="the read happens once at trace time and its value is "
                "baked into the compiled program; time the jitted call at "
                "its HOST call site (after block_until_ready), or use "
                "jax.profiler device traces",
            )
        elif any(dotted == s or dotted.endswith("." + s) for s in _SPAN_SUFFIXES):
            yield ctx.finding(
                "SPMD205", node,
                "telemetry.span opened inside a traced function",
                hint="the span brackets TRACING (one-time Python), not the "
                "compiled execution; move the span to the host call site "
                "around the jitted/fused call, as the op engine already "
                "does for its own sites",
            )


# --------------------------------------------------------------------- #
# SPMD206: monolithic resplit inside a loop body                         #
# --------------------------------------------------------------------- #
#: layout-change entry points whose repeated monolithic execution is the
#: worst-case pattern: each iteration pays a full GSPMD reshard
#: (gather+slice envelope) where one hoisted resplit — or the planned
#: rotation schedule — was expected
_RESPLIT_CALLS = {"resplit", "resplit_", "alltoall", "commit_split"}


def _planned_policy_call(ctx: FileContext, expr: ast.AST, leaf_name: str) -> bool:
    """True when ``expr`` is a ``redistribution("planned"|"auto")`` /
    ``set_redistribution("planned"|"auto")`` call (positionally or via
    ``policy=``) from the comm layer (or a bare name, the fixture/test
    spelling) — the exemption: under the planner, a loop-body resplit
    replays one bounded compiled schedule instead of the monolithic
    worst case."""
    if not isinstance(expr, ast.Call):
        return False
    dotted = ctx.resolve(expr.func) or ""
    if dotted.rsplit(".", 1)[-1] != leaf_name:
        return False
    if not (
        dotted == leaf_name
        or "comm" in dotted
        or "redistribute" in dotted
        or "heat_tpu" in dotted
    ):
        return False
    policy = expr.args[0] if expr.args else None
    if policy is None:
        for kw in expr.keywords:
            if kw.arg == "policy":
                policy = kw.value
    return isinstance(policy, ast.Constant) and policy.value in ("planned", "auto")


@rule("SPMD206", "monolithic split→split resplit inside a loop body")
def check_resplit_in_loop(ctx: FileContext) -> Iterable[Finding]:
    """A ``resplit``/``alltoall``/``commit_split`` lexically inside a
    ``for``/``while`` body repeats the framework's single most expensive
    layout primitive every iteration — under the monolithic policy each
    pass is a worst-case GSPMD reshard (all-gather + slice envelope,
    reference ``Alltoallv`` communication.py:764-881).  Almost always
    the change is loop-invariant and hoists, or belongs under the
    planned redistribution policy, whose compiled rotation schedule
    moves ``(p-1)/p²`` of the array per device with bounded peak memory
    and replays from the program cache.  Exempt when the call sits
    inside a ``with redistribution("planned"|"auto")`` block or follows
    a ``set_redistribution("planned"|"auto")`` call in the same scope;
    traced bodies (jit/shard_map/fuse) are also exempt — there the
    "call" is a sharding constraint compiled once, not a per-iteration
    collective."""
    planned_sets: List[Tuple[ast.AST, int]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _planned_policy_call(
            ctx, node, "set_redistribution"
        ):
            encl = ctx.enclosing_functions(node)
            planned_sets.append((encl[0] if encl else ctx.tree, node.lineno))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.resolve(node.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        if leaf not in _RESPLIT_CALLS:
            continue
        # a resplit is a method of a DNDarray/comm object (or the comm
        # module's function) — a bare local helper named `resplit` is
        # not the layout primitive
        if "." not in dotted:
            continue
        if ctx.in_traced_context(node):
            continue
        in_loop = False
        exempt = False
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = ctx.parents.get(cur)
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                in_loop = True
            if isinstance(cur, ast.With):
                for item in cur.items:
                    if _planned_policy_call(ctx, item.context_expr, "redistribution"):
                        exempt = True
                        break
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break  # loop containment is per-function, not lexical-outward
        if not in_loop or exempt:
            continue
        encl = ctx.enclosing_functions(node)
        scope = encl[0] if encl else ctx.tree
        if any(s is scope and ln < node.lineno for s, ln in planned_sets):
            continue
        yield ctx.finding(
            "SPMD206", node,
            f"monolithic layout change {leaf!r} inside a loop body pays a "
            "worst-case reshard every iteration",
            hint="hoist the resplit out of the loop if the layout is "
            "loop-invariant; otherwise run it under the planned "
            "redistribution policy (ht.comm.set_redistribution('planned') "
            "or `with redistribution(\"planned\")`), whose compiled "
            "schedule is minimal-traffic and memory-bounded — or mark the "
            "call with `# spmdlint: disable=SPMD206` if the per-iteration "
            "monolithic reshard is deliberate",
        )


# --------------------------------------------------------------------- #
# SPMD207: silent broad except around dispatch/collective/io sites       #
# --------------------------------------------------------------------- #
#: exception leaves that catch "anything that can go wrong at a guarded
#: site" — the fault classes the resilience layer exists to make visible
_BROAD_EXC = {"Exception", "BaseException", "OSError", "IOError",
              "EnvironmentError"}

#: call leaves whose failures must never vanish: file opens/loads/saves,
#: checkpoint and loop-snapshot manifests, layout changes, collectives
_GUARDED_SITE_CALLS = {
    "open", "File", "Dataset",
    "load", "save", "load_hdf5", "save_hdf5", "load_netcdf", "save_netcdf",
    "load_csv", "save_csv", "load_loop_state", "save_loop_state",
    "load_estimator", "save_estimator",
    "resplit", "resplit_", "commit_split", "apply_sharding", "redistribute",
    "alltoall", "allreduce", "allgather", "all_gather", "ppermute", "psum",
}


def _broad_handler_names(ctx: FileContext, handler: ast.ExceptHandler) -> List[str]:
    """The broad exception leaves a handler catches (empty = narrow)."""
    t = handler.type
    if t is None:
        return ["(bare except)"]
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        dotted = ctx.resolve(e) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        if leaf in _BROAD_EXC:
            out.append(leaf)
    return out


def _handler_is_silent(ctx: FileContext, handler: ast.ExceptHandler) -> bool:
    """True when nothing in the handler body makes the fault visible: no
    re-raise, no reference to the caught exception (the deferred-error
    barrier pattern binds it — ``err = e``), no incident record, no
    warning/log call."""
    caught = handler.name
    for stmt in handler.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Raise):
                return False
            if caught and isinstance(sub, ast.Name) and sub.id == caught:
                return False
            if isinstance(sub, ast.Call):
                dotted = ctx.resolve(sub.func) or ""
                leaf = dotted.rsplit(".", 1)[-1]
                if leaf == "record" or "incident" in dotted:
                    return False
                if leaf in ("warn", "warning", "error", "exception", "critical"):
                    return False
    return True


@rule("SPMD207", "silent broad except around dispatch/collective/io sites")
def check_silent_broad_except(ctx: FileContext) -> Iterable[Finding]:
    """A ``try`` whose body touches a dispatch, collective, or io site
    (file opens/loads/saves, checkpoint manifests, resplits, ring
    collectives) with an ``except Exception``/``except OSError`` handler
    that neither re-raises, nor references the caught exception (the
    deferred-error barrier pattern — ``err = e`` past a collective
    fence), nor records an incident, makes the fault *invisible*: the
    fit continues on garbage, the chaos lane can't see the injection,
    and the retry/elastic machinery never engages.  Transient faults
    belong on the retry engine (``resilience.retry``); real failures
    belong in the incident log (``resilience.incidents.record``) or
    propagated."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        guarded_leaf = None
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = ctx.resolve(sub.func) or ""
                leaf = dotted.rsplit(".", 1)[-1]
                if leaf in _GUARDED_SITE_CALLS:
                    guarded_leaf = leaf
                    break
            if guarded_leaf:
                break
        if guarded_leaf is None:
            continue
        for handler in node.handlers:
            broad = _broad_handler_names(ctx, handler)
            if not broad or not _handler_is_silent(ctx, handler):
                continue
            yield ctx.finding(
                "SPMD207", handler,
                f"broad `except {broad[0]}` swallows failures of guarded "
                f"site {guarded_leaf!r} without re-raise or incident "
                "record — the fault becomes invisible",
                hint="re-raise after cleanup, bind and defer the exception "
                "past the barrier (err = e), route transients through "
                "resilience.retry, or record it with "
                "resilience.incidents.record(...); mark the handler with "
                "`# spmdlint: disable=SPMD207` if the swallow is deliberate",
            )


# --------------------------------------------------------------------- #
# SPMD208: unbucketed dynamic batch shape entering a compiled program    #
# --------------------------------------------------------------------- #
#: shape-canonicalization helpers: a slice bound routed through one of
#: these is drawn from a finite shape space (powers of two / shard
#: multiples), so the compiled-program cache stays bounded
_BUCKETING_CALLS = {
    "bucket_rows", "next_pow2", "_padded_len", "pad_to_bucket",
    "pad_to_shards", "pad_batch",
}


def _is_compiled_callable(ctx: FileContext, func: ast.AST, at: ast.AST) -> bool:
    """True when ``func`` is a compiled-program value: a direct
    ``fuse(f)(...)`` / ``jitted(key, make)(...)`` product, a name bound
    from one, or a function defined under ``@fuse`` / ``@jax.jit``."""
    if isinstance(func, ast.Call):
        return ctx.resolves_to(func.func, "fuse", "jitted", "jit", "jax.jit")
    if isinstance(func, ast.Name):
        rec = ctx.lookup(func.id, at)
        if (
            rec is not None
            and rec[0] == "expr"
            and isinstance(rec[1], ast.Call)
            and ctx.resolves_to(rec[1].func, "fuse", "jitted", "jit", "jax.jit")
        ):
            return True
        fn = ctx.local_function(func.id, at)
        if isinstance(fn, ast.FunctionDef):
            for dec in fn.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if ctx.resolves_to(target, "fuse", "jit", "jax.jit"):
                    return True
    return False


def _bound_is_bucketed(ctx: FileContext, bound: ast.AST, at: ast.AST) -> bool:
    """True when the slice bound routes through a bucketing helper —
    directly (``x[:bucket_rows(n)]``) or via one local assignment
    (``b = bucket_rows(n); ... x[:b]``)."""
    for sub in ast.walk(bound):
        if isinstance(sub, ast.Call):
            dotted = ctx.resolve(sub.func) or ""
            if dotted.rsplit(".", 1)[-1] in _BUCKETING_CALLS:
                return True
        if isinstance(sub, ast.Name):
            rec = ctx.lookup(sub.id, at)
            if rec is not None and rec[0] == "expr" and isinstance(rec[1], ast.Call):
                dotted = ctx.resolve(rec[1].func) or ""
                if dotted.rsplit(".", 1)[-1] in _BUCKETING_CALLS:
                    return True
    return False


def _dynamic_slice_operand(
    ctx: FileContext, expr: ast.AST, at: ast.AST
) -> Optional[ast.Subscript]:
    """The offending Subscript when ``expr`` is (or is a name
    once-assigned from) a slice whose bounds are dynamic and unbucketed;
    None otherwise."""
    if isinstance(expr, ast.Name):
        rec = ctx.lookup(expr.id, at)
        if rec is not None and rec[0] == "expr":
            expr = rec[1]
    if not isinstance(expr, ast.Subscript):
        return None
    sl = expr.slice
    slices = [sl] if isinstance(sl, ast.Slice) else (
        [e for e in sl.elts if isinstance(e, ast.Slice)]
        if isinstance(sl, ast.Tuple) else []
    )
    bounds = [b for s in slices for b in (s.lower, s.upper) if b is not None]
    dynamic = [
        b for b in bounds
        if any(isinstance(sub, (ast.Name, ast.Call)) for sub in ast.walk(b))
    ]
    if not dynamic:
        return None
    if all(_bound_is_bucketed(ctx, b, at) for b in dynamic):
        return None
    return expr


@rule("SPMD208", "unbucketed dynamic batch shape entering a compiled program in a loop")
def check_unbucketed_dynamic_batch(ctx: FileContext) -> Iterable[Finding]:
    """A call to a compiled program (``fuse(...)`` / ``jitted(...)``
    product or ``@fuse``-decorated function) lexically inside a
    ``for``/``while`` body, where an operand is a slice with
    data-dependent bounds (``queue[off : off + n]``), retraces and
    recompiles once per DISTINCT shape — the compiled-program cache keys
    on operand avals, so a request-sized slice turns the cache into an
    unbounded compile treadmill (one entry per batch size ever seen,
    each a full trace+lower+compile pause at serving time).

    The fix is the serving pad discipline: round the row count to a
    power of two and zero-pad (``serve.bucket_rows`` + ``pad_batch``, or
    ``pad_to_shards`` on a split axis) so the shape space is finite and
    every steady-state call replays a warm program.  Bounds routed
    through those bucketing helpers — directly or via one local
    assignment — are exempt, as are constant bounds and traced bodies
    (inside a trace the slice is program structure, not a per-call
    shape)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not _is_compiled_callable(ctx, node.func, node):
            continue
        if ctx.in_traced_context(node):
            continue
        in_loop = False
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = ctx.parents.get(cur)
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                in_loop = True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break  # loop containment is per-function, as in SPMD206
        if not in_loop:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            offending = _dynamic_slice_operand(ctx, arg, node)
            if offending is None:
                continue
            yield ctx.finding(
                "SPMD208", node,
                f"dynamic-shape operand {ast.unparse(offending)!r} enters a "
                "compiled program inside a loop — every distinct slice "
                "length is a fresh trace+compile, an unbounded program "
                "cache",
                hint="bucket the row count to a finite shape space before "
                "the call (serve.bucket_rows + pad_batch zero-padding, or "
                "pad_to_shards on a split axis) and slice the result "
                "AFTER the compiled call; mark the call with "
                "`# spmdlint: disable=SPMD208` if the slice lengths are "
                "genuinely bounded",
            )
            break


# --------------------------------------------------------------------- #
# SPMD209: serialized ring body — same-round ppermute consumption        #
# --------------------------------------------------------------------- #
#: loop-tracing entry points whose body argument runs once per ring
#: round; the indices name the traced body function(s), mirroring
#: :data:`~heat_tpu.analysis.core._TRACING_CALLS`
_LOOP_BODY_CALLS = {"fori_loop": (2,), "scan": (0,), "while_loop": (0, 1)}

#: calls that package a ppermute result without touching its values —
#: building a payload tuple is shipping, not consuming
_CONTAINER_CALLS = {"tuple", "list"}


def _overlap_gated(ctx: FileContext, node: ast.AST) -> bool:
    """True when ``node`` sits under an ``if`` whose test — or a ``with``
    whose context manager — names the overlap policy (an identifier
    containing ``overlap``).  That is the exemption: the file already
    branches on the double-buffer schedule, and BOTH arms of the branch
    are deliberate (the serial arm is the policy's bitwise twin, not an
    oversight).  The walk crosses function boundaries on purpose: a loop
    body ``def`` nested under ``if overlapped:`` is gated too."""
    cur: Optional[ast.AST] = node
    while cur is not None:
        cur = ctx.parents.get(cur)
        if isinstance(cur, ast.If):
            for sub in ast.walk(cur.test):
                label = sub.id if isinstance(sub, ast.Name) else (
                    sub.attr if isinstance(sub, ast.Attribute) else ""
                )
                if "overlap" in label.lower():
                    return True
        if isinstance(cur, ast.With):
            for item in cur.items:
                expr = item.context_expr
                target = expr.func if isinstance(expr, ast.Call) else expr
                dotted = ctx.resolve(target) or ""
                if "overlap" in dotted.rsplit(".", 1)[-1].lower():
                    return True
    return False


def _round_body(ctx: FileContext, node: ast.AST, loop_fns: set):
    """The per-round body containing ``node``: the nearest lexical
    ``for``/``while`` inside the enclosing function, or the enclosing
    function itself when it is the body argument of a jax loop
    combinator.  ``None`` when ``node`` does not run once per round."""
    cur: Optional[ast.AST] = node
    while cur is not None:
        cur = ctx.parents.get(cur)
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return cur
        if isinstance(cur, _FUNC_TYPES):
            return cur if cur in loop_fns else None
    return None


def _same_round_consumption(ctx: FileContext, node: ast.AST, body: ast.AST):
    """How the ppermute result is consumed inside its own round, or
    ``None`` when it only feeds the next round's carry.

    Two shapes count: the call nested under arithmetic or a non-container
    call in the same statement, and an assigned name loaded again later
    in the body.  Loads inside ``return`` statements are excluded — a
    returned carry IS the pipelined pattern (the value crosses into the
    next round, where overlap is possible); same-round reuse is what
    pins the wire onto the critical path."""
    stmt = ctx.enclosing_statement(node)
    cur: Optional[ast.AST] = node
    while cur is not stmt and cur is not None:
        cur = ctx.parents.get(cur)
        if isinstance(cur, (ast.BinOp, ast.UnaryOp, ast.Compare)):
            return "folded into arithmetic in the same statement"
        if isinstance(cur, ast.Call):
            leaf = (ctx.resolve(cur.func) or "").rsplit(".", 1)[-1]
            if leaf not in _CONTAINER_CALLS:
                return f"passed straight into {leaf or 'a call'}()"
    if isinstance(stmt, ast.AugAssign):
        return "augmented-assigned into live state"
    targets: set = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    targets.add(sub.id)
    elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
        targets.add(stmt.target.id)
    if not targets:
        return None
    after = getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno
    for sub in ast.walk(body):
        if (
            isinstance(sub, ast.Name)
            and isinstance(sub.ctx, ast.Load)
            and sub.id in targets
            and getattr(sub, "lineno", 0) > after
        ):
            ret: Optional[ast.AST] = sub
            while ret is not None and ret is not body:
                if isinstance(ret, ast.Return):
                    break
                ret = ctx.parents.get(ret)
            if isinstance(ret, ast.Return):
                continue  # next-round carry, not same-round consumption
            return f"read back as {sub.id!r} later in the round"
    return None


@rule("SPMD209", "serialized ring body: ppermute result consumed in the same round")
def check_serialized_ring_body(ctx: FileContext) -> Iterable[Finding]:
    """A ``jax.lax.ppermute`` inside a per-round body — a lexical
    ``for``/``while`` or a function passed to
    ``fori_loop``/``scan``/``while_loop`` — whose result is consumed in
    the SAME round (nested under arithmetic or a consuming call, or its
    assigned name is loaded again before the round ends) puts the wire
    hop on the critical path: every round is ``wire + compute`` instead
    of ``max(wire, compute)``, and no scheduler can hide the transfer
    because the data dependency forbids it.  Results that only feed the
    ``return``-ed carry are exempt — that IS the double-buffered shape
    (the in-flight slab crosses into the next round while this round's
    math runs).  Bodies gated on the overlap policy (under an ``if``
    test or ``with`` manager naming ``overlap``) are exempt as a pair:
    the serial arm there is the policy's deliberate bitwise twin."""
    loop_fns: set = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.resolve(node.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        if leaf in _LOOP_BODY_CALLS and (
            dotted == leaf or "jax" in dotted or "lax" in dotted
        ):
            for idx in _LOOP_BODY_CALLS[leaf]:
                if idx < len(node.args):
                    fn = ctx._fn_node_of(node.args[idx], node)
                    if fn is not None:
                        loop_fns.add(fn)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.resolves_to(node.func, "ppermute"):
            continue
        body = _round_body(ctx, node, loop_fns)
        if body is None or _overlap_gated(ctx, node):
            continue
        how = _same_round_consumption(ctx, node, body)
        if how is None:
            continue
        yield ctx.finding(
            "SPMD209", node,
            f"ppermute result {how} — the ring round serializes as "
            "wire + compute, every hop on the critical path",
            hint="double-buffer the ring: carry (current, in-flight) "
            "slabs, issue the next round's ppermute first, and fold the "
            "PREVIOUS round's operand (parallel/primitives.py ring_map; "
            "policy in heat_tpu.comm.overlap) — or gate the serial body "
            "under `if overlap_enabled(...)` so it is the policy's "
            "deliberate twin; mark with `# spmdlint: disable=SPMD209` if "
            "the same-round dependency is inherent to the algorithm",
        )


# --------------------------------------------------------------------- #
# SPMD210: request-scoped observability inside traced functions          #
# --------------------------------------------------------------------- #
#: dotted-suffix forms of the request-scoped observability entry points
#: (`heat_tpu.telemetry`, the `heat_tpu.obs` facade, and the internal
#: `from ..telemetry import _core` spelling) — context managers and
#: calls that run at TRACE time inside a traced body
_OBS_CTX_SUFFIXES = (
    "telemetry.trace_ctx", "telemetry._core.trace_ctx", "obs.trace_ctx",
)
_OBS_CALL_SUFFIXES = (
    "telemetry.observe", "telemetry._core.observe", "obs.observe",
)
_OBS_FLIGHT_SUFFIXES = ("flight.note",)


def _obs_match(dotted: str, suffixes) -> bool:
    return any(dotted == s or dotted.endswith("." + s) for s in suffixes)


@rule("SPMD210", "request-scoped observability inside traced functions records trace time, not run time")
def check_traced_observability(ctx: FileContext) -> Iterable[Finding]:
    """The SPMD205 argument, extended to the observability layer: a
    ``telemetry.trace_ctx`` entered, a ``telemetry.observe`` recorded, or
    a ``flight.note`` appended inside a jit/shard_map/fuse-traced body
    runs ONCE, at trace time, against abstract tracers.  The trace
    context is set and torn down before the compiled program ever
    executes (no run-time event can carry the ids); the observation
    lands a single trace-time value (often a tracer's ``str()``) in the
    histogram instead of per-execution samples; the flight note records
    the *tracing* of the program, not its launches.  All three belong at
    the HOST call site — around the jitted/fused call, where the serve
    engine places them."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and ctx.in_traced_context(node)):
            continue
        dotted = ctx.resolve(node.func)
        if dotted is None:
            continue
        if _obs_match(dotted, _OBS_CTX_SUFFIXES):
            yield ctx.finding(
                "SPMD210", node,
                "telemetry.trace_ctx entered inside a traced function",
                hint="the context is installed and reset during TRACING — "
                "compiled executions carry no request ids; wrap the host "
                "call site instead (the serve engine re-establishes the "
                "context per micro-batch around its fused predict call)",
            )
        elif _obs_match(dotted, _OBS_CALL_SUFFIXES):
            yield ctx.finding(
                "SPMD210", node,
                "telemetry.observe recorded inside a traced function",
                hint="the histogram receives ONE trace-time observation "
                "(possibly of a tracer), not per-execution samples; "
                "observe the measured value at the host call site after "
                "block_until_ready",
            )
        elif _obs_match(dotted, _OBS_FLIGHT_SUFFIXES):
            yield ctx.finding(
                "SPMD210", node,
                "flight-recorder note inside a traced function",
                hint="the note records the one-time tracing, not the "
                "compiled executions; note at the host call site, or rely "
                "on the _emit mirror for enabled-telemetry events",
            )


# --------------------------------------------------------------------- #
# SPMD211: retry loop without a deadline                                 #
# --------------------------------------------------------------------- #
#: identifier fragments whose presence anywhere in the loop marks it as
#: BOUNDED: a deadline/timeout check, an attempt budget, or delegation to
#: the retry engine (``for attempt in retry(policy)`` never matches the
#: rule anyway — it is a ``for``, not a ``while True``)
_RETRY_BOUND_MARKERS = (
    "deadline", "attempt", "retry", "timeout", "tries", "budget", "backoff",
)


def _loop_mentions_bound(node: ast.While) -> bool:
    """True when any identifier in the loop smells like a bound — the
    author is counting attempts or watching a clock, so the loop is a
    (possibly hand-rolled) bounded retry, not an infinite one."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.keyword):
            name = sub.arg
        if name is not None:
            low = name.lower()
            if any(m in low for m in _RETRY_BOUND_MARKERS):
                return True
    return False


def _handler_swallows_and_retries(handler: ast.ExceptHandler) -> bool:
    """True when the handler neither escapes the loop (``break``/
    ``return``) nor propagates (``raise``) — control falls back to the
    ``while True`` header and the failing call runs again, forever."""
    for stmt in handler.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, (ast.Raise, ast.Break, ast.Return)):
                return False
    return True


def _retried_site(ctx: FileContext, try_node: ast.Try) -> Optional[str]:
    """The retry-worthy call inside the ``try`` body, if any: a compiled
    program call (fuse/jit product) or one of SPMD207's guarded io/layout
    sites.  Anything else failing forever is somebody else's lint."""
    for stmt in try_node.body:
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            if _is_compiled_callable(ctx, sub.func, sub):
                return "a compiled program call"
            dotted = ctx.resolve(sub.func) or ""
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf in _GUARDED_SITE_CALLS:
                return f"guarded site {leaf!r}"
    return None


@rule("SPMD211", "retry loop without a deadline around a compiled/guarded call")
def check_unbounded_retry(ctx: FileContext) -> Iterable[Finding]:
    """A ``while True`` whose body try/excepts a compiled program call or
    a guarded io/layout site, where the handler swallows and loops (no
    ``raise``/``break``/``return``), retries FOREVER: a permanent fault
    (mesh gone, manifest corrupt, sidecar deleted) turns into a silent
    busy-loop that holds the serving thread, never surfaces an incident,
    and defeats the chaos lane's determinism (fire counts diverge with
    host timing).  Bounded retries belong on the retry engine —
    ``for attempt in resilience.retry.retry(policy, site=...)`` gives a
    deadline, jittered backoff, and incident records for free.  Loops
    that visibly count attempts or check a deadline/timeout are exempt,
    as is the retry engine's own implementation."""
    if ctx.relpath.endswith("resilience/retry.py"):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.While):
            continue
        test = node.test
        if not (isinstance(test, ast.Constant) and bool(test.value)):
            continue
        if _loop_mentions_bound(node):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Try):
                continue
            site = _retried_site(ctx, sub)
            if site is None:
                continue
            for handler in sub.handlers:
                if not _handler_swallows_and_retries(handler):
                    continue
                yield ctx.finding(
                    "SPMD211", handler,
                    f"`while True` retries {site} with no deadline or "
                    "attempt budget — a permanent fault becomes an "
                    "infinite busy-loop",
                    hint="route the call through `for attempt in "
                    "resilience.retry.retry(policy, site=...)` (deadline + "
                    "seeded backoff + incidents), or bound the loop with "
                    "an attempt counter / deadline check; mark with "
                    "`# spmdlint: disable=SPMD211` if the forever-retry "
                    "is deliberate",
                )


# --------------------------------------------------------------------- #
# SPMD212: blocking host read inside a compiled-program loop             #
# --------------------------------------------------------------------- #
#: dotted names whose call opens an on-disk dataset handle — re-opening
#: (and reading) one of these per loop iteration serializes the loop on
#: host storage latency
_HOST_READ_OPENERS = frozenset({
    "h5py.File",
    "netCDF4.Dataset",
    "scipy.io.netcdf_file",
})


def _file_handle_expr(ctx: FileContext, expr, at, depth: int = 0) -> bool:
    """True when ``expr`` evaluates to (a view of) an on-disk dataset
    handle: a direct opener call, a name once-bound to one, a subscript
    chain off one (``f[name][lo:hi]``), or its ``.variables`` mapping."""
    if depth > 8:
        return False
    if isinstance(expr, ast.Attribute) and expr.attr == "variables":
        return _file_handle_expr(ctx, expr.value, at, depth + 1)
    if isinstance(expr, ast.Subscript):
        return _file_handle_expr(ctx, expr.value, at, depth + 1)
    if isinstance(expr, ast.Call):
        return (ctx.resolve(expr.func) or "") in _HOST_READ_OPENERS
    if isinstance(expr, ast.Name):
        rec = ctx.lookup(expr.id, at)
        if rec is not None and rec[0] == "expr":
            return _file_handle_expr(ctx, rec[1], at, depth + 1)
    return False


def _blocking_host_read(ctx: FileContext, call: ast.Call) -> Optional[str]:
    """Why ``call`` is a blocking on-disk read, or None if it isn't."""
    dotted = ctx.resolve(call.func) or ""
    if dotted in _HOST_READ_OPENERS:
        return f"`{dotted}` re-opens the file every iteration"
    leaf = dotted.rsplit(".", 1)[-1]
    if (
        leaf in ("asarray", "array")
        and call.args
        and _file_handle_expr(ctx, call.args[0], call)
    ):
        return (
            f"`{leaf}` of a file-handle slice materializes the slab "
            "synchronously on the host"
        )
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr == "read_direct"
        and _file_handle_expr(ctx, call.func.value, call)
    ):
        return "`read_direct` on an open dataset handle blocks on storage"
    return None


@rule("SPMD212", "blocking host read inside a loop that dispatches compiled programs")
def check_blocking_read_in_compiled_loop(ctx: FileContext) -> Iterable[Finding]:
    """A loop body that both reads from an on-disk dataset (h5py/netCDF4
    handle access, ``np.asarray`` over a file-handle slice) and dispatches
    a compiled program serializes the device behind host storage: every
    iteration the accelerator sits idle for the full read+copy latency
    before its next dispatch, the exact ``h·(read+copy+compute)`` serial
    schedule ``comm._costs.stream_model`` prices.  The streaming path
    reads chunk ``t+1`` on a worker thread while chunk ``t`` computes —
    ``read + h·max(read+copy, compute)`` — and its generator keeps the
    read out of the dispatching loop's body by construction.  Reads in
    traced contexts are exempt (they are staging-time constants, not
    per-dispatch io)."""
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
            continue
        compiled = None
        read = None
        why = None
        for stmt in loop.body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call) or ctx.in_traced_context(sub):
                    continue
                if compiled is None and _is_compiled_callable(ctx, sub.func, sub):
                    compiled = sub
                if read is None:
                    why = _blocking_host_read(ctx, sub)
                    if why is not None:
                        read = sub
        if compiled is not None and read is not None:
            yield ctx.finding(
                "SPMD212", read,
                "blocking host read in a loop body that also dispatches a "
                f"compiled program — {why}, so the device idles behind "
                "storage every iteration",
                hint="stream the dataset through "
                "`heat_tpu.io.stream.stream_chunks` (double-buffered "
                "host→device prefetch overlaps the next read with this "
                "chunk's compute), or hoist the read out of the loop; mark "
                "with `# spmdlint: disable=SPMD212` if the serialization "
                "is deliberate",
            )


# --------------------------------------------------------------------- #
# SPMD213: blocking socket/pipe I/O inside a compiled-program loop       #
# --------------------------------------------------------------------- #
#: module-level calls that block the calling thread on a peer process or
#: pipe — one of these per iteration serializes the device behind IPC
_BLOCKING_PIPE_CALLS = frozenset({
    "os.read",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
})

#: socket methods that block until the peer answers
_SOCKET_BLOCKING_METHODS = frozenset({"recv", "recv_into", "recvfrom", "accept"})

#: constructors whose value is a socket object
_SOCKET_OPENERS = frozenset({
    "socket.socket", "socket.create_connection",
})

#: methods on a ``subprocess.Popen`` value that wait for the child
_POPEN_WAIT_METHODS = frozenset({"wait", "communicate"})


def _value_from_opener(ctx: FileContext, expr, at, openers: frozenset,
                       depth: int = 0) -> bool:
    """True when ``expr`` evaluates to a value produced by one of the
    ``openers``: a direct constructor call or a name once-bound to one."""
    if depth > 8:
        return False
    if isinstance(expr, ast.Call):
        return (ctx.resolve(expr.func) or "") in openers
    if isinstance(expr, ast.Name):
        rec = ctx.lookup(expr.id, at)
        if rec is not None and rec[0] == "expr":
            return _value_from_opener(ctx, rec[1], at, openers, depth + 1)
    return False


def _blocking_pipe_io(ctx: FileContext, call: ast.Call) -> Optional[str]:
    """Why ``call`` blocks on a socket/pipe/child, or None if it doesn't."""
    dotted = ctx.resolve(call.func) or ""
    if dotted in _BLOCKING_PIPE_CALLS:
        return f"`{dotted}` blocks the dispatching thread on a pipe/child"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr in _SOCKET_BLOCKING_METHODS and _value_from_opener(
            ctx, call.func.value, call, _SOCKET_OPENERS
        ):
            return f"`.{attr}` on a socket blocks until the peer answers"
        if attr in _POPEN_WAIT_METHODS and _value_from_opener(
            ctx, call.func.value, call, frozenset({"subprocess.Popen"})
        ):
            return f"`.{attr}` waits for the child process to exit"
    return None


@rule("SPMD213", "blocking socket/pipe I/O inside a loop that dispatches compiled programs")
def check_blocking_ipc_in_compiled_loop(ctx: FileContext) -> Iterable[Finding]:
    """A loop body that both performs blocking IPC (``socket.recv``,
    ``os.read``, ``subprocess.run``, ``Popen.wait``/``communicate``) and
    dispatches a compiled program serializes the device behind the peer:
    every iteration the accelerator idles for the full round-trip before
    its next dispatch — the process-boundary twin of SPMD212's storage
    stall.  The serving plane's shape is the fix: the dispatching loop
    lives in the replica process and never touches a socket, while the
    parent's RPC threads (``heat_tpu.serve.procfleet``) own the blocking
    recv and feed work through queues.  IPC in traced contexts is exempt
    (staging-time constants, not per-dispatch waits)."""
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
            continue
        compiled = None
        ipc = None
        why = None
        for stmt in loop.body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call) or ctx.in_traced_context(sub):
                    continue
                if compiled is None and _is_compiled_callable(ctx, sub.func, sub):
                    compiled = sub
                if ipc is None:
                    why = _blocking_pipe_io(ctx, sub)
                    if why is not None:
                        ipc = sub
        if compiled is not None and ipc is not None:
            yield ctx.finding(
                "SPMD213", ipc,
                "blocking socket/pipe I/O in a loop body that also "
                f"dispatches a compiled program — {why}, so the device "
                "idles behind IPC every iteration",
                hint="move the exchange off the dispatch path: a worker "
                "thread owning the socket feeds a queue the loop drains "
                "(the `heat_tpu.serve.procfleet` worker/outbox shape), or "
                "batch the IPC outside the loop; mark with "
                "`# spmdlint: disable=SPMD213` if the round-trip is "
                "deliberate",
            )


# --------------------------------------------------------------------- #
# SPMD214: unbounded blocking wait inside a worker loop                  #
# --------------------------------------------------------------------- #
def _opener_call(ctx: FileContext, expr, at, openers: frozenset,
                 depth: int = 0) -> Optional[ast.Call]:
    """The opener call that produced ``expr``'s value (a direct
    constructor call or a name once-bound to one), or None — the
    call-returning sibling of :func:`_value_from_opener`, kept separate
    so SPMD214 can inspect the opener's own arguments."""
    if depth > 8:
        return None
    if isinstance(expr, ast.Call):
        return expr if (ctx.resolve(expr.func) or "") in openers else None
    if isinstance(expr, ast.Name):
        rec = ctx.lookup(expr.id, at)
        if rec is not None and rec[0] == "expr":
            return _opener_call(ctx, rec[1], at, openers, depth + 1)
    return None


def _socket_has_timeout(ctx: FileContext, recv_call: ast.Call) -> bool:
    """True when the socket behind ``recv_call`` is visibly bounded: its
    opener passed a ``timeout`` (keyword, or ``create_connection``'s
    second positional), or the file calls ``settimeout`` with a
    non-None value on the same name."""
    opener = _opener_call(ctx, recv_call.func.value, recv_call,
                          _SOCKET_OPENERS)
    if opener is None:
        return True  # unknown provenance: not ours to flag
    if any(kw.arg == "timeout" for kw in opener.keywords):
        return True
    if (ctx.resolve(opener.func) or "").endswith("create_connection") \
            and len(opener.args) >= 2:
        return True
    if isinstance(recv_call.func.value, ast.Name):
        name = recv_call.func.value.id
        for sub in ast.walk(ctx.tree):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "settimeout"
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == name
                and sub.args
                and not (isinstance(sub.args[0], ast.Constant)
                         and sub.args[0].value is None)
            ):
                return True
    return False


def _unbounded_wait(ctx: FileContext, call: ast.Call) -> Optional[str]:
    """Why ``call`` can block its worker thread forever, or None."""
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if attr in ("wait", "get") and not call.args and not call.keywords:
        # zero-arg wait()/get(): a Condition/Event/Queue/Popen blocking
        # call with no timeout at all (dict.get always has arguments,
        # so mapping reads never match)
        return (
            f"`.{attr}()` has no timeout, so the thread blocks forever "
            "when the notify/put/exit it waits for never comes"
        )
    if attr in _SOCKET_BLOCKING_METHODS and not _socket_has_timeout(ctx, call):
        return (
            f"`.{attr}` on a timeout-less socket blocks forever when the "
            "peer stalls without closing (the half-open gray failure)"
        )
    return None


@rule("SPMD214", "unbounded wait/recv inside a `while True` worker loop")
def check_unbounded_wait_in_worker_loop(ctx: FileContext) -> Iterable[Finding]:
    """A ``while True`` worker loop parked on a zero-timeout blocking
    call — ``cv.wait()``, ``queue.get()``, ``popen.wait()``, or a
    ``recv``/``accept`` on a socket with no timeout anywhere in sight —
    can never observe anything but the event it waits for: a peer that
    stalls without closing (the half-open socket), a producer that died
    mid-hand-off, or a shutdown flag all leave the thread wedged forever,
    unjoinable and invisible to deadlines.  That is exactly the gray
    failure the serving plane's hardening exists to catch, and the fix is
    always the same shape: wait with a timeout inside the loop and
    re-check liveness/deadline on each wakeup (the deadline-aware waits
    in ``serve.procfleet.flush`` / ``serve.wfq.pop``).  Loops that
    visibly track a bound (deadline/timeout/attempt/budget identifiers,
    same exemption as SPMD211) are exempt — the author is already
    watching a clock."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.While):
            continue
        test = node.test
        if not (isinstance(test, ast.Constant) and bool(test.value)):
            continue
        if _loop_mentions_bound(node):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call) or ctx.in_traced_context(sub):
                continue
            why = _unbounded_wait(ctx, sub)
            if why is None:
                continue
            yield ctx.finding(
                "SPMD214", sub,
                f"unbounded blocking wait in a `while True` worker loop "
                f"— {why}",
                hint="wait with a timeout and re-check liveness/deadline "
                "each wakeup (compute the deadline once, wait the "
                "remainder — the `serve.wfq.pop` shape), or bound the "
                "socket with `settimeout`; mark with "
                "`# spmdlint: disable=SPMD214` if blocking forever is "
                "deliberate",
            )


# --------------------------------------------------------------------- #
# SPMD301/302: Pallas tiling and grids                                   #
# --------------------------------------------------------------------- #
@rule("SPMD301", "Pallas BlockSpec tiles must respect the hardware tile grid")
def check_pallas_tiling(ctx: FileContext) -> Iterable[Finding]:
    """Literal BlockSpec dimensions must sit on the TPU tile grid: the
    minor-most block dim a multiple of 128, the second-minor a multiple
    of the dtype's sublane count (8 for f32 — bf16 needs 16, flagged in
    the hint).  Size-1 dims and symbolic dims (``bq``, ``D`` — values
    produced by `_pick_block`-style helpers) are exempt: Mosaic also
    accepts block dims equal to the array dims."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.resolves_to(node.func, "BlockSpec"):
            continue
        shape = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "block_shape":
                shape = kw.value
        if not isinstance(shape, (ast.Tuple, ast.List)) or len(shape.elts) < 2:
            continue
        minor, second = shape.elts[-1], shape.elts[-2]
        if isinstance(minor, ast.Constant) and isinstance(minor.value, int):
            v = minor.value
            if v > 1 and v % 128:
                yield ctx.finding(
                    "SPMD301", node,
                    f"BlockSpec minor dim {v} is not a multiple of the "
                    "128-lane tile",
                    hint="pick a 128-multiple (or exactly the array dim); "
                    "f32 tiles are 8x128, bf16 16x128",
                )
        if isinstance(second, ast.Constant) and isinstance(second.value, int):
            v = second.value
            if v > 1 and v % 8:
                yield ctx.finding(
                    "SPMD301", node,
                    f"BlockSpec second-minor dim {v} is not a multiple of "
                    "the sublane tile (8 for f32, 16 for bf16)",
                    hint="round the block up to the dtype's sublane "
                    "multiple or use the full array dim",
                )


@rule("SPMD302", "pallas_call grids must be static")
def check_pallas_static_grid(ctx: FileContext) -> Iterable[Finding]:
    """The grid is compile-time program structure: building it from
    traced array values (``jnp.*``/``lax.*`` results) either fails to
    lower or silently re-specializes per call."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.resolves_to(node.func, "pallas_call"):
            continue
        grid = None
        for kw in node.keywords:
            if kw.arg == "grid":
                grid = kw.value
        if grid is None:
            continue
        for sub in ast.walk(grid):
            if isinstance(sub, ast.Call):
                dotted = ctx.resolve(sub.func) or ""
                if dotted.startswith(("jax.numpy.", "jax.lax.", "jax.random.")):
                    yield ctx.finding(
                        "SPMD302", sub,
                        f"pallas_call grid uses traced value {dotted!r}",
                        hint="grids must be python ints fixed at trace "
                        "time; derive them from static shapes "
                        "(x.shape[...] // block), not from array values",
                    )


# --------------------------------------------------------------------- #
# SPMD401: jitted() cache-key hygiene                                    #
# --------------------------------------------------------------------- #
_OK_KEY_ATTRS = {
    "dtype", "ndim", "shape", "size", "split", "axis_name", "name",
    "itemsize", "value",
}
_OK_KEY_CALLS = {"str", "int", "float", "bool", "tuple", "len", "repr", "frozenset", "hash"}


def _classify_key_element(ctx: FileContext, el: ast.AST, fn_scope) -> Optional[Tuple[str, str]]:
    """Return (message, hint) when ``el`` is a risky cache-key part."""
    if isinstance(el, ast.Constant):
        return None
    if isinstance(el, (ast.Tuple,)):
        for sub in el.elts:
            bad = _classify_key_element(ctx, sub, fn_scope)
            if bad:
                return bad
        return None
    if isinstance(el, (ast.List, ast.Dict, ast.Set)):
        return (
            "unhashable literal in jitted() key",
            "use a tuple (lists/dicts/sets raise TypeError at lookup)",
        )
    if isinstance(el, ast.Lambda):
        return (
            "lambda in jitted() key",
            "a fresh lambda has a fresh identity every call: the cache "
            "grows one dead entry per call and never hits",
        )
    if isinstance(el, ast.Starred):
        return ("starred element in jitted() key", "splice statically instead")
    if isinstance(el, ast.Name):
        # a name that the enclosing function CALLS is a callable value:
        # bound methods / closures in keys are the ring_map cache leak
        if fn_scope is not None:
            for sub in ast.walk(fn_scope):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == el.id
                ):
                    return (
                        f"callable {el.id!r} in jitted() key",
                        "bound methods and closures are not identity-stable "
                        "across calls (PR-1 ring_map leak); key on stable "
                        "data instead, or gate with _compile.cache_stable() "
                        "and suppress",
                    )
        return None
    if isinstance(el, ast.Attribute):
        if el.attr in _OK_KEY_ATTRS:
            return None
        return (
            f"attribute {ast.unparse(el)!r} in jitted() key may be a bound "
            "method or per-call object",
            "key on plain data (dtype/shape/axis tuples, str(dtype), "
            "comm) — never on methods or arrays",
        )
    if isinstance(el, ast.Call):
        if isinstance(el.func, ast.Name) and el.func.id in _OK_KEY_CALLS:
            return None
        dotted = ctx.resolve(el.func) or ""
        if dotted.startswith(("jax.numpy.", "numpy.", "jax.")):
            return (
                f"array-valued call {dotted!r} in jitted() key",
                "jax arrays are unhashable and never identity-stable; key "
                "on the static parameters that produced the array",
            )
        return (
            f"unvetted call {ast.unparse(el.func)!r} in jitted() key",
            "only str/int/float/bool/tuple/len conversions are known "
            "hashable+stable; hoist anything else into a named static",
        )
    if isinstance(el, (ast.BinOp, ast.UnaryOp, ast.Compare, ast.IfExp, ast.Subscript)):
        return None  # plain data arithmetic: hashable if its parts are
    if isinstance(el, ast.JoinedStr):
        return None
    return None


@rule("SPMD401", "jitted() cache keys: hashable, identity-stable parts only")
def check_jit_cache_keys(ctx: FileContext) -> Iterable[Finding]:
    """Call sites of the op engine's ``jitted(key, make_fn)`` must build
    ``key`` from parts that are hashable AND identity-stable across calls
    — no bound methods, no lambdas/closures, no arrays.  The key must be
    a tuple literal visible at the call site (directly or via one local
    assignment) so this can be audited at all."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.resolves_to(node.func, "jitted"):
            continue
        if not node.args:
            continue
        key = node.args[0]
        anchor = node
        if isinstance(key, ast.Name):
            rec = ctx.lookup(key.id, node)
            if rec is not None and rec[0] == "expr":
                key = rec[1]
        if not isinstance(key, ast.Tuple):
            yield ctx.finding(
                "SPMD401", anchor,
                "jitted() key is not a statically-visible tuple literal",
                hint="build the key as a tuple at (or one assignment above) "
                "the call site so its parts can be audited",
            )
            continue
        if not (key.elts and isinstance(key.elts[0], ast.Constant)
                and isinstance(key.elts[0].value, str)):
            yield ctx.finding(
                "SPMD401", anchor,
                "jitted() key does not start with a namespace string",
                hint="lead with a unique op-name string so two ops can "
                "never collide on structurally-equal parameter tuples",
            )
        enclosing = ctx.enclosing_functions(node)
        fn_scope = enclosing[-1] if enclosing else None
        for el in key.elts:
            bad = _classify_key_element(ctx, el, fn_scope)
            if bad:
                yield ctx.finding("SPMD401", anchor, bad[0], hint=bad[1])


# --------------------------------------------------------------------- #
# SPMD001: suppression hygiene                                          #
# --------------------------------------------------------------------- #
@rule("SPMD001", "inline suppression of a reason-required rule must carry a reason")
def check_suppression_reasons(ctx: FileContext) -> Iterable[Finding]:
    """A ``# spmdlint: disable=...`` comment that silences a rule in
    :data:`~heat_tpu.analysis.rules.REASON_REQUIRED` (SPMD204, SPMD207 —
    the checks whose whole purpose is making a risky pattern deliberate)
    must justify itself with a ``-- reason`` tail::

        # spmdlint: disable=SPMD204 -- bench harness, guards off by design

    A bare suppression (or an empty reason after ``--``) of those rules is
    itself a finding, so silencing the check leaves an audit trail either
    way."""
    from .rules import REASON_REQUIRED

    for lineno, ids, reason in ctx.suppressions():
        gated = sorted(set(ids) & REASON_REQUIRED)
        if gated and not reason:
            anchor = ast.Pass(lineno=lineno, col_offset=0)
            yield ctx.finding(
                "SPMD001", anchor,
                f"suppression of {', '.join(gated)} has no reason",
                hint="append '-- <why this is safe here>' to the "
                "spmdlint: disable comment",
            )
