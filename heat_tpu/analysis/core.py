"""AST walker core: per-file model shared by every checker.

``FileContext`` parses one Python source file and precomputes what the
rules need:

- an import-alias map so ``pl.BlockSpec`` / ``ppermute`` / ``jitted``
  resolve to dotted names regardless of import spelling (relative imports
  are resolved against the file's package position on disk);
- a parent map (child → parent AST node) for enclosing-statement and
  enclosing-function queries;
- per-scope assignment tables (including tuple-unpacking, the
  ``mesh, name = comm.mesh, comm.axis_name`` idiom);
- the set of TRACED functions: anything passed to ``jit`` / ``shard_map``
  / ``pallas_call`` / ``lax.fori_loop``-family / ``vmap``/``grad`` /
  ``heat_tpu.fuse``, decorated with ``jax.jit`` or ``fuse`` (bare or via
  ``partial``), or nested inside a factory handed to the op engine's
  ``jitted``;
- inline-suppression handling (``# spmdlint: disable=SPMD101`` on the
  finding's line or its statement's first line, ``# spmdlint: skip-file``
  in the header).

Checkers receive a context and call :meth:`FileContext.finding`, which
applies inline suppressions and stamps the line-insensitive baseline
fingerprint.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

from .rules import RULES, Finding, all_rules

__all__ = [
    "FileContext", "analyze_contexts", "analyze_file", "analyze_paths",
    "iter_py_files", "norm_relpath", "repo_root_for",
]

#: ``# spmdlint: disable=SPMD101,SPMD202`` with an optional human reason
#: after a ``--`` separator (``disable=SPMD204 -- guards off by design``).
#: Group 1 = the comma-separated rule ids, group 2 = the reason (None when
#: absent, "" when the separator is present but empty).
_SUPPRESS_RE = re.compile(
    r"#\s*spmdlint:\s*disable="
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
    r"(?:\s*--\s*(.*?))?\s*$"
)
_SKIP_FILE_RE = re.compile(r"#\s*spmdlint:\s*skip-file")

#: jax entry points whose function argument (by position) gets traced
_TRACING_CALLS = {
    "jit": (0,),
    "shard_map": (0,),
    "pallas_call": (0,),
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "scan": (0,),
    "cond": (1, 2),
    "vmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    # heat_tpu.fuse: the whole-program compiler traces its function the
    # same way jit does (core/fuse.py) — host syncs inside it are bugs
    "fuse": (0,),
}

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]
_FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def repo_root_for(path: str) -> Optional[str]:
    """Nearest enclosing repo root of ``path``: the first ancestor holding
    a ``.git`` directory, ``pyproject.toml``, or committed spmdlint
    baseline.  None when ``path`` is outside any recognizable repo."""
    d = os.path.dirname(os.path.abspath(path))
    while True:
        if (
            os.path.isdir(os.path.join(d, ".git"))
            or os.path.isfile(os.path.join(d, "pyproject.toml"))
            or os.path.isfile(os.path.join(d, "spmdlint-baseline.json"))
        ):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def norm_relpath(path: str, root: Optional[str] = None) -> str:
    """Canonical finding path: relative to ``root`` (or the file's repo
    root), always ``/``-separated.  ``spmdlint.py heat_tpu``,
    ``./heat_tpu``, and the absolute spelling — from any working
    directory — all map a file to the SAME relpath, so baseline
    fingerprints are path-spelling- and cwd-insensitive."""
    ap = os.path.abspath(path)
    anchor = root or repo_root_for(ap)
    rel = os.path.relpath(ap, anchor) if anchor else os.path.relpath(ap)
    return rel.replace(os.sep, "/")


def _module_name_for(path: str) -> str:
    """Dotted module name from the file's package position on disk (walk
    up while ``__init__.py`` exists).  Fixture files outside any package
    just get their stem."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    name = ".".join(reversed(parts))
    return name[: -len(".__init__")] if name.endswith(".__init__") else name


class FileContext:
    def __init__(self, path: str, source: Optional[str] = None, relpath: Optional[str] = None):
        self.path = path
        self.relpath = (relpath or norm_relpath(path)).replace(os.sep, "/")
        if source is None:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        if os.path.exists(path):
            self.module = _module_name_for(path)
        else:
            # fixture context (source supplied): derive the module from
            # the declared relpath so synthetic multi-file programs still
            # resolve cross-module imports
            name = self.relpath[:-3] if self.relpath.endswith(".py") else self.relpath
            name = name.replace("/", ".")
            if name.endswith(".__init__"):
                name = name[: -len(".__init__")]
            self.module = name or "<fixture>"

        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        self.aliases = self._collect_aliases()
        self.module_names = self._collect_module_names()
        self._scope_assigns: Dict[ast.AST, Dict[str, Tuple]] = {}
        self.traced_fns = self._collect_traced()
        self.skip_file = any(
            _SKIP_FILE_RE.search(ln) for ln in self.lines[:5]
        )

    # ------------------------------------------------------------------ #
    # imports / name resolution                                           #
    # ------------------------------------------------------------------ #
    def _collect_aliases(self) -> Dict[str, str]:
        """local name -> dotted origin (``pl`` -> ``jax.experimental.pallas``).

        ``from x import *`` contributes no aliases directly (the imported
        names are unknowable per-file) but IS recorded in
        :attr:`star_imports` so :meth:`resolve` can fall back to the star
        module for otherwise-unknown names, and the splitflow Program can
        resolve them exactly against the exporting file.  Imports inside
        ``if TYPE_CHECKING:`` blocks are collected like any other — they
        bind the names rules match on, even though they never execute."""
        out: Dict[str, str] = {}
        self.star_imports: List[str] = []
        pkg_parts = self.module.split(".")
        if self.relpath.rsplit("/", 1)[-1] == "__init__.py":
            # a package __init__'s module IS the package: `from . import x`
            # (level 1) must resolve to the package itself, so give the
            # path a synthetic leaf for the level arithmetic to strip
            pkg_parts = pkg_parts + ["__init__"]
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:
                    # resolve `from ..core import x` against this module
                    base = pkg_parts[: max(len(pkg_parts) - node.level, 0)]
                    mod = ".".join(base + ([mod] if mod else []))
                for a in node.names:
                    if a.name == "*":
                        if mod:
                            self.star_imports.append(mod)
                        continue
                    out[a.asname or a.name] = f"{mod}.{a.name}" if mod else a.name
        return out

    def _collect_module_names(self) -> set:
        """Names bound at module scope by defs/classes/assignments (NOT
        imports) — the names a star-import fallback must never shadow."""
        names: set = set()
        for st in self.tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                names.add(st.name)
            elif isinstance(st, ast.Assign):
                for tgt in st.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
            elif isinstance(st, ast.AnnAssign) and isinstance(st.target, ast.Name):
                names.add(st.target.id)
        return names

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with import aliases
        substituted; None for anything else.

        A name with no alias and no module-scope binding in a file with
        exactly ONE ``from x import *`` resolves through that star module
        (the only place it can have come from); with several star imports
        the origin is ambiguous and the bare name is kept."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id)
        if root is None:
            if (
                len(self.star_imports) == 1
                and node.id not in self.module_names
            ):
                root = f"{self.star_imports[0]}.{node.id}"
            else:
                root = node.id
        parts.append(root)
        return ".".join(reversed(parts))

    def resolves_to(self, node: ast.AST, *names: str) -> bool:
        """True when ``node`` resolves to any of ``names`` (matched on the
        full dotted path or any dotted-boundary suffix)."""
        dotted = self.resolve(node)
        if dotted is None:
            return False
        for n in names:
            if dotted == n or dotted.endswith("." + n):
                return True
        return False

    # ------------------------------------------------------------------ #
    # structure queries                                                   #
    # ------------------------------------------------------------------ #
    def enclosing_functions(self, node: ast.AST) -> List[FuncNode]:
        """Function nodes containing ``node``, innermost first."""
        out: List[FuncNode] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_TYPES):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def enclosing_statement(self, node: ast.AST) -> ast.AST:
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parents.get(cur)
        return cur if cur is not None else node

    def qualname(self, node: ast.AST) -> str:
        names = []
        for fn in self.enclosing_functions(node):
            names.append(getattr(fn, "name", "<lambda>"))
        return ".".join(reversed(names)) or "<module>"

    def scope_assignments(self, scope: ast.AST) -> Dict[str, Tuple]:
        """name -> ("expr", value_node) | ("unpack", call_node, index) for
        assignments made DIRECTLY in ``scope`` (nested defs excluded)."""
        cached = self._scope_assigns.get(scope)
        if cached is not None:
            return cached
        table: Dict[str, Tuple] = {}

        def visit(stmts):
            for st in stmts:
                if isinstance(st, _FUNC_TYPES + (ast.ClassDef,)):
                    continue
                if isinstance(st, ast.Assign):
                    self._record_assign(table, st.targets, st.value)
                elif isinstance(st, ast.AnnAssign) and st.value is not None:
                    self._record_assign(table, [st.target], st.value)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(st, field, None)
                    if sub:
                        visit(sub)
                for h in getattr(st, "handlers", []) or []:
                    visit(h.body)

        body = getattr(scope, "body", [])
        visit(body if isinstance(body, list) else [])
        self._scope_assigns[scope] = table
        return table

    @staticmethod
    def _record_assign(table, targets, value):
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                table[tgt.id] = ("expr", value)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                elts = tgt.elts
                if isinstance(value, (ast.Tuple, ast.List)) and len(value.elts) == len(elts):
                    for t, v in zip(elts, value.elts):
                        if isinstance(t, ast.Name):
                            table[t.id] = ("expr", v)
                elif isinstance(value, ast.Call):
                    for i, t in enumerate(elts):
                        if isinstance(t, ast.Name):
                            table[t.id] = ("unpack", value, i)

    def lookup(self, name: str, node: ast.AST) -> Optional[Tuple]:
        """Nearest-scope assignment record for ``name`` visible at
        ``node``: enclosing functions innermost-out, then module level."""
        for scope in self.enclosing_functions(node) + [self.tree]:
            rec = self.scope_assignments(scope).get(name)
            if rec is not None:
                return rec
        return None

    def module_function(self, name: str) -> Optional[ast.FunctionDef]:
        for st in self.tree.body:
            if isinstance(st, ast.FunctionDef) and st.name == name:
                return st
        return None

    def local_function(self, name: str, at: ast.AST) -> Optional[FuncNode]:
        """A def or name-bound lambda named ``name`` visible at ``at``."""
        for scope in self.enclosing_functions(at) + [self.tree]:
            for st in ast.walk(scope) if scope is not self.tree else self.tree.body:
                if isinstance(st, ast.FunctionDef) and st.name == name:
                    return st
            rec = self.scope_assignments(scope).get(name)
            if rec and rec[0] == "expr" and isinstance(rec[1], ast.Lambda):
                return rec[1]
        return None

    # ------------------------------------------------------------------ #
    # traced-function discovery                                           #
    # ------------------------------------------------------------------ #
    def _fn_node_of(self, expr: ast.AST, at: ast.AST) -> Optional[FuncNode]:
        """Resolve a function-valued expression to its def/lambda node."""
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Call):
            # functools.partial(kernel, ...) and decorator-style wrappers
            if self.resolves_to(expr.func, "functools.partial", "partial") and expr.args:
                return self._fn_node_of(expr.args[0], at)
            return None
        if isinstance(expr, ast.Name):
            return self.local_function(expr.id, at)
        return None

    def _collect_traced(self) -> set:
        traced: set = set()

        def mark(fn: Optional[FuncNode]):
            if fn is not None:
                traced.add(fn)

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                dotted = self.resolve(node.func) or ""
                leaf = dotted.rsplit(".", 1)[-1]
                if leaf in _TRACING_CALLS and (
                    "jax" in dotted
                    or leaf in ("shard_map", "pallas_call", "jit", "fuse")
                    or dotted == leaf
                ):
                    for idx in _TRACING_CALLS[leaf]:
                        if idx < len(node.args):
                            mark(self._fn_node_of(node.args[idx], node))
                elif leaf == "jitted" and len(node.args) >= 2:
                    # op-engine factory: make_fn itself runs eagerly at
                    # build time, but every function DEFINED inside it is
                    # the traced program
                    factory = self._fn_node_of(node.args[1], node)
                    if isinstance(factory, ast.Lambda):
                        # lambda: lambda a, b: ... — the inner lambda(s)
                        for sub in ast.walk(factory.body):
                            if isinstance(sub, _FUNC_TYPES):
                                traced.add(sub)
                    elif factory is not None:
                        for sub in ast.walk(factory):
                            if isinstance(sub, _FUNC_TYPES) and sub is not factory:
                                traced.add(sub)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if self.resolves_to(target, "jax.jit", "jit", "fuse"):
                        traced.add(node)
                    elif (
                        isinstance(dec, ast.Call)
                        and self.resolves_to(dec.func, "functools.partial", "partial")
                        and dec.args
                        and self.resolves_to(dec.args[0], "jax.jit", "jit")
                    ):
                        traced.add(node)
        return traced

    def in_traced_context(self, node: ast.AST) -> bool:
        """True when ``node`` executes at trace time: some enclosing
        function is (or is nested in) a traced function."""
        return any(fn in self.traced_fns for fn in self.enclosing_functions(node))

    # ------------------------------------------------------------------ #
    # findings / suppression                                              #
    # ------------------------------------------------------------------ #
    def _suppressed(self, rule_id: str, node: ast.AST) -> bool:
        stmt = self.enclosing_statement(node)
        lines = {getattr(node, "lineno", 0), getattr(stmt, "lineno", 0)}
        # for multiline simple statements (a jitted() call with its key on
        # its own line) accept the pragma anywhere in the span; defs and
        # classes stay first-line-only so a nested suppression cannot
        # accidentally silence a finding anchored at the def itself
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            start = getattr(stmt, "lineno", 0)
            end = getattr(stmt, "end_lineno", start) or start
            lines.update(range(start, end + 1))
        for ln in lines:
            if 1 <= ln <= len(self.lines):
                m = _SUPPRESS_RE.search(self.lines[ln - 1])
                if m and rule_id in [s.strip() for s in m.group(1).split(",")]:
                    return True
        return False

    def suppressions(self) -> List[Tuple[int, List[str], Optional[str]]]:
        """Every inline suppression comment in the file as
        ``(lineno, rule_ids, reason)`` — ``reason`` is None when no ``--``
        separator is present and the (stripped) free text after it
        otherwise.  SPMD001 audits this list for reason-required rules.

        Unlike the fast line-scan in :meth:`_suppressed`, this walks real
        COMMENT tokens, so pragma look-alikes inside string literals
        (lint-test fixtures quoting suppressions) are not reported."""
        out: List[Tuple[int, List[str], Optional[str]]] = []
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [
                (tok.start[0], tok.string)
                for tok in tokens
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = list(enumerate(self.lines, 1))
        for i, text in comments:
            m = _SUPPRESS_RE.search(text)
            if m:
                ids = [s.strip() for s in m.group(1).split(",")]
                reason = m.group(2)
                out.append((i, ids, reason.strip() if reason is not None else None))
        return out

    def finding(
        self, rule_id: str, node: ast.AST, message: str, hint: str = ""
    ) -> Optional[Finding]:
        """Build a Finding at ``node``, honoring inline suppressions."""
        if self.skip_file or self._suppressed(rule_id, node):
            return None
        line = getattr(node, "lineno", 0)
        snippet = self.lines[line - 1].strip() if 1 <= line <= len(self.lines) else ""
        context = f"{self.qualname(node)}::{' '.join(snippet.split())}"
        return Finding(
            rule=rule_id,
            path=self.relpath,
            line=line,
            message=message,
            hint=hint,
            context=context,
        )


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if not d.startswith((".", "__pycache__")))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def _register_all_rules() -> None:
    # imports for the side effect of registering every rule: the per-file
    # checkers and the program-scope splitflow rules (SPMD501-504)
    from . import checkers  # noqa: F401
    from .splitflow import checkers as _sf_checkers  # noqa: F401


def _wanted(r, dynamic: bool, rules: Optional[Sequence[str]]) -> bool:
    if rules is not None and r.id not in rules:
        return False
    return dynamic or not r.dynamic


def analyze_contexts(
    contexts: Sequence[FileContext],
    dynamic: bool = True,
    rules: Optional[Sequence[str]] = None,
    cache=None,
) -> List[Finding]:
    """Run every registered rule over pre-built contexts: file-scope
    rules per context, then the program-scope (splitflow) rules once over
    the whole set.  ``cache`` is an optional
    :class:`heat_tpu.analysis.cache.FindingsCache`; per-file results hit
    it, program-scope results are interprocedural and always recompute."""
    _register_all_rules()
    findings: List[Finding] = []
    live = [ctx for ctx in contexts if not ctx.skip_file]
    file_rules = [r for r in all_rules() if r.scope == "file"]
    for ctx in live:
        cached = cache.get(ctx, dynamic, rules) if cache is not None else None
        if cached is not None:
            findings.extend(cached)
            continue
        per_file: List[Finding] = []
        for r in file_rules:
            if _wanted(r, dynamic, rules):
                per_file.extend(f for f in r.check(ctx) if f is not None)
        if cache is not None:
            cache.put(ctx, dynamic, rules, per_file)
        findings.extend(per_file)
    program_rules = [
        r for r in all_rules()
        if r.scope == "program" and _wanted(r, dynamic, rules)
    ]
    if program_rules and live:
        from .splitflow import build_program

        program = build_program(live)
        for r in program_rules:
            findings.extend(f for f in r.check(program) if f is not None)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def analyze_file(
    path: str,
    source: Optional[str] = None,
    dynamic: bool = True,
    relpath: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    ctx = FileContext(path, source=source, relpath=relpath)
    return analyze_contexts([ctx], dynamic=dynamic, rules=rules)


def analyze_paths(
    paths: Sequence[str],
    dynamic: bool = True,
    root: Optional[str] = None,
    cache=None,
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Analyze every ``.py`` under ``paths``; ``root`` anchors the
    relative paths used in findings and baseline fingerprints (defaulting
    to each file's repo root, so fingerprints do not depend on how the
    path was spelled or where the linter was launched from)."""
    contexts = [
        FileContext(f, relpath=norm_relpath(f, root)) for f in iter_py_files(paths)
    ]
    return analyze_contexts(contexts, dynamic=dynamic, cache=cache, rules=rules)
