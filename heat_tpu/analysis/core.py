"""AST walker core: per-file model shared by every checker.

``FileContext`` parses one Python source file and precomputes what the
rules need:

- an import-alias map so ``pl.BlockSpec`` / ``ppermute`` / ``jitted``
  resolve to dotted names regardless of import spelling (relative imports
  are resolved against the file's package position on disk);
- a parent map (child → parent AST node) for enclosing-statement and
  enclosing-function queries;
- per-scope assignment tables (including tuple-unpacking, the
  ``mesh, name = comm.mesh, comm.axis_name`` idiom);
- the set of TRACED functions: anything passed to ``jit`` / ``shard_map``
  / ``pallas_call`` / ``lax.fori_loop``-family / ``vmap``/``grad`` /
  ``heat_tpu.fuse``, decorated with ``jax.jit`` or ``fuse`` (bare or via
  ``partial``), or nested inside a factory handed to the op engine's
  ``jitted``;
- inline-suppression handling (``# spmdlint: disable=SPMD101`` on the
  finding's line or its statement's first line, ``# spmdlint: skip-file``
  in the header).

Checkers receive a context and call :meth:`FileContext.finding`, which
applies inline suppressions and stamps the line-insensitive baseline
fingerprint.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

from .rules import RULES, Finding, all_rules

__all__ = ["FileContext", "analyze_file", "analyze_paths", "iter_py_files"]

_SUPPRESS_RE = re.compile(r"#\s*spmdlint:\s*disable=([A-Za-z0-9_,\s]+)")
_SKIP_FILE_RE = re.compile(r"#\s*spmdlint:\s*skip-file")

#: jax entry points whose function argument (by position) gets traced
_TRACING_CALLS = {
    "jit": (0,),
    "shard_map": (0,),
    "pallas_call": (0,),
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "scan": (0,),
    "cond": (1, 2),
    "vmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    # heat_tpu.fuse: the whole-program compiler traces its function the
    # same way jit does (core/fuse.py) — host syncs inside it are bugs
    "fuse": (0,),
}

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]
_FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _module_name_for(path: str) -> str:
    """Dotted module name from the file's package position on disk (walk
    up while ``__init__.py`` exists).  Fixture files outside any package
    just get their stem."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    name = ".".join(reversed(parts))
    return name[: -len(".__init__")] if name.endswith(".__init__") else name


class FileContext:
    def __init__(self, path: str, source: Optional[str] = None, relpath: Optional[str] = None):
        self.path = path
        self.relpath = relpath or os.path.relpath(path)
        if source is None:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.module = _module_name_for(path) if os.path.exists(path) else "<fixture>"

        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        self.aliases = self._collect_aliases()
        self._scope_assigns: Dict[ast.AST, Dict[str, Tuple]] = {}
        self.traced_fns = self._collect_traced()
        self.skip_file = any(
            _SKIP_FILE_RE.search(ln) for ln in self.lines[:5]
        )

    # ------------------------------------------------------------------ #
    # imports / name resolution                                           #
    # ------------------------------------------------------------------ #
    def _collect_aliases(self) -> Dict[str, str]:
        """local name -> dotted origin (``pl`` -> ``jax.experimental.pallas``)."""
        out: Dict[str, str] = {}
        pkg_parts = self.module.split(".")
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:
                    # resolve `from ..core import x` against this module
                    base = pkg_parts[: max(len(pkg_parts) - node.level, 0)]
                    mod = ".".join(base + ([mod] if mod else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    out[a.asname or a.name] = f"{mod}.{a.name}" if mod else a.name
        return out

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with import aliases
        substituted; None for anything else."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def resolves_to(self, node: ast.AST, *names: str) -> bool:
        """True when ``node`` resolves to any of ``names`` (matched on the
        full dotted path or any dotted-boundary suffix)."""
        dotted = self.resolve(node)
        if dotted is None:
            return False
        for n in names:
            if dotted == n or dotted.endswith("." + n):
                return True
        return False

    # ------------------------------------------------------------------ #
    # structure queries                                                   #
    # ------------------------------------------------------------------ #
    def enclosing_functions(self, node: ast.AST) -> List[FuncNode]:
        """Function nodes containing ``node``, innermost first."""
        out: List[FuncNode] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_TYPES):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def enclosing_statement(self, node: ast.AST) -> ast.AST:
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parents.get(cur)
        return cur if cur is not None else node

    def qualname(self, node: ast.AST) -> str:
        names = []
        for fn in self.enclosing_functions(node):
            names.append(getattr(fn, "name", "<lambda>"))
        return ".".join(reversed(names)) or "<module>"

    def scope_assignments(self, scope: ast.AST) -> Dict[str, Tuple]:
        """name -> ("expr", value_node) | ("unpack", call_node, index) for
        assignments made DIRECTLY in ``scope`` (nested defs excluded)."""
        cached = self._scope_assigns.get(scope)
        if cached is not None:
            return cached
        table: Dict[str, Tuple] = {}

        def visit(stmts):
            for st in stmts:
                if isinstance(st, _FUNC_TYPES + (ast.ClassDef,)):
                    continue
                if isinstance(st, ast.Assign):
                    self._record_assign(table, st.targets, st.value)
                elif isinstance(st, ast.AnnAssign) and st.value is not None:
                    self._record_assign(table, [st.target], st.value)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(st, field, None)
                    if sub:
                        visit(sub)
                for h in getattr(st, "handlers", []) or []:
                    visit(h.body)

        body = getattr(scope, "body", [])
        visit(body if isinstance(body, list) else [])
        self._scope_assigns[scope] = table
        return table

    @staticmethod
    def _record_assign(table, targets, value):
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                table[tgt.id] = ("expr", value)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                elts = tgt.elts
                if isinstance(value, (ast.Tuple, ast.List)) and len(value.elts) == len(elts):
                    for t, v in zip(elts, value.elts):
                        if isinstance(t, ast.Name):
                            table[t.id] = ("expr", v)
                elif isinstance(value, ast.Call):
                    for i, t in enumerate(elts):
                        if isinstance(t, ast.Name):
                            table[t.id] = ("unpack", value, i)

    def lookup(self, name: str, node: ast.AST) -> Optional[Tuple]:
        """Nearest-scope assignment record for ``name`` visible at
        ``node``: enclosing functions innermost-out, then module level."""
        for scope in self.enclosing_functions(node) + [self.tree]:
            rec = self.scope_assignments(scope).get(name)
            if rec is not None:
                return rec
        return None

    def module_function(self, name: str) -> Optional[ast.FunctionDef]:
        for st in self.tree.body:
            if isinstance(st, ast.FunctionDef) and st.name == name:
                return st
        return None

    def local_function(self, name: str, at: ast.AST) -> Optional[FuncNode]:
        """A def or name-bound lambda named ``name`` visible at ``at``."""
        for scope in self.enclosing_functions(at) + [self.tree]:
            for st in ast.walk(scope) if scope is not self.tree else self.tree.body:
                if isinstance(st, ast.FunctionDef) and st.name == name:
                    return st
            rec = self.scope_assignments(scope).get(name)
            if rec and rec[0] == "expr" and isinstance(rec[1], ast.Lambda):
                return rec[1]
        return None

    # ------------------------------------------------------------------ #
    # traced-function discovery                                           #
    # ------------------------------------------------------------------ #
    def _fn_node_of(self, expr: ast.AST, at: ast.AST) -> Optional[FuncNode]:
        """Resolve a function-valued expression to its def/lambda node."""
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Call):
            # functools.partial(kernel, ...) and decorator-style wrappers
            if self.resolves_to(expr.func, "functools.partial", "partial") and expr.args:
                return self._fn_node_of(expr.args[0], at)
            return None
        if isinstance(expr, ast.Name):
            return self.local_function(expr.id, at)
        return None

    def _collect_traced(self) -> set:
        traced: set = set()

        def mark(fn: Optional[FuncNode]):
            if fn is not None:
                traced.add(fn)

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                dotted = self.resolve(node.func) or ""
                leaf = dotted.rsplit(".", 1)[-1]
                if leaf in _TRACING_CALLS and (
                    "jax" in dotted
                    or leaf in ("shard_map", "pallas_call", "jit", "fuse")
                    or dotted == leaf
                ):
                    for idx in _TRACING_CALLS[leaf]:
                        if idx < len(node.args):
                            mark(self._fn_node_of(node.args[idx], node))
                elif leaf == "jitted" and len(node.args) >= 2:
                    # op-engine factory: make_fn itself runs eagerly at
                    # build time, but every function DEFINED inside it is
                    # the traced program
                    factory = self._fn_node_of(node.args[1], node)
                    if isinstance(factory, ast.Lambda):
                        # lambda: lambda a, b: ... — the inner lambda(s)
                        for sub in ast.walk(factory.body):
                            if isinstance(sub, _FUNC_TYPES):
                                traced.add(sub)
                    elif factory is not None:
                        for sub in ast.walk(factory):
                            if isinstance(sub, _FUNC_TYPES) and sub is not factory:
                                traced.add(sub)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if self.resolves_to(target, "jax.jit", "jit", "fuse"):
                        traced.add(node)
                    elif (
                        isinstance(dec, ast.Call)
                        and self.resolves_to(dec.func, "functools.partial", "partial")
                        and dec.args
                        and self.resolves_to(dec.args[0], "jax.jit", "jit")
                    ):
                        traced.add(node)
        return traced

    def in_traced_context(self, node: ast.AST) -> bool:
        """True when ``node`` executes at trace time: some enclosing
        function is (or is nested in) a traced function."""
        return any(fn in self.traced_fns for fn in self.enclosing_functions(node))

    # ------------------------------------------------------------------ #
    # findings / suppression                                              #
    # ------------------------------------------------------------------ #
    def _suppressed(self, rule_id: str, node: ast.AST) -> bool:
        stmt = self.enclosing_statement(node)
        lines = {getattr(node, "lineno", 0), getattr(stmt, "lineno", 0)}
        # for multiline simple statements (a jitted() call with its key on
        # its own line) accept the pragma anywhere in the span; defs and
        # classes stay first-line-only so a nested suppression cannot
        # accidentally silence a finding anchored at the def itself
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            start = getattr(stmt, "lineno", 0)
            end = getattr(stmt, "end_lineno", start) or start
            lines.update(range(start, end + 1))
        for ln in lines:
            if 1 <= ln <= len(self.lines):
                m = _SUPPRESS_RE.search(self.lines[ln - 1])
                if m and rule_id in [s.strip() for s in m.group(1).split(",")]:
                    return True
        return False

    def finding(
        self, rule_id: str, node: ast.AST, message: str, hint: str = ""
    ) -> Optional[Finding]:
        """Build a Finding at ``node``, honoring inline suppressions."""
        if self.skip_file or self._suppressed(rule_id, node):
            return None
        line = getattr(node, "lineno", 0)
        snippet = self.lines[line - 1].strip() if 1 <= line <= len(self.lines) else ""
        context = f"{self.qualname(node)}::{' '.join(snippet.split())}"
        return Finding(
            rule=rule_id,
            path=self.relpath,
            line=line,
            message=message,
            hint=hint,
            context=context,
        )


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if not d.startswith((".", "__pycache__")))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def analyze_file(
    path: str,
    source: Optional[str] = None,
    dynamic: bool = True,
    relpath: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    # import for the side effect of registering every rule
    from . import checkers  # noqa: F401

    ctx = FileContext(path, source=source, relpath=relpath)
    if ctx.skip_file:
        return []
    findings: List[Finding] = []
    for r in all_rules():
        if rules is not None and r.id not in rules:
            continue
        if r.dynamic and not dynamic:
            continue
        findings.extend(f for f in r.check(ctx) if f is not None)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def analyze_paths(
    paths: Sequence[str], dynamic: bool = True, root: Optional[str] = None
) -> List[Finding]:
    """Analyze every ``.py`` under ``paths``; ``root`` anchors the
    relative paths used in findings and baseline fingerprints."""
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        rel = os.path.relpath(f, root) if root else os.path.relpath(f)
        findings.extend(analyze_file(f, dynamic=dynamic, relpath=rel))
    return findings
