"""splitflow: interprocedural sharding dataflow analysis.

An abstract interpreter over DNDarray split metadata (:mod:`domain`),
driven by a statically-parsed view of the runtime split-semantics
registry (:mod:`registry`), with per-op-kind transfer functions
(:mod:`transfer`) and an interprocedural engine (:mod:`engine`).  Powers
the program-scope rules SPMD501–504 (:mod:`checkers`) and the static
comm-cost report (:mod:`report`) — both fed by the same
:class:`CommEvent` stream, both importable without jax.
"""

from .domain import NOT_ARRAY, Spec, TOP, UNKNOWN, join
from .engine import CommEvent, Program, build_program
from .registry import package_registry, static_registry
from .report import cost_report, render_table
from .summary import layout_summary
from .transfer import OpFact, apply_kind

__all__ = [
    "CommEvent", "NOT_ARRAY", "OpFact", "Program", "Spec", "TOP", "UNKNOWN",
    "apply_kind", "build_program", "cost_report", "join", "layout_summary",
    "package_registry", "render_table", "static_registry",
]
