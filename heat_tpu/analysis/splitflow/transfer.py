"""Transfer functions: op kind × operand specs → result spec + comm facts.

One function per declared op kind (see
:mod:`heat_tpu.core._split_semantics` for the authoritative kind
catalog).  Each mirrors the runtime's split bookkeeping exactly:

- ``binary`` follows ``core/_operations.__binary_op``: the non-None-split
  operand anchors, and two operands split along DIFFERENT axes force a
  hidden ``t2.resplit(t1.split)`` — the implicit-resplit fact SPMD501
  reports.
- ``reduction`` follows ``__reduce_op``: reducing the split axis
  replicates the result, reducing below it shifts the split down.
- ``matmul`` follows ``linalg.basics._result_split_matmul``.
- ``resplit`` IS the layout change; the fact records src → dst so the
  cost report can price it with :mod:`heat_tpu.comm._costs`.

Transfer functions return ``(result, facts)`` where ``result`` is a
:class:`~heat_tpu.analysis.splitflow.domain.Spec` (or a tuple of Specs
for multi-output ops) and each fact is an :class:`OpFact` the engine
stamps with its AST location.  Facts are emitted only on *known* layout
components — ⊤ never produces one (no guessing).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from .domain import NOT_ARRAY, Spec, TOP, UNKNOWN, join_split

__all__ = ["MISSING", "NONLIT", "OpFact", "apply_kind"]


@dataclass
class OpFact:
    """One statically-derived communication/layout fact.

    ``op`` ∈ ``implicit_resplit`` (SPMD501), ``resplit_chain`` (SPMD502),
    ``split_oob`` (SPMD503), ``noop_collective`` (SPMD504), ``resplit``
    (explicit, priced by the cost report), ``reduce`` (collective combine
    of a sharded reduction; recorded, not priced).
    """

    op: str
    src: object = None
    dst: object = None
    shape: Optional[Tuple[int, ...]] = None
    dtype: Optional[str] = None
    note: str = ""


#: argument not present in the call — kind defaults apply (a bare
#: ``x.resplit()`` means axis=None, exactly like the runtime signature)
_MISSING = MISSING = object()

#: argument present but not a static literal — the value is unknown and
#: anything derived from it goes to ⊤ (never to a default)
NONLIT = object()


def _first_array(operands: Sequence[Spec]) -> Spec:
    for s in operands:
        if isinstance(s, Spec) and s.is_array:
            return s
    return NOT_ARRAY


def _is_splits_tuple(v) -> bool:
    return isinstance(v, (tuple, list)) and all(
        g is None or isinstance(g, int) for g in v)


def _promote_split(split, ndim):
    """Canonical form for layout comparison: a 1-D int split promotes to
    its one-hot splits tuple when the rank is known (mirrors
    ``normalize_splits`` in the runtime)."""
    if isinstance(split, int) and ndim is not None:
        tup = [None] * ndim
        tup[split % ndim] = 0
        return tuple(tup)
    if _is_splits_tuple(split):
        return tuple(split)
    return split


def _splits_tuple_issues(tup, ndim, *, mesh_ndim=None) -> List[str]:
    """Static validity problems of a literal splits tuple (SPMD503 fuel).

    ``mesh_ndim`` is the mesh rank to validate entries against; ``None``
    means the target mesh is unknown (a ``comm=`` argument is present)
    and entry values are not checked.
    """
    issues: List[str] = []
    if ndim is not None and len(tup) != ndim:
        issues.append(
            f"splits tuple has {len(tup)} entries for a {ndim}-d array")
    seen = {}
    for d, g in enumerate(tup):
        if g is None:
            continue
        if mesh_ndim is not None and not (-mesh_ndim <= g < mesh_ndim):
            issues.append(
                f"splits[{d}]={g} out of range for a {mesh_ndim}-d mesh")
        if g in seen:
            issues.append(
                f"mesh axis {g} shards both dims {seen[g]} and {d}")
        else:
            seen[g] = d
    return issues


def _shape_after_reduce(shape, axes, keepdims):
    if shape is None or axes is _MISSING:
        return None
    if axes is None:
        return () if not keepdims else tuple(1 for _ in shape)
    axes = {a % len(shape) for a in axes}
    if keepdims:
        return tuple(1 if i in axes else s for i, s in enumerate(shape))
    return tuple(s for i, s in enumerate(shape) if i not in axes)


def _elementwise(x: Spec) -> Tuple[Spec, List[OpFact]]:
    return x, []


def _binary(a: Spec, b: Spec) -> Tuple[Spec, List[OpFact]]:
    if not a.is_array:
        return b, []
    if not b.is_array:
        return a, []
    facts: List[OpFact] = []
    if isinstance(a.split, int):
        out = a.split
        if isinstance(b.split, int) and b.split != a.split:
            # __binary_op auto-reshards t2 to t1's split (hidden traffic)
            facts.append(OpFact(
                "implicit_resplit", src=b.split, dst=a.split,
                shape=b.shape, dtype=b.dtype,
                note="operand splits disagree; right operand is resharded",
            ))
    elif a.split is None:
        out = b.split
    else:  # a ⊤
        out = TOP
    shape = a.shape if a.shape == b.shape else None
    dtype = a.dtype if a.dtype == b.dtype else None
    return Spec(split=out, shape=shape, dtype=dtype,
                ragged=a.ragged or b.ragged), facts


def _reduction(x: Spec, axes, keepdims) -> Tuple[Spec, List[OpFact]]:
    if not x.is_array:
        return NOT_ARRAY, []
    facts: List[OpFact] = []
    keep = keepdims is True
    shape = _shape_after_reduce(x.shape, axes, keep)
    if x.split is TOP:
        return Spec(split=TOP, shape=shape, dtype=x.dtype), facts
    if x.split is None:
        return Spec(split=None, shape=shape, dtype=x.dtype), facts
    if axes is _MISSING:  # axis not statically known
        return Spec(split=TOP, shape=shape, dtype=x.dtype), facts
    if axes is None:
        facts.append(OpFact("reduce", src=x.split, dst=None,
                            shape=x.shape, dtype=x.dtype,
                            note="full reduction of a sharded operand"))
        return Spec(split=None, shape=shape, dtype=x.dtype), facts
    norm = {a % len(x.shape) if x.shape else a for a in axes}
    if x.split in norm:
        facts.append(OpFact("reduce", src=x.split, dst=None,
                            shape=x.shape, dtype=x.dtype,
                            note="reduction along the split axis"))
        split = x.split if keep else None
        return Spec(split=split, shape=shape, dtype=x.dtype), facts
    shift = 0 if keep else sum(1 for a in norm if a < x.split)
    return Spec(split=x.split - shift, shape=shape, dtype=x.dtype), facts


def _matmul(a: Spec, b: Spec) -> Tuple[Spec, List[OpFact]]:
    if not a.is_array or not b.is_array:
        return UNKNOWN, []
    shape = None
    if a.shape is not None and b.shape is not None \
            and len(a.shape) == 2 and len(b.shape) == 2:
        shape = (a.shape[0], b.shape[1])
    dtype = a.dtype if a.dtype == b.dtype else None
    if a.split is TOP or b.split is TOP:
        return Spec(split=TOP, shape=shape, dtype=dtype), []
    if _is_splits_tuple(a.split) or _is_splits_tuple(b.split):
        # grid SUMMA path: two fully 2-D-sharded operands keep the grid
        # layout, and the rank-local schedules — rows-by-r times
        # cols-by-c ("rowcol") and its mirror ("colrow") — commit their
        # product onto the grid without redistributing either operand;
        # anything else over a splits tuple is left unknown
        if a.split == (0, 1) and b.split == (0, 1):
            return Spec(split=(0, 1), shape=shape, dtype=dtype), []
        if a.split == (0, None) and b.split == (None, 1):
            return Spec(split=(0, 1), shape=shape, dtype=dtype), []
        if a.split == (None, 1) and b.split == (0, None):
            return Spec(split=(0, 1), shape=shape, dtype=dtype), []
        return Spec(split=TOP, shape=shape, dtype=dtype), []
    if a.split == 0:
        return Spec(split=0, shape=shape, dtype=dtype), []
    if isinstance(b.split, int):
        if b.shape is not None and b.split == len(b.shape) - 1:
            return Spec(split=b.split, shape=shape, dtype=dtype), []
        if b.shape is None:
            return Spec(split=TOP, shape=shape, dtype=dtype), []
    facts = []
    if a.split == 1 or b.split == 0:
        facts.append(OpFact("reduce", src=a.split if a.split == 1 else b.split,
                            dst=None, shape=shape, dtype=dtype,
                            note="sharded contraction combines partials"))
    return Spec(split=None, shape=shape, dtype=dtype), facts


def _transpose(x: Spec, axes) -> Tuple[Spec, List[OpFact]]:
    if not x.is_array:
        return NOT_ARRAY, []
    shape = None
    if x.shape is not None:
        order = axes if axes not in (None, _MISSING) else tuple(
            reversed(range(len(x.shape)))
        )
        if isinstance(order, (tuple, list)) and len(order) == len(x.shape):
            shape = tuple(x.shape[a] for a in order)
    if not isinstance(x.split, int):
        return Spec(split=x.split, shape=shape, dtype=x.dtype), []
    if axes is _MISSING:
        return Spec(split=TOP, shape=shape, dtype=x.dtype), []
    if axes is None:
        if x.ndim is None:
            return Spec(split=TOP, shape=shape, dtype=x.dtype), []
        return Spec(split=x.ndim - 1 - x.split, shape=shape, dtype=x.dtype), []
    try:
        return Spec(split=list(axes).index(x.split), shape=shape,
                    dtype=x.dtype), []
    except ValueError:
        return Spec(split=TOP, shape=shape, dtype=x.dtype), []


def _reshape(x: Spec, newshape) -> Tuple[Spec, List[OpFact]]:
    if not x.is_array:
        return NOT_ARRAY, []
    shape = tuple(newshape) if isinstance(newshape, (tuple, list)) and all(
        isinstance(s, int) for s in newshape) else None
    if isinstance(x.split, int):
        split = x.split if shape is not None and x.split < len(shape) else (
            0 if shape is not None else TOP)
    else:
        split = x.split
    return Spec(split=split, shape=shape, dtype=x.dtype), []


def _concat(arrays: Sequence[Spec], axis) -> Tuple[Spec, List[OpFact]]:
    splits = [a.split for a in arrays if a.is_array]
    if not splits:
        return UNKNOWN, []
    if any(s is TOP for s in splits):
        split = TOP
    else:
        split = next((s for s in splits if s is not None), None)
    shape = None
    shapes = [a.shape for a in arrays if a.is_array]
    if axis not in (None, _MISSING) and all(s is not None for s in shapes) \
            and shapes and len({s[:axis] + s[axis + 1:] for s in shapes}) == 1:
        cat = sum(s[axis] for s in shapes)
        s0 = list(shapes[0])
        s0[axis] = cat
        shape = tuple(s0)
    dtypes = {a.dtype for a in arrays if a.is_array}
    return Spec(split=split, shape=shape,
                dtype=dtypes.pop() if len(dtypes) == 1 else None), []


def _axis_shift_in(x: Spec, axis) -> Tuple[Spec, List[OpFact]]:
    """stack/expand_dims: a new axis at ``axis`` shifts splits at or
    above it up by one."""
    if not x.is_array:
        return NOT_ARRAY, []
    shape = None
    if x.shape is not None and axis is not _MISSING and axis is not None:
        a = axis % (len(x.shape) + 1)
        shape = x.shape[:a] + (1,) + x.shape[a:]
    if not isinstance(x.split, int):
        return Spec(split=x.split, shape=shape, dtype=x.dtype), []
    if axis is _MISSING or axis is None:
        return Spec(split=TOP, shape=shape, dtype=x.dtype), []
    a = axis if axis >= 0 else (axis % ((x.ndim or 0) + 1))
    return Spec(split=x.split + 1 if a <= x.split else x.split,
                shape=shape, dtype=x.dtype), []


def _squeeze(x: Spec, axis) -> Tuple[Spec, List[OpFact]]:
    if not x.is_array:
        return NOT_ARRAY, []
    if not isinstance(x.split, int):
        return Spec(split=x.split, shape=None, dtype=x.dtype), []
    if axis is _MISSING or axis is None:
        return Spec(split=TOP, shape=None, dtype=x.dtype), []
    a = axis % len(x.shape) if x.shape else axis
    return Spec(split=x.split - 1 if a < x.split else x.split,
                shape=None, dtype=x.dtype), []


def _flatten(x: Spec) -> Tuple[Spec, List[OpFact]]:
    if not x.is_array:
        return NOT_ARRAY, []
    shape = None
    if x.shape is not None:
        n = 1
        for s in x.shape:
            n *= s
        shape = (n,)
    if isinstance(x.split, int):
        split = 0
    else:
        split = x.split
    return Spec(split=split, shape=shape, dtype=x.dtype), []


def _resplit(x: Spec, dst) -> Tuple[Spec, List[OpFact]]:
    if not x.is_array:
        return NOT_ARRAY, []
    facts: List[OpFact] = []
    if dst is _MISSING or dst is NONLIT:
        return Spec(split=TOP, shape=x.shape, dtype=x.dtype), facts
    if isinstance(dst, (tuple, list)):
        if not _is_splits_tuple(dst):
            return Spec(split=TOP, shape=x.shape, dtype=x.dtype), facts
        dst = tuple(dst)
        # the target mesh rank is the comm's, which is not statically
        # known here — check only the mesh-independent invariants
        issues = _splits_tuple_issues(dst, x.ndim, mesh_ndim=None)
        if issues:
            facts.append(OpFact(
                "split_oob", src=x.split, dst=dst,
                shape=x.shape, dtype=x.dtype, note="; ".join(issues),
            ))
            return Spec(split=TOP, shape=x.shape, dtype=x.dtype), facts
        if x.split is not TOP and _promote_split(x.split, x.ndim) == \
                _promote_split(dst, x.ndim):
            facts.append(OpFact(
                "noop_collective", src=x.split, dst=dst,
                shape=x.shape, dtype=x.dtype,
                note="resplit to the layout the value already has",
            ))
        elif x.split is not TOP:
            facts.append(OpFact("resplit", src=x.split, dst=dst,
                                shape=x.shape, dtype=x.dtype))
        return Spec(split=dst, shape=x.shape, dtype=x.dtype,
                    ragged=x.ragged), facts
    if isinstance(dst, int) and x.ndim is not None \
            and not (-x.ndim <= dst < x.ndim):
        facts.append(OpFact(
            "split_oob", src=x.split, dst=dst, shape=x.shape, dtype=x.dtype,
            note=f"axis {dst} out of range for {x.ndim}-d shape {x.shape}",
        ))
        return Spec(split=TOP, shape=x.shape, dtype=x.dtype), facts
    if isinstance(dst, int) and x.ndim is not None:
        dst = dst % x.ndim
    if x.split is not TOP and _promote_split(x.split, x.ndim) == \
            _promote_split(dst, x.ndim):
        facts.append(OpFact(
            "noop_collective", src=x.split, dst=dst,
            shape=x.shape, dtype=x.dtype,
            note="resplit to the layout the value already has",
        ))
    elif x.split is not TOP:
        facts.append(OpFact("resplit", src=x.split, dst=dst,
                            shape=x.shape, dtype=x.dtype))
    return Spec(split=dst, shape=x.shape, dtype=x.dtype,
                ragged=x.ragged), facts


def _factory(shape, split, dtype, splits=_MISSING,
             has_comm=False) -> Tuple[Spec, List[OpFact]]:
    facts: List[OpFact] = []
    shp = None
    if isinstance(shape, int):
        shp = (shape,)
    elif isinstance(shape, (tuple, list)) and all(
            isinstance(s, int) for s in shape):
        shp = tuple(shape)
    if splits is not _MISSING:
        # N-D mesh spelling.  Entries name MESH axes: without an explicit
        # ``comm=`` the array lands on the default 1-D mesh, so any entry
        # other than 0/None is statically out of range (SPMD503).
        if splits is NONLIT or not _is_splits_tuple(splits):
            return Spec(split=TOP, shape=shp, dtype=dtype), facts
        tup = tuple(splits)
        issues = _splits_tuple_issues(
            tup, len(shp) if shp is not None else None,
            mesh_ndim=None if has_comm else 1)
        if issues:
            facts.append(OpFact(
                "split_oob", src=None, dst=tup, shape=shp, dtype=dtype,
                note="; ".join(issues),
            ))
            return Spec(split=TOP, shape=shp, dtype=dtype), facts
        return Spec(split=tup, shape=shp, dtype=dtype), facts
    if split is NONLIT:
        return Spec(split=TOP, shape=shp, dtype=dtype), facts
    sp = split if split is not _MISSING else None
    if isinstance(sp, int) and shp is not None and not (-len(shp) <= sp < len(shp)):
        facts.append(OpFact(
            "split_oob", src=None, dst=sp, shape=shp, dtype=dtype,
            note=f"split={sp} out of range for shape {shp}",
        ))
        sp = TOP
    elif isinstance(sp, int) and shp is not None:
        sp = sp % len(shp)
    return Spec(split=sp, shape=shp, dtype=dtype), facts


def _entry_split0(x: Spec) -> Tuple[Spec, List[OpFact]]:
    """predict-family contract: output rides the input's row sharding
    when the input is row-split, else comes back replicated."""
    if not x.is_array or x.split is TOP:
        return Spec(split=TOP), []
    return Spec(split=0 if x.split == 0 else None, dtype=None), []


def _entry_svd(a: Spec, compute_uv) -> Tuple[object, List[OpFact]]:
    if not a.is_array:
        return UNKNOWN, []
    if compute_uv is False:
        return Spec(split=None, dtype=a.dtype), []
    s_spec = Spec(split=None, dtype=a.dtype)
    if a.split is None:
        return (Spec(split=None, dtype=a.dtype), s_spec,
                Spec(split=None, dtype=a.dtype)), []
    tall = None
    if a.shape is not None and len(a.shape) == 2:
        tall = a.shape[0] >= a.shape[1]
    if _is_splits_tuple(a.split):
        # grid QDWH path: a fully 2-D-sharded tall operand keeps U on
        # the grid with S and V replicated; wide grid inputs factor the
        # transpose and swap, landing V on the grid instead
        if a.split in ((0, 1), (1, 0)):
            if tall is None:
                return (Spec(split=TOP, dtype=a.dtype), s_spec,
                        Spec(split=TOP, dtype=a.dtype)), []
            if tall:
                return (Spec(split=(0, 1), dtype=a.dtype), s_spec,
                        Spec(split=None, dtype=a.dtype)), []
            return (Spec(split=None, dtype=a.dtype), s_spec,
                    Spec(split=(0, 1), dtype=a.dtype)), []
        return (Spec(split=TOP, dtype=a.dtype), s_spec,
                Spec(split=TOP, dtype=a.dtype)), []
    if a.split is TOP or tall is None:
        return (Spec(split=TOP, dtype=a.dtype), s_spec,
                Spec(split=TOP, dtype=a.dtype)), []
    if tall:
        u = Spec(split=0 if a.split == 0 else None, dtype=a.dtype)
        return (u, s_spec, Spec(split=None, dtype=a.dtype)), []
    # wide: factor the transpose and swap U/V
    v = Spec(split=0 if a.split == 1 else None, dtype=a.dtype)
    return (Spec(split=None, dtype=a.dtype), s_spec, v), []


def _entry_qr(a: Spec, calc_q) -> Tuple[object, List[OpFact]]:
    """qr contract: grid ``(0, 1)`` operands pin ``Q`` to ``(0, 1)`` and
    ``R`` to ``(None, 1)`` (each row of the panel hierarchy owns its R
    stripe); on a 1-D mesh Q follows the operand split while R is only
    sharded down the split-1 chain."""
    if not a.is_array:
        return UNKNOWN, []
    if a.split is TOP:
        top = Spec(split=TOP, dtype=a.dtype)
        return (top, top) if calc_q is not False else (NOT_ARRAY, top), []
    if _is_splits_tuple(a.split):
        if a.split == (0, 1):
            q = Spec(split=(0, 1), dtype=a.dtype)
            r = Spec(split=(None, 1), dtype=a.dtype)
        else:
            q = Spec(split=TOP, dtype=a.dtype)
            r = Spec(split=TOP, dtype=a.dtype)
    else:
        q = Spec(split=a.split, dtype=a.dtype)
        r = Spec(split=1 if a.split == 1 else None, dtype=a.dtype)
    if calc_q is False:
        # the runtime returns QR(None, R); R's layout does not depend on
        # whether Q was materialized
        return (NOT_ARRAY, r), []
    return (q, r), []


def apply_kind(kind: str, operands: Sequence[Spec], *,
               axis=_MISSING, shape=_MISSING, split=_MISSING,
               dtype: Optional[str] = None, keepdims=_MISSING,
               compute_uv=_MISSING, calc_q=_MISSING,
               arrays: Sequence[Spec] = (),
               splits=_MISSING, has_comm=False,
               ) -> Tuple[object, List[OpFact]]:
    """Dispatch one op kind over evaluated operand specs.

    ``operands`` are the array-valued operands in call order;
    ``axis``/``shape``/``split`` are statically-extracted literals
    (``_MISSING`` when the argument is absent or not a literal).
    """
    # present-but-dynamic arguments behave like unknown (⊤), never like
    # the kind's default; ``split`` keeps NONLIT so resplit/factory can
    # distinguish "dynamic axis" from "axis omitted"
    if axis is NONLIT:
        axis = _MISSING
    if shape is NONLIT:
        shape = _MISSING
    if keepdims is NONLIT:
        keepdims = _MISSING
    if compute_uv is NONLIT:
        compute_uv = _MISSING
    if calc_q is NONLIT:
        calc_q = _MISSING
    x = _first_array(operands)
    if kind == "elementwise":
        return _elementwise(x)
    if kind == "binary":
        arr = [s for s in operands if isinstance(s, Spec)]
        a = arr[0] if arr else NOT_ARRAY
        b = arr[1] if len(arr) > 1 else NOT_ARRAY
        return _binary(a, b)
    if kind == "reduction":
        ax = axis
        if isinstance(ax, int):
            ax = (ax,)
        elif isinstance(ax, (tuple, list)):
            ax = tuple(ax)
        elif ax is not None and ax is not _MISSING:
            ax = _MISSING
        return _reduction(x, ax, keepdims)
    if kind == "cumulative":
        return x, []
    if kind == "matmul":
        arr = [s for s in operands if isinstance(s, Spec) and s.is_array]
        if len(arr) < 2:
            return UNKNOWN, []
        return _matmul(arr[0], arr[1])
    if kind == "transpose":
        return _transpose(x, axis)
    if kind == "reshape":
        return _reshape(x, shape if shape is not _MISSING else None)
    if kind == "concat":
        ax = axis if isinstance(axis, int) else (0 if axis is _MISSING else axis)
        return _concat(list(arrays) or list(operands), ax)
    if kind == "stack":
        specs = list(arrays) or list(operands)
        joined = _first_array(specs)
        ax = axis if isinstance(axis, int) else 0
        out, facts = _axis_shift_in(joined, ax)
        for s in specs[1:]:
            if s.is_array:
                out = out.with_split(join_split(out.split, _axis_shift_in(s, ax)[0].split))
        return out, facts
    if kind == "expand_dims":
        return _axis_shift_in(x, axis)
    if kind == "squeeze":
        return _squeeze(x, axis)
    if kind == "flatten":
        return _flatten(x)
    if kind == "resplit":
        return _resplit(x, split if split is not _MISSING else (
            axis if axis is not _MISSING else None))
    if kind == "factory":
        return _factory(shape if shape is not _MISSING else None,
                        split, dtype or "float32",
                        splits=splits, has_comm=has_comm)
    if kind == "factory_like":
        if not x.is_array:
            return UNKNOWN, []
        if split is NONLIT:
            return x.widened(), []
        if split is not _MISSING and (split is None or isinstance(split, int)):
            # explicit layout override; allocates in place, no traffic
            return Spec(split=split, shape=x.shape, dtype=x.dtype), []
        return x, []
    if kind == "entry_fit":
        return NOT_ARRAY, []
    if kind == "entry_split0":
        return _entry_split0(x)
    if kind == "entry_svd":
        return _entry_svd(x, compute_uv if compute_uv is not _MISSING else True)
    if kind == "entry_qr":
        return _entry_qr(x, calc_q if calc_q is not _MISSING else True)
    return UNKNOWN, []
