"""Program-scope rules over the splitflow dataflow results.

Each checker receives the whole :class:`~heat_tpu.analysis.splitflow.engine.Program`
and translates the engine's :class:`CommEvent` stream into findings.
All four fire only on *known* layout facts — a ⊤ anywhere in the derived
state produces no event, so these rules cannot guess.

SPMD501 implicit resplit
    ``__binary_op`` silently reshards its right operand when both
    operands are split along different axes.  The program still computes
    the right answer — it just moves a whole operand over the wire on
    every evaluation, invisibly.  Resplit one input once, up front.

SPMD502 redundant resplit chain
    ``x.resplit(1).resplit(0)`` (directly nested, or through a
    single-use temporary) materializes an intermediate layout nothing
    reads.  Each hop is a full collective; go to the final split in one.

SPMD503 split axis out of range
    A literal split/resplit axis outside ``[-ndim, ndim)`` for a value of
    statically-known rank is a guaranteed ``ValueError`` from
    ``sanitize_axis`` at runtime.  A lint finding beats a crash at step
    40k of a training run.

SPMD504 layout collective on a replicated/identical layout
    ``resplit`` to the split the value already has (including
    ``resplit(None)`` of a value inferred replicated) is a no-op
    layout-wise, but still walks the full plan/dispatch path every call.
    Delete it, or gate it on ``x.split != target``.

SPMD505 hand-placed resplit inside an autoshard-wrapped function
    Under ``ht.autoshard`` the solver owns interior layout: every
    non-final placement is searched and may be rerouted or elided, so a
    hand resplit there is at best a request the plan overrides and at
    worst forces an incomplete summary back onto the hand layout.  Keep
    layout out of solved pipelines (or suppress where a pinned hop is
    genuinely intended).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..rules import Finding, rule
from .engine import Program, _fmt_split

__all__ = [
    "check_implicit_resplit", "check_resplit_chain",
    "check_split_out_of_range", "check_noop_collective",
    "check_autoshard_hand_layout",
]


def _findings_for(program: Program, op: str, build) -> List[Finding]:
    out: List[Finding] = []
    seen: set = set()
    for ev in program.events:
        if ev.fact.op != op:
            continue
        message, hint = build(ev)
        f = ev.ctx.finding(_RULE_FOR[op], ev.node, message, hint)
        if f is None:
            continue
        fp = f.fingerprint()
        if fp in seen:
            continue
        seen.add(fp)
        out.append(f)
    return out


_RULE_FOR = {
    "implicit_resplit": "SPMD501",
    "resplit_chain": "SPMD502",
    "split_oob": "SPMD503",
    "noop_collective": "SPMD504",
}


@rule("SPMD501", "implicit resplit: operand splits disagree", scope="program")
def check_implicit_resplit(program: Program) -> Iterable[Finding]:
    def build(ev):
        f = ev.fact
        where = f" of shape {f.shape}" if f.shape is not None else ""
        return (
            f"operands are split along axes {_fmt_split(f.src)} and "
            f"{_fmt_split(f.dst)}; the right operand{where} is implicitly "
            f"resharded to split={_fmt_split(f.dst)} on every evaluation",
            "resplit one operand explicitly (once, outside any loop) so "
            "the wire cost is visible and paid a single time",
        )

    return _findings_for(program, "implicit_resplit", build)


@rule("SPMD502", "redundant resplit chain", scope="program")
def check_resplit_chain(program: Program) -> Iterable[Finding]:
    def build(ev):
        return (
            "resplit of a value that is itself a fresh resplit result; "
            "the intermediate layout is never used",
            "collapse the chain into a single resplit to the final axis — "
            "each hop is a full redistribution collective",
        )

    return _findings_for(program, "resplit_chain", build)


@rule("SPMD503", "split axis statically out of range", scope="program")
def check_split_out_of_range(program: Program) -> Iterable[Finding]:
    def build(ev):
        f = ev.fact
        ndim = len(f.shape) if f.shape is not None else "?"
        if isinstance(f.dst, tuple):
            # splits-tuple spelling: the transfer function records WHICH
            # mesh invariant broke (entry range / arity / duplicate axis)
            return (
                f"invalid splits tuple {_fmt_split(f.dst)} for the "
                f"{ndim}-d value (shape {f.shape}): {f.note}; "
                f"normalize_splits raises ValueError at runtime",
                "each entry names a mesh axis of the target comm "
                "(the default comm's mesh is 1-D — pass comm=grid_comm(...) "
                "for 2-D layouts), at most once, one entry per array dim",
            )
        return (
            f"split axis {_fmt_split(f.dst)} is out of range for the "
            f"{ndim}-d value (shape {f.shape}); sanitize_axis raises "
            f"ValueError at runtime",
            f"use an axis in [-{ndim}, {ndim}) or fix the shape",
        )

    return _findings_for(program, "split_oob", build)


@rule("SPMD504", "layout collective on an already-matching layout",
      scope="program")
def check_noop_collective(program: Program) -> Iterable[Finding]:
    def build(ev):
        f = ev.fact
        what = ("resplit(None) of a value inferred replicated"
                if f.dst is None else
                f"resplit to split={_fmt_split(f.dst)}, the split the value "
                f"already has")
        return (
            f"{what}; the collective is a layout no-op",
            "drop the call, or guard it with `if x.split != target:` when "
            "the input layout varies",
        )

    return _findings_for(program, "noop_collective", build)


def _autoshard_wrapped_defs(ctx) -> List[ast.AST]:
    """Defs the file statically hands to ``ht.autoshard`` — decorated
    (``@ht.autoshard`` / ``@autoshard(donate=True)``) or wrapped inline
    (``solved = ht.autoshard(pipeline)``)."""
    wrapped: List[ast.AST] = []
    seen: set = set()

    def _mark(fn_node):
        if fn_node is not None and id(fn_node) not in seen:
            seen.add(id(fn_node))
            wrapped.append(fn_node)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if ctx.resolves_to(target, "autoshard"):
                    _mark(node)
        elif isinstance(node, ast.Call) and ctx.resolves_to(node.func, "autoshard"):
            if node.args and isinstance(node.args[0], ast.Name):
                _mark(ctx.local_function(node.args[0].id, node))
    return wrapped


@rule("SPMD505", "hand-placed resplit inside an autoshard-wrapped function",
      scope="program")
def check_autoshard_hand_layout(program: Program) -> Iterable[Finding]:
    out: List[Finding] = []
    seen: set = set()
    for ctx in program.contexts:
        wrapped = _autoshard_wrapped_defs(ctx)
        if not wrapped:
            continue
        wrapped_ids = {id(fn) for fn in wrapped}
        for ev in program.events:
            if ev.ctx is not ctx or ev.fact.op not in ("resplit", "noop_collective"):
                continue
            if not any(id(fn) in wrapped_ids for fn in ctx.enclosing_functions(ev.node)):
                continue
            f = ctx.finding(
                "SPMD505", ev.node,
                f"hand-placed resplit to {_fmt_split(ev.fact.dst)} inside an "
                "autoshard-wrapped function; the solver owns interior layout "
                "here and may reroute or elide this hop",
                "let ht.autoshard place the layout (drop the call), or "
                "suppress if this hop is a deliberately pinned placement",
            )
            if f is None:
                continue
            fp = f.fingerprint()
            if fp in seen:
                continue
            seen.add(fp)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line))
    return out
