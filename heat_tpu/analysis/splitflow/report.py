"""Static comm-cost report.

Prices the layout traffic the dataflow engine derived — explicit
resplits and SPMD501 implicit reshards — with the SAME arithmetic the
runtime uses: :mod:`heat_tpu.comm._costs` is loaded by file path
(``importlib`` spec, no package import, no jax), and ``plan_cost`` /
``ring_wire_model`` in there are exactly what ``comm/redistribute.plan``
and ``comm/compressed.wire_model`` delegate to.  The oracle lane asserts
byte-for-byte equality between this report and the runtime telemetry
ledger, so the numbers here are predictions, not estimates.

Only events with statically-known shape AND dtype are priced; everything
else is counted in ``unmodeled_events`` rather than silently dropped.
Output is deterministic: keys sorted, no timestamps — two runs over the
same tree produce identical JSON.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Dict, List, Optional

from .engine import Program, _fmt_split

__all__ = ["cost_report", "load_costs", "render_table"]

#: events that move bytes and are priced with plan_cost; "reduce" events
#: (sharded reductions/contractions) are recorded but combine *results*
#: via jit-compiled collectives outside the resplit ledger, so they are
#: listed, never priced
_PRICED_OPS = ("resplit", "implicit_resplit")

_COSTS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "comm", "_costs.py",
)


def load_costs():
    """The runtime cost model, loaded without importing heat_tpu."""
    spec = importlib.util.spec_from_file_location(
        "heat_tpu_comm_costs_static", _COSTS_PATH
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def cost_report(
    program: Program, mesh: int = 8, precision: Optional[str] = "f32"
) -> Dict:
    """Per-function modeled wire bytes at mesh size ``mesh``.

    ``precision`` mirrors the runtime redistribution policy knob: "f32"
    (the default — no compression) or "auto"/"int8_block"/"bf16", fed to
    ``resolve_mode`` per event exactly like ``plan`` does.
    """
    costs = load_costs()
    functions: Dict[str, Dict] = {}
    unmodeled = 0
    for ev in sorted(
        program.events, key=lambda e: (e.ctx.relpath, e.line, e.fact.op)
    ):
        f = ev.fact
        site = ev.site()
        entry = functions.setdefault(site, {
            "path": ev.ctx.relpath,
            "function": ev.qualname,
            "events": [],
            "modeled_wire_bytes": 0,
            "modeled_exact_bytes": 0,
            "modeled_critical_path_ms": {"serial": 0.0, "overlap": 0.0},
        })
        record = {
            "line": ev.line,
            "op": f.op,
            "src": _fmt_split(f.src),
            "dst": _fmt_split(f.dst),
            "shape": list(f.shape) if f.shape is not None else None,
            "dtype": f.dtype,
        }
        priced = (
            f.op in _PRICED_OPS
            and f.shape is not None
            and f.dtype is not None
            and isinstance(f.src, (int, type(None)))
            and isinstance(f.dst, (int, type(None)))
            and f.src != f.dst
        )
        if priced:
            item = costs.itemsize(f.dtype)
            total = 1
            for s in f.shape:
                total *= s
            mode_for = (
                lambda nbytes: costs.resolve_mode(f.dtype, nbytes, precision)
            )
            plan = costs.plan_cost(
                tuple(f.shape), f.dtype, f.src, f.dst, mesh, mode_for=mode_for
            )
            # time model per schedule: serial rings sum wire + compute per
            # hop, overlapped rings pay max(wire, compute) after a warm-up
            # hop (compute is not statically known — 0 here, so this is
            # the pure wire-bound floor under each schedule)
            hops = sum(1 for s in plan["steps"] if s[0] == "rotate")
            cp = {
                "serial": costs.critical_path_ms(
                    plan["wire_bytes"], hops, overlap=False
                ),
                "overlap": costs.critical_path_ms(
                    plan["wire_bytes"], hops, overlap=True
                ),
            }
            record.update({
                "wire_bytes": plan["wire_bytes"],
                "exact_wire_bytes": plan["exact_wire_bytes"],
                "peak_live_bytes": plan["peak_live_bytes"],
                "mode": plan["mode"],
                "critical_path_ms": cp,
                "monolithic_wire_bytes": costs.monolithic_cost(
                    tuple(f.shape), item, f.src, f.dst, mesh
                )["wire_bytes"],
            })
            entry["modeled_wire_bytes"] += plan["wire_bytes"]
            entry["modeled_exact_bytes"] += plan["exact_wire_bytes"]
            entry["modeled_critical_path_ms"]["serial"] += cp["serial"]
            entry["modeled_critical_path_ms"]["overlap"] += cp["overlap"]
        else:
            record["wire_bytes"] = None
            if f.op in _PRICED_OPS:
                unmodeled += 1
        entry["events"].append(record)
    functions = {k: functions[k] for k in sorted(functions)}
    return {
        "mesh": mesh,
        "precision": precision,
        "cost_model": "heat_tpu/comm/_costs.py",
        "functions": functions,
        "totals": {
            "modeled_wire_bytes": sum(
                e["modeled_wire_bytes"] for e in functions.values()
            ),
            "modeled_exact_bytes": sum(
                e["modeled_exact_bytes"] for e in functions.values()
            ),
            "modeled_critical_path_ms": {
                sched: sum(
                    e["modeled_critical_path_ms"][sched]
                    for e in functions.values()
                )
                for sched in ("serial", "overlap")
            },
            "events": sum(len(e["events"]) for e in functions.values()),
            "unmodeled_events": unmodeled,
        },
    }


def render_table(report: Dict) -> str:
    """Human-readable view of :func:`cost_report` output."""
    lines: List[str] = []
    mesh = report["mesh"]
    lines.append(
        f"static comm-cost report  (mesh={mesh}, "
        f"precision={report['precision']}, model={report['cost_model']})"
    )
    header = f"{'modeled wire':>14}  {'events':>6}  function"
    lines.append(header)
    lines.append("-" * len(header))
    for site, entry in report["functions"].items():
        lines.append(
            f"{entry['modeled_wire_bytes']:>14,}  "
            f"{len(entry['events']):>6}  {site}"
        )
        for ev in entry["events"]:
            wire = f"{ev['wire_bytes']:,}" if ev["wire_bytes"] is not None \
                else "(unmodeled)"
            shape = "x".join(str(s) for s in ev["shape"]) \
                if ev["shape"] else "?"
            lines.append(
                f"{'':>14}  {'':>6}    L{ev['line']}: {ev['op']} "
                f"{ev['src']}→{ev['dst']} {shape} {ev['dtype'] or '?'} "
                f"= {wire} B"
            )
    t = report["totals"]
    lines.append("-" * len(header))
    lines.append(
        f"{t['modeled_wire_bytes']:>14,}  {t['events']:>6}  TOTAL "
        f"({t['unmodeled_events']} unmodeled)"
    )
    return "\n".join(lines)
