"""Per-call layout-transfer summaries: the auto-layout solver's search graph.

:func:`layout_summary` projects one analyzed function's slice of the
:class:`~heat_tpu.analysis.splitflow.engine.Program` event stream into
plain data — the input :class:`heat_tpu.comm._costs.LayoutSolver`
searches.  Each *seam* is one layout-transfer event (explicit
``resplit``, layout no-op, or ``__binary_op``'s implicit reshard) with a
literal shape/dtype, the hand-placed ``src``/``dst`` layouts, and two
pieces of provenance the solver's chain DP needs:

``prev``
    the seam whose result this seam consumes, RECORDED ONLY when that
    intermediate is dead — directly nested
    (``x.resplit(1).resplit(None)``) or a single-use temporary (the
    SPMD502 single-load rule, via :meth:`Program.load_count`).  A dead
    intermediate's placement is the solver's to choose; a live one is
    pinned.
``alternatives``
    the op layer's declared legal placements for this seam's result
    (:func:`heat_tpu.core._split_semantics.layout_alternatives`, a
    dependency-free import), enumerated for the target mesh rank —
    1-D splits or splits tuples.

A summary is ``complete`` only when every seam is modelable (literal
shape, known dtype, int/``None``/tuple layouts) and the function's
layout behavior is statically faithful: no seams under loops or
branches (call-order alignment with the plan would be unsound), no
in-place ``resplit_``, and no calls into local helpers that carry their
own layout traffic (interprocedural solving is future work —
docs/design.md §21).  ``ht.autoshard`` falls back to the hand layout on
incomplete summaries rather than guess.

Everything returned is dicts/tuples on purpose: ``comm/_costs.py`` is
loaded by file path (stdlib-only) and must consume the summary without
importing this package.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, List, Optional

from .engine import CommEvent, Program

__all__ = ["layout_summary"]

_SEMANTICS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "core", "_split_semantics.py",
)
_semantics_mod = None


def _semantics():
    """The op layer's declarations, loaded by file path (no package
    import, no jax) — the same discipline as :func:`report.load_costs`."""
    global _semantics_mod
    if _semantics_mod is None:
        import sys

        spec = importlib.util.spec_from_file_location(
            "heat_tpu_split_semantics_static", _SEMANTICS_PATH
        )
        mod = importlib.util.module_from_spec(spec)
        # registered under the private static name so the dataclass
        # machinery can resolve the module at class-creation time
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _semantics_mod = mod
    return _semantics_mod

#: event ops that become seams, in the engine's emission vocabulary
_SEAM_OPS = ("resplit", "noop_collective", "implicit_resplit")


def _is_layout(x) -> bool:
    if x is None or isinstance(x, int):
        return True
    return isinstance(x, tuple) and all(
        g is None or isinstance(g, int) for g in x
    )


def _bound_name(ctx, node: ast.AST) -> Optional[str]:
    """Name an ``x = <seam>`` statement binds, if the seam IS the whole
    right-hand side (a nested seam has no name of its own)."""
    parent = ctx.parents.get(node)
    if isinstance(parent, ast.Assign) and parent.value is node:
        if len(parent.targets) == 1 and isinstance(parent.targets[0], ast.Name):
            return parent.targets[0].id
    return None


def _operand_expr(node: ast.AST) -> Optional[ast.AST]:
    """The expression a resplit call reads: ``x`` in ``x.resplit(a)`` or
    ``ht.resplit(x, a)``."""
    if not isinstance(node, ast.Call):
        return None
    if isinstance(node.func, ast.Attribute):
        return node.func.value
    if node.args:
        return node.args[0]
    return None


def _under_control_flow(ctx, node: ast.AST, fn: ast.AST) -> bool:
    cur = ctx.parents.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, (ast.For, ast.While, ast.If, ast.Try)):
            return True
        cur = ctx.parents.get(cur)
    return False


def _assign_count(fn: ast.AST, name: str) -> int:
    n = 0
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    n += 1
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            t = node.target
            if isinstance(t, ast.Name) and t.id == name:
                n += 1
    return n


def _fn_def(program: Program, ctx, qualname: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef):
            if Program._qual_of_def(ctx, node) == qualname:
                return node
    return None


def _callee_qualnames(program: Program, ctx, fn: ast.FunctionDef) -> List[str]:
    """Qualnames of local defs transitively reachable from ``fn`` by
    direct name calls (the summary's helper-traffic guard)."""
    out: List[str] = []
    seen = {fn.name}
    work = [fn]
    while work:
        cur = work.pop()
        for node in ast.walk(cur):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = ctx.module_function(node.func.id)
                if callee is not None and callee.name not in seen:
                    seen.add(callee.name)
                    out.append(Program._qual_of_def(ctx, callee))
                    work.append(callee)
    return out


def layout_summary(
    program: Program,
    qualname: str,
    *,
    module: Optional[str] = None,
    mesh_ndim: int = 1,
) -> Dict:
    """Export ``qualname``'s layout-transfer summary from ``program``.

    ``mesh_ndim`` selects the alternatives spelling (1 → int splits,
    N → splits tuples).  See the module docstring for the seam schema
    and the ``complete`` contract.
    """
    layout_alternatives = _semantics().layout_alternatives

    events: List[CommEvent] = [
        ev for ev in program.events
        if ev.qualname == qualname
        and (module is None or ev.ctx.module == module)
        and ev.fact.op in _SEAM_OPS
    ]
    events.sort(key=lambda ev: (
        ev.line, getattr(ev.node, "col_offset", 0), ev.fact.op,
    ))
    notes: List[str] = []
    complete = True

    ctx = events[0].ctx if events else None
    if ctx is None:
        for c in program.contexts:
            if module is not None and c.module != module:
                continue
            if _fn_def(program, c, qualname) is not None:
                ctx = c
                break
    if ctx is None:
        return {
            "function": qualname, "module": module, "path": None,
            "complete": False, "notes": [f"no analyzed def for {qualname!r}"],
            "seams": (),
        }
    fn = _fn_def(program, ctx, qualname)
    if fn is None:
        return {
            "function": qualname, "module": ctx.module, "path": ctx.relpath,
            "complete": False, "notes": [f"no def node for {qualname!r}"],
            "seams": (),
        }

    event_nodes = {id(ev.node) for ev in events}
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "resplit_":
                complete = False
                notes.append(
                    f"L{node.lineno}: in-place resplit_ rebinds layout "
                    "behind the summary's back"
                )
            elif node.func.attr == "resplit" and id(node) not in event_nodes:
                # the engine derived no layout fact for this call (dynamic
                # axis, unknown operand layout): the summary cannot see
                # all of the function's traffic, so it must not be solved
                complete = False
                notes.append(
                    f"L{node.lineno}: resplit with no statically derived "
                    "layout fact"
                )
    helper_quals = _callee_qualnames(program, ctx, fn)
    if helper_quals:
        noisy = sorted({
            ev.qualname for ev in program.events
            if ev.ctx is ctx and ev.qualname in helper_quals
            and ev.fact.op in _SEAM_OPS
        })
        if noisy:
            complete = False
            notes.append(
                "local helper(s) carry their own layout traffic: "
                + ", ".join(noisy)
            )
    oob = [
        ev for ev in program.events
        if ev.qualname == qualname and ev.ctx is ctx
        and ev.fact.op == "split_oob"
    ]
    if oob:
        complete = False
        notes.append("statically invalid split axis (SPMD503) in this function")

    seams: List[Dict] = []
    node_to_index: Dict[int, int] = {}
    var_to_index: Dict[str, int] = {}
    for i, ev in enumerate(events):
        f = ev.fact
        shape = f.shape
        modeled = (
            shape is not None
            and all(isinstance(s, int) for s in shape)
            and isinstance(f.dtype, str)
            and _is_layout(f.src) and _is_layout(f.dst)
        )
        if not modeled:
            complete = False
            notes.append(
                f"L{ev.line}: {f.op} with statically unknown "
                "shape/dtype/layout"
            )
        if _under_control_flow(ctx, ev.node, fn):
            complete = False
            notes.append(
                f"L{ev.line}: {f.op} under control flow — call order "
                "cannot be aligned with a static plan"
            )
        explicit = f.op in ("resplit", "noop_collective")
        var = _bound_name(ctx, ev.node) if explicit else None
        ndim = len(shape) if shape is not None else 0
        seam = {
            "index": i,
            "line": ev.line,
            "op": f.op,
            "shape": tuple(shape) if shape is not None else None,
            "dtype": f.dtype,
            "src": f.src,
            "dst": f.dst,
            "var": var,
            "pinned": True,
            "prev": None,
            "alternatives": (
                layout_alternatives("resplit", ndim, mesh_ndim)
                if explicit and modeled else ()
            ),
        }
        seams.append(seam)
        node_to_index[id(ev.node)] = i
        if var is not None:
            var_to_index[var] = i  # latest binding wins, in program order

        if explicit:
            operand = _operand_expr(ev.node)
            prev_i: Optional[int] = None
            if isinstance(operand, ast.Call) and id(operand) in node_to_index:
                prev_i = node_to_index[id(operand)]  # nested: dead by construction
            elif isinstance(operand, ast.Name):
                cand = var_to_index.get(operand.id)
                if (
                    cand is not None and cand != i
                    and program.load_count(ctx, fn, operand.id) == 1
                    and _assign_count(fn, operand.id) == 1
                ):
                    prev_i = cand
            if prev_i is not None and seams[prev_i]["op"] != "implicit_resplit":
                seam["prev"] = prev_i
                seams[prev_i]["pinned"] = False

    return {
        "function": qualname,
        "module": ctx.module,
        "path": ctx.relpath,
        "complete": complete,
        "notes": notes,
        "seams": tuple(seams),
    }
