"""Abstract domain for sharding states.

The runtime invariant this mirrors: a DNDarray is split along at most ONE
axis (``split ∈ {None, 0..ndim-1}``) and its at-rest buffer may be padded
along a ragged split axis.  The abstract value adds ⊤ ("could be
anything") so the dataflow engine can stay sound where it cannot prove a
layout, and optionally carries the static shape/dtype so the comm-cost
report can price layout changes with the exact arithmetic of
:mod:`heat_tpu.comm._costs`.

Lattice (on the ``split`` component)::

            ⊤  (unknown)
          / | \\
      None  0  1  ...     (known layouts)

``join`` goes UP (toward ⊤) — merging two control-flow paths that commit
different layouts yields "unknown", never a wrong concrete guess.  Rules
fire only on *known* facts, so ⊤ silences them; the oracle lane keeps the
engine honest about how often it reaches ⊤ on real pipelines (it must
not, for the supported op surface).

The lattice height is 2, so every loop fixpoint converges in at most two
body passes — the engine exploits that bound directly.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

__all__ = ["NOT_ARRAY", "Spec", "TOP", "UNKNOWN", "join", "join_split"]


class _Top:
    """Singleton ⊤ for the split component (distinct from None, which is
    the *known* replicated layout)."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "⊤"


TOP = _Top()


@dataclass(frozen=True)
class Spec:
    """Abstract sharding state of one value.

    ``split``
        ``None`` (known replicated), an ``int`` axis (known split), or
        :data:`TOP` (unknown).
    ``shape`` / ``dtype``
        Static global shape and canonical dtype name when the engine
        could prove them (tuple literals reaching a factory call), else
        None.  Only used for costing and range checks — never required.
    ``ragged``
        True when the split axis is known not to divide evenly (the
        at-rest buffer is padded).
    ``is_array``
        False for abstract values that are *not* DNDarrays (estimators,
        scalars, plans); transfer functions ignore those operands.
    """

    split: object = TOP
    shape: Optional[Tuple[int, ...]] = None
    dtype: Optional[str] = None
    ragged: bool = False
    is_array: bool = True

    @property
    def known(self) -> bool:
        return self.split is not TOP

    @property
    def ndim(self) -> Optional[int]:
        return len(self.shape) if self.shape is not None else None

    def with_split(self, split) -> "Spec":
        return replace(self, split=split)

    def widened(self) -> "Spec":
        return replace(self, split=TOP)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        bits = [f"split={self.split!r}" if self.known else "split=⊤"]
        if self.shape is not None:
            bits.append(f"shape={self.shape}")
        if self.dtype is not None:
            bits.append(f"dtype={self.dtype}")
        if self.ragged:
            bits.append("ragged")
        if not self.is_array:
            bits = ["non-array"]
        return f"Spec({', '.join(bits)})"


#: the all-unknown array value — what the engine assumes for function
#: parameters with no call-site information
UNKNOWN = Spec()

#: abstract value for non-DNDarray objects (estimators, scalars, shapes)
NOT_ARRAY = Spec(split=TOP, is_array=False)


def join_split(a, b):
    """Least upper bound of two split components."""
    if a is TOP or b is TOP:
        return TOP
    return a if a == b else TOP


def join(a: Spec, b: Spec) -> Spec:
    """Least upper bound of two abstract values (per-component)."""
    if a is b:
        return a
    if not a.is_array and not b.is_array:
        return NOT_ARRAY
    return Spec(
        split=join_split(a.split, b.split),
        shape=a.shape if a.shape == b.shape else None,
        dtype=a.dtype if a.dtype == b.dtype else None,
        ragged=a.ragged or b.ragged,
        is_array=True,
    )
