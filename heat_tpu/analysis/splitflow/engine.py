"""Interprocedural sharding-dataflow engine.

``build_program(contexts)`` runs an abstract interpretation of every
analyzed file over the :mod:`domain` lattice:

- module bodies execute first (factory calls at module scope bind
  concrete Specs);
- every function/method is then analyzed once with all-⊤ parameters — the
  "open-world" pass that guarantees coverage;
- every *call site* whose callee resolves to an analyzed def triggers a
  summary computation with the caller's argument Specs — the
  interprocedural pass that recovers precision through helpers, across
  modules, through ``comm/__init__``-style re-exports and single-star
  imports (resolution rides :class:`~heat_tpu.analysis.core.FileContext`'s
  alias machinery plus the Program-level export chain).

Summaries are memoized on ``(function, argument layout key)`` with a
recursion guard, loops run to fixpoint (two joined passes — the lattice
has height 2), and branches join.  Ops with declared split semantics
(:mod:`registry`) are dispatched through :mod:`transfer`, which also
yields :class:`~heat_tpu.analysis.splitflow.transfer.OpFact` records; the
engine stamps those with their AST site into :class:`CommEvent` — the
single feed for the SPMD501–504 rules and the comm-cost report.
"""

from __future__ import annotations

import ast
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import FileContext
from .domain import NOT_ARRAY, Spec, TOP, UNKNOWN, join
from .registry import StaticSem, static_registry
from .transfer import MISSING, NONLIT, OpFact, apply_kind

__all__ = ["CommEvent", "Program", "build_program"]

_DTYPE_NAMES = {
    "bool", "int8", "uint8", "int16", "uint16", "int32", "uint32",
    "int64", "uint64", "float16", "bfloat16", "float32", "float64",
    "complex64", "complex128",
}

#: kinds that may fire with no array operand (they CREATE the array), so
#: the "some operand must already be a DNDarray" guard is replaced by a
#: "the callee must resolve into heat_tpu" guard
_CREATION_KINDS = {"factory"}

_MAX_CALL_DEPTH = 24


@dataclass
class CommEvent:
    """One :class:`OpFact` stamped with where it happened."""

    ctx: FileContext
    node: ast.AST
    qualname: str
    fact: OpFact

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 0)

    def site(self) -> str:
        return f"{self.ctx.relpath}::{self.qualname}"


def _fmt_split(s) -> str:
    if s is TOP:
        return "⊤"
    if s is None:
        return "None"
    if isinstance(s, tuple):
        return "(" + ", ".join(_fmt_split(g) for g in s) + ")"
    return str(s)


class Program:
    """Whole-analysis view handed to program-scope rules.

    Attributes of interest:

    ``events``
        every :class:`CommEvent` the interpreter derived, deduplicated by
        (file, AST site, fact identity);
    ``fn_specs`` / ``fn_envs``
        per-function return Spec and final local environment from the
        open-world pass, keyed ``(module, qualname)`` — what the oracle
        lane compares against runtime metadata;
    ``module_envs``
        final module-scope environment per context.
    """

    def __init__(self, contexts: Sequence[FileContext]):
        self.contexts = list(contexts)
        self.by_module: Dict[str, FileContext] = {}
        for ctx in self.contexts:
            self.by_module.setdefault(ctx.module, ctx)
        self.registry: Dict[str, StaticSem] = static_registry(
            ctx.tree for ctx in self.contexts
        )
        self.events: List[CommEvent] = []
        self._event_keys: set = set()
        self.module_envs: Dict[FileContext, Dict[str, object]] = {}
        self.fn_specs: Dict[Tuple[str, str], object] = {}
        self.fn_envs: Dict[Tuple[str, str], Dict[str, object]] = {}
        self._summaries: Dict[Tuple[int, tuple], object] = {}
        self._in_progress: set = set()
        self._load_counts: Dict[int, Counter] = {}
        self._run()

    # ------------------------------------------------------------------ #
    # top-level passes                                                    #
    # ------------------------------------------------------------------ #
    def _run(self) -> None:
        for ctx in self.contexts:
            interp = _Interp(self, ctx, fn=None, env={})
            interp.exec_block(ctx.tree.body)
            self.module_envs[ctx] = interp.env
        for ctx in self.contexts:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.FunctionDef):
                    self._open_world(ctx, node)

    def _open_world(self, ctx: FileContext, fn: ast.FunctionDef) -> None:
        env: Dict[str, object] = {}
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        for i, name in enumerate(names):
            # `self`/`cls` in a method position is the estimator, not data
            if i == 0 and name in ("self", "cls") and isinstance(
                    ctx.parents.get(fn), ast.ClassDef):
                env[name] = NOT_ARRAY
            else:
                env[name] = UNKNOWN
        if args.vararg:
            env[args.vararg.arg] = NOT_ARRAY
        if args.kwarg:
            env[args.kwarg.arg] = NOT_ARRAY
        interp = _Interp(self, ctx, fn=fn, env=env)
        interp.exec_block(fn.body)
        qual = self._qual_of_def(ctx, fn)
        self.fn_specs[(ctx.module, qual)] = interp.return_spec()
        self.fn_envs[(ctx.module, qual)] = interp.env

    @staticmethod
    def _qual_of_def(ctx: FileContext, fn: ast.FunctionDef) -> str:
        names = [fn.name]
        cur = ctx.parents.get(fn)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.append(cur.name)
            elif isinstance(cur, ast.ClassDef):
                names.append(cur.name)
            cur = ctx.parents.get(cur)
        return ".".join(reversed(names))

    # ------------------------------------------------------------------ #
    # events                                                              #
    # ------------------------------------------------------------------ #
    def record(self, ctx: FileContext, node: ast.AST, fact: OpFact) -> None:
        key = (
            ctx.relpath, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), fact.op,
            _fmt_split(fact.src), _fmt_split(fact.dst), fact.shape,
        )
        if key in self._event_keys:
            return
        self._event_keys.add(key)
        self.events.append(CommEvent(ctx, node, ctx.qualname(node), fact))

    # ------------------------------------------------------------------ #
    # interprocedural resolution                                          #
    # ------------------------------------------------------------------ #
    def resolve_def(
        self, dotted: str, depth: int = 0
    ) -> Optional[Tuple[FileContext, ast.FunctionDef]]:
        """Find the analyzed def a dotted name ultimately refers to,
        chasing re-export chains (``heat_tpu.comm.plan`` →
        ``comm/__init__`` alias → ``heat_tpu.comm.redistribute.plan``)
        and star-exports."""
        if depth > 8 or not dotted:
            return None
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            ctx = self.by_module.get(mod)
            if ctx is None:
                continue
            rest = parts[cut:]
            leaf = rest[0]
            if len(rest) == 1:
                fn = ctx.module_function(leaf)
                if fn is not None:
                    return ctx, fn
            target = ctx.aliases.get(leaf)
            if target is not None and target != dotted:
                return self.resolve_def(".".join([target] + rest[1:]), depth + 1)
            if leaf not in ctx.module_names:
                for star in ctx.star_imports:
                    hit = self.resolve_def(".".join([star] + rest), depth + 1)
                    if hit is not None:
                        return hit
            return None
        return None

    def resolve_class(self, dotted: str, depth: int = 0) -> bool:
        """True when the dotted name refers to an analyzed class (its
        constructor yields an estimator, not an array)."""
        if depth > 8 or not dotted:
            return False
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            ctx = self.by_module.get(".".join(parts[:cut]))
            if ctx is None:
                continue
            rest = parts[cut:]
            leaf = rest[0]
            if len(rest) == 1:
                for st in ctx.tree.body:
                    if isinstance(st, ast.ClassDef) and st.name == leaf:
                        return True
            target = ctx.aliases.get(leaf)
            if target is not None and target != dotted:
                return self.resolve_class(".".join([target] + rest[1:]), depth + 1)
            return False
        return False

    # ------------------------------------------------------------------ #
    # summaries                                                           #
    # ------------------------------------------------------------------ #
    @staticmethod
    def _arg_key(spec) -> object:
        if isinstance(spec, tuple):
            return tuple(Program._arg_key(s) for s in spec)
        if not isinstance(spec, Spec):
            return "?"
        return (
            "A" if spec.is_array else "O",
            _fmt_split(spec.split), spec.shape, spec.dtype,
        )

    def summarize(
        self,
        ctx: FileContext,
        fn: ast.AST,
        argspecs: Sequence[object],
        kwargspecs: Optional[Dict[str, object]] = None,
        depth: int = 0,
    ) -> object:
        """Return Spec of ``fn`` under the given argument layouts."""
        key = (id(fn), tuple(self._arg_key(a) for a in argspecs),
               tuple(sorted(
                   (k, self._arg_key(v)) for k, v in (kwargspecs or {}).items()
               )))
        if key in self._summaries:
            return self._summaries[key]
        if key in self._in_progress or depth > _MAX_CALL_DEPTH:
            return UNKNOWN
        self._in_progress.add(key)
        try:
            env: Dict[str, object] = {}
            args = getattr(fn, "args", None)
            if args is not None:
                pos = [a.arg for a in args.posonlyargs + args.args]
                for name, spec in zip(pos, argspecs):
                    env[name] = spec
                for name in pos[len(argspecs):]:
                    env[name] = (kwargspecs or {}).get(name, NOT_ARRAY)
                for a in args.kwonlyargs:
                    env[a.arg] = (kwargspecs or {}).get(a.arg, NOT_ARRAY)
                if args.vararg:
                    env[args.vararg.arg] = NOT_ARRAY
                if args.kwarg:
                    env[args.kwarg.arg] = NOT_ARRAY
            interp = _Interp(self, ctx, fn=fn, env=env, depth=depth + 1)
            if isinstance(fn, ast.Lambda):
                result = interp.eval(fn.body)
            else:
                interp.exec_block(fn.body)
                result = interp.return_spec()
            self._summaries[key] = result
            return result
        finally:
            self._in_progress.discard(key)

    def load_count(self, ctx: FileContext, fn: Optional[ast.AST], name: str) -> int:
        """How many times ``name`` is LOADED inside ``fn`` (for the
        single-use leg of resplit-chain detection)."""
        scope = fn if fn is not None else ctx.tree
        counts = self._load_counts.get(id(scope))
        if counts is None:
            counts = Counter()
            for node in ast.walk(scope):
                if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                    counts[node.id] += 1
            self._load_counts[id(scope)] = counts
        return counts[name]


class _Interp:
    """Abstract interpreter for one function (or module) body."""

    def __init__(self, program: Program, ctx: FileContext, fn, env, depth=0):
        self.program = program
        self.ctx = ctx
        self.fn = fn
        self.env: Dict[str, object] = env
        self.depth = depth
        self.returns: List[object] = []
        #: name -> resplit Call node that produced its current value
        #: (provenance for SPMD502 chain detection)
        self.resplit_origin: Dict[str, ast.Call] = {}

    # ------------------------------------------------------------------ #
    # statements                                                          #
    # ------------------------------------------------------------------ #
    def exec_block(self, stmts: Sequence[ast.stmt]) -> None:
        for st in stmts:
            self.exec_stmt(st)

    def exec_stmt(self, st: ast.stmt) -> None:
        if isinstance(st, ast.Assign):
            val = self.eval(st.value)
            for tgt in st.targets:
                self._bind(tgt, val, st.value)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._bind(st.target, self.eval(st.value), st.value)
        elif isinstance(st, ast.AugAssign):
            cur = self.eval(st.target) if isinstance(st.target, ast.Name) else UNKNOWN
            rhs = self.eval(st.value)
            out, facts = apply_kind("binary", [_as_spec(cur), _as_spec(rhs)])
            self._emit(st, facts)
            self._bind(st.target, out, st)
        elif isinstance(st, ast.Expr):
            self.eval(st.value)
        elif isinstance(st, ast.Return):
            self.returns.append(
                self.eval(st.value) if st.value is not None else NOT_ARRAY
            )
        elif isinstance(st, ast.If):
            self.eval(st.test)
            then_env, then_org = dict(self.env), dict(self.resplit_origin)
            self.exec_block(st.body)
            then_env, self.env = self.env, then_env
            then_org, self.resplit_origin = self.resplit_origin, then_org
            self.exec_block(st.orelse)
            self.env = _join_envs(self.env, then_env)
            self.resplit_origin = {
                k: v for k, v in self.resplit_origin.items()
                if then_org.get(k) is v
            }
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self.eval(st.iter)
            self._bind(st.target, UNKNOWN, st.iter)
            self._fixpoint(st.body)
            self.exec_block(st.orelse)
        elif isinstance(st, ast.While):
            self.eval(st.test)
            self._fixpoint(st.body)
            self.exec_block(st.orelse)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                val = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, val, item.context_expr)
            self.exec_block(st.body)
        elif isinstance(st, ast.Try):
            pre = dict(self.env)
            self.exec_block(st.body)
            merged = self.env
            for handler in st.handlers:
                self.env = dict(pre)
                self.exec_block(handler.body)
                merged = _join_envs(merged, self.env)
            self.env = merged
            self.exec_block(st.orelse)
            self.exec_block(st.finalbody)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            self.env[st.name] = NOT_ARRAY
        elif isinstance(st, (ast.Import, ast.ImportFrom)):
            pass  # alias resolution rides FileContext
        elif isinstance(st, (ast.Assert, ast.Raise, ast.Delete, ast.Global,
                             ast.Nonlocal, ast.Pass, ast.Break, ast.Continue)):
            pass

    def _fixpoint(self, body: Sequence[ast.stmt]) -> None:
        # lattice height 2: two joined passes reach the loop fixpoint
        for _ in range(2):
            before = dict(self.env)
            self.exec_block(body)
            self.env = _join_envs(before, self.env)
        self.resplit_origin.clear()

    def _bind(self, tgt: ast.AST, val: object, value_node: ast.AST) -> None:
        if isinstance(tgt, ast.Name):
            self.env[tgt.id] = val
            self.resplit_origin.pop(tgt.id, None)
            if isinstance(value_node, ast.Call) and self._call_kind(
                    value_node) == "resplit":
                self.resplit_origin[tgt.id] = value_node
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            elts = tgt.elts
            vals = val if isinstance(val, tuple) and len(val) == len(elts) \
                else [UNKNOWN] * len(elts)
            for t, v in zip(elts, vals):
                self._bind(t, v, value_node)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, NOT_ARRAY, value_node)
        # attribute/subscript targets: no tracked binding

    def return_spec(self) -> object:
        if not self.returns:
            return NOT_ARRAY
        out = self.returns[0]
        for r in self.returns[1:]:
            if isinstance(out, tuple) and isinstance(r, tuple) \
                    and len(out) == len(r):
                out = tuple(join(_as_spec(a), _as_spec(b))
                            for a, b in zip(out, r))
            else:
                out = join(_as_spec(out), _as_spec(r))
        return out

    # ------------------------------------------------------------------ #
    # expressions                                                         #
    # ------------------------------------------------------------------ #
    def eval(self, node: Optional[ast.AST]) -> object:
        if node is None:
            return NOT_ARRAY
        if isinstance(node, ast.Name):
            val = self.env.get(node.id)
            if val is not None:
                return val
            menv = self.program.module_envs.get(self.ctx)
            if menv is not None and node.id in menv and menv is not self.env:
                return menv[node.id]
            return NOT_ARRAY
        if isinstance(node, ast.Constant):
            return NOT_ARRAY
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self.eval(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, ast.BinOp):
            a, b = self.eval(node.left), self.eval(node.right)
            kind = "matmul" if isinstance(node.op, ast.MatMult) else "binary"
            out, facts = apply_kind(kind, [_as_spec(a), _as_spec(b)])
            self._emit(node, facts)
            return out
        if isinstance(node, ast.Compare):
            a = self.eval(node.left)
            b = self.eval(node.comparators[0]) if node.comparators else NOT_ARRAY
            out, facts = apply_kind("binary", [_as_spec(a), _as_spec(b)])
            self._emit(node, facts)
            return out
        if isinstance(node, ast.BoolOp):
            specs = [_as_spec(self.eval(v)) for v in node.values]
            out = specs[0]
            for s in specs[1:]:
                out = join(out, s)
            return out
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand)
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            return join(_as_spec(self.eval(node.body)),
                        _as_spec(self.eval(node.orelse)))
        if isinstance(node, ast.Attribute):
            return self._eval_attribute(node)
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value)
            if isinstance(base, tuple):
                idx = node.slice
                if isinstance(idx, ast.Constant) and isinstance(idx.value, int) \
                        and -len(base) <= idx.value < len(base):
                    return base[idx.value]
                return UNKNOWN
            if isinstance(base, Spec) and base.is_array:
                # DNDarray indexing changes shape/layout in data-dependent
                # ways the static model does not track
                return UNKNOWN
            return NOT_ARRAY
        if isinstance(node, ast.Call):
            return self.eval_call(node)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                self.eval(gen.iter)
            return NOT_ARRAY
        if isinstance(node, ast.NamedExpr):
            val = self.eval(node.value)
            self._bind(node.target, val, node.value)
            return val
        return NOT_ARRAY

    def _eval_attribute(self, node: ast.Attribute) -> object:
        val = self.eval(node.value)
        if isinstance(val, tuple):
            if node.attr in ("U", "S", "V") and len(val) == 3:
                return val[("U", "S", "V").index(node.attr)]
            return NOT_ARRAY
        if isinstance(val, Spec) and val.is_array:
            if node.attr == "T":
                out, facts = apply_kind("transpose", [val], axis=None)
                self._emit(node, facts)
                return out
            return NOT_ARRAY  # .larray/.split/.shape/.comm/...
        return NOT_ARRAY

    # ------------------------------------------------------------------ #
    # calls                                                               #
    # ------------------------------------------------------------------ #
    def _call_kind(self, call: ast.Call) -> Optional[str]:
        leaf = call.func.attr if isinstance(call.func, ast.Attribute) else (
            call.func.id if isinstance(call.func, ast.Name) else None)
        sem = self.program.registry.get(leaf) if leaf else None
        return sem.kind if sem else None

    def eval_call(self, node: ast.Call) -> object:
        func = node.func
        receiver: object = None
        if isinstance(func, ast.Attribute):
            receiver = self.eval(func.value)
            leaf = func.attr
        elif isinstance(func, ast.Name):
            leaf = func.id
        else:
            for a in node.args:
                self.eval(a)
            return NOT_ARRAY

        arg_vals = [self.eval(a) for a in node.args]
        kw_vals = {kw.arg: self.eval(kw.value) for kw in node.keywords
                   if kw.arg is not None}

        sem = self.program.registry.get(leaf)
        receiver_is_array = isinstance(receiver, Spec) and receiver.is_array
        dotted = self.ctx.resolve(func) or ""

        if sem is not None and self._sem_applies(
                sem, receiver, arg_vals, kw_vals, dotted):
            result = self._apply_sem(sem, node, receiver, arg_vals, kw_vals)
            # in-place layout mutation (`x.resplit_(axis)`) rebinds the
            # receiver — without this the next resplit_ looks like a no-op
            if sem.kind == "resplit" and leaf.endswith("_") \
                    and isinstance(func, ast.Attribute) \
                    and isinstance(func.value, ast.Name) \
                    and isinstance(result, Spec):
                self.env[func.value.id] = result
                self.resplit_origin.pop(func.value.id, None)
            return result

        # interprocedural: the callee is an analyzed def
        if not receiver_is_array:
            target = None
            if isinstance(func, ast.Name):
                fn = self.ctx.local_function(leaf, node)
                if fn is not None:
                    target = (self.ctx, fn)
            if target is None and dotted:
                target = self.program.resolve_def(dotted)
            if target is not None:
                return self.program.summarize(
                    target[0], target[1], arg_vals, kw_vals, depth=self.depth
                )
            if dotted and self.program.resolve_class(dotted):
                return NOT_ARRAY
        # unknown callee over array data: stay sound, assume an array of
        # unknown layout when any operand was one
        operands = ([receiver] if receiver is not None else []) + arg_vals \
            + list(kw_vals.values())
        if any(isinstance(v, Spec) and v.is_array for v in operands):
            return UNKNOWN
        return NOT_ARRAY

    def _sem_applies(self, sem, receiver, arg_vals, kw_vals, dotted) -> bool:
        heatish = dotted.startswith("heat_tpu.") or dotted.startswith("heat_tpu")
        if sem.kind in _CREATION_KINDS:
            return heatish
        operands = ([receiver] if receiver is not None else []) \
            + arg_vals + list(kw_vals.values())
        flat = []
        for v in operands:
            flat.extend(v if isinstance(v, tuple) else (v,))
        return any(isinstance(v, Spec) and v.is_array for v in flat)

    def _apply_sem(self, sem, node, receiver, arg_vals, kw_vals) -> object:
        # positional extras = the call arguments after the array operand
        # (method form: all of them; module form: everything past the
        # first array-valued argument)
        extras = list(node.args)
        if not (isinstance(receiver, Spec) and receiver.is_array):
            for i, v in enumerate(arg_vals):
                if isinstance(v, Spec) and v.is_array or isinstance(v, tuple):
                    extras = list(node.args[i + 1:])
                    break
        lit_extras = [_literal_of(a) for a in extras]
        kw_lits = {kw.arg: _literal_of(kw.value) for kw in node.keywords
                   if kw.arg is not None}

        operands = []
        if isinstance(receiver, Spec) and receiver.is_array:
            operands.append(receiver)
        for v in arg_vals:
            if isinstance(v, tuple):
                operands.extend(_as_spec(x) for x in v)
            elif isinstance(v, Spec):
                operands.append(v)
        for v in kw_vals.values():
            if isinstance(v, Spec) and v.is_array:
                operands.append(v)

        params: Dict[str, object] = {}
        kind = sem.kind
        if kind == "reduction":
            # the runtime default is axis=None — a FULL reduction
            params["axis"] = kw_lits.get(
                "axis", lit_extras[0] if lit_extras else None)
            params["keepdims"] = kw_lits.get("keepdims", MISSING)
        elif kind in ("cumulative", "expand_dims", "squeeze"):
            params["axis"] = kw_lits.get(
                "axis", lit_extras[0] if lit_extras else MISSING)
        elif kind == "transpose":
            ax = kw_lits.get("axes", MISSING)
            if ax is MISSING and lit_extras:
                if len(lit_extras) == 1 and isinstance(
                        lit_extras[0], (tuple, list, type(None))):
                    ax = lit_extras[0]
                elif all(isinstance(x, int) for x in lit_extras):
                    ax = tuple(lit_extras)
                else:
                    ax = NONLIT
            elif ax is MISSING and not extras:
                ax = None  # full reverse, the runtime default
            params["axis"] = ax
        elif kind == "reshape":
            shp = kw_lits.get("shape", kw_lits.get("newshape", MISSING))
            if shp is MISSING and lit_extras:
                if len(lit_extras) == 1 and isinstance(
                        lit_extras[0], (tuple, list, int)):
                    shp = lit_extras[0]
                elif all(isinstance(x, int) for x in lit_extras):
                    shp = tuple(lit_extras)
                else:
                    shp = NONLIT
            if isinstance(shp, int):
                shp = (shp,)
            params["shape"] = shp
        elif kind in ("concat", "stack"):
            params["axis"] = kw_lits.get(
                "axis", lit_extras[0] if lit_extras else 0)
            first = arg_vals[0] if arg_vals else NOT_ARRAY
            if isinstance(first, tuple):
                params["arrays"] = tuple(_as_spec(v) for v in first)
        elif kind == "resplit":
            params["split"] = kw_lits.get("axis", kw_lits.get(
                "split", lit_extras[0] if lit_extras else MISSING))
        elif kind == "factory":
            params["shape"] = self._factory_shape(sem.name, node, kw_lits)
            params["split"] = kw_lits.get("split", MISSING)
            params["splits"] = kw_lits.get("splits", MISSING)
            params["has_comm"] = any(
                kw.arg == "comm" for kw in node.keywords)
            params["dtype"] = self._dtype_of(node, sem.name)
        elif kind == "factory_like":
            params["split"] = kw_lits.get("split", MISSING)
        elif kind == "entry_svd":
            params["compute_uv"] = kw_lits.get("compute_uv", MISSING)
        elif kind == "entry_qr":
            # calc_q is the third positional after tiles_per_proc
            params["calc_q"] = kw_lits.get(
                "calc_q", lit_extras[1] if len(lit_extras) > 1 else MISSING)

        result, facts = apply_kind(kind, operands, **params)
        self._emit(node, facts)
        if kind == "resplit" and any(
                f.op in ("resplit", "noop_collective") for f in facts):
            self._check_chain(node)
        return result

    def _dtype_of(self, node: ast.Call, leaf: str = "") -> Optional[str]:
        for kw in node.keywords:
            if kw.arg == "dtype":
                dotted = self.ctx.resolve(kw.value) or ""
                name = dotted.rsplit(".", 1)[-1]
                if name in _DTYPE_NAMES:
                    return name
                lit = _literal_of(kw.value)
                if isinstance(lit, str) and lit in _DTYPE_NAMES:
                    return lit
                return None
        # data-driven factories infer dtype from their input; everything
        # else defaults to the canonical float32
        return None if leaf in ("array", "arange") else "float32"

    def _factory_shape(self, leaf: str, node: ast.Call, kw_lits) -> object:
        """Global result shape of a factory call, respecting each
        factory's actual signature (``array`` takes DATA, ``arange`` a
        range, ``eye`` row/col counts, the rest a shape)."""
        pos = [_literal_of(a) for a in node.args]
        if leaf == "array":
            data = kw_lits.get("obj", pos[0] if pos else MISSING)
            shp = _data_shape(data) if data not in (MISSING, NONLIT) else None
            if shp is None:
                return NONLIT
            ndmin = kw_lits.get("ndmin", 0)
            if isinstance(ndmin, int) and ndmin > len(shp):
                shp = (1,) * (ndmin - len(shp)) + shp
            return shp
        if leaf == "arange":
            if pos and all(isinstance(p, int) for p in pos):
                try:
                    n = len(range(*pos[:3]))
                except (TypeError, ValueError):
                    return NONLIT
                return (n,)
            return NONLIT
        if leaf in ("linspace", "logspace"):
            num = kw_lits.get("num", pos[2] if len(pos) > 2 else 50)
            return (num,) if isinstance(num, int) and num >= 0 else NONLIT
        if leaf == "eye":
            n = pos[0] if pos else kw_lits.get("n", MISSING)
            m = kw_lits.get("m", pos[1] if len(pos) > 1 else n)
            if isinstance(n, int) and isinstance(m, int):
                return (n, m)
            return NONLIT
        shp = kw_lits.get("shape", MISSING)
        if shp is MISSING and node.args:
            shp = pos[0]
        return shp

    def _check_chain(self, node: ast.Call) -> None:
        """SPMD502: the value being resplit is ITSELF a fresh resplit
        result nobody else uses — the intermediate layout is dead."""
        func = node.func
        operand_expr = None
        if isinstance(func, ast.Attribute):
            operand_expr = func.value
        elif node.args:
            operand_expr = node.args[0]
        if operand_expr is None:
            return
        inner: Optional[ast.Call] = None
        if isinstance(operand_expr, ast.Call) and self._call_kind(
                operand_expr) == "resplit":
            inner = operand_expr
        elif isinstance(operand_expr, ast.Name):
            origin = self.resplit_origin.get(operand_expr.id)
            if origin is not None and self.program.load_count(
                    self.ctx, self.fn, operand_expr.id) == 1:
                inner = origin
        if inner is not None:
            self.program.record(self.ctx, node, OpFact(
                "resplit_chain",
                note="intermediate layout from the inner resplit is never "
                     "used; go to the final split in one step",
            ))

    def _emit(self, node: ast.AST, facts: Sequence[OpFact]) -> None:
        for fact in facts:
            self.program.record(self.ctx, node, fact)


def _as_spec(val: object) -> Spec:
    if isinstance(val, Spec):
        return val
    if isinstance(val, tuple):
        out = NOT_ARRAY
        for v in val:
            out = join(out, _as_spec(v))
        return out
    return NOT_ARRAY


def _join_envs(a: Dict[str, object], b: Dict[str, object]) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k in set(a) | set(b):
        va, vb = a.get(k), b.get(k)
        if va is None:
            out[k] = _widen(vb)
        elif vb is None:
            out[k] = _widen(va)
        elif isinstance(va, tuple) and isinstance(vb, tuple) \
                and len(va) == len(vb):
            out[k] = tuple(join(_as_spec(x), _as_spec(y))
                           for x, y in zip(va, vb))
        else:
            out[k] = join(_as_spec(va), _as_spec(vb))
    return out


def _widen(val: object) -> object:
    # bound on one path only: the binding may not exist afterwards, so
    # nothing layout-specific may be concluded from it
    if isinstance(val, Spec) and val.is_array:
        return val.widened()
    if isinstance(val, tuple):
        return tuple(_widen(v) for v in val)
    return val


def _data_shape(x) -> Optional[tuple]:
    """np-style shape of nested literal sequences (``ht.array`` data)."""
    if isinstance(x, (list, tuple)):
        if not x:
            return (0,)
        sub = _data_shape(x[0])
        if sub is None or any(_data_shape(e) != sub for e in x[1:]):
            return None
        return (len(x),) + sub
    if isinstance(x, (bool, int, float, complex)):
        return ()
    return None


def _literal_of(node: ast.AST) -> object:
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError, TypeError):
        return NONLIT


def build_program(contexts: Sequence[FileContext]) -> Program:
    """Run the splitflow analysis over pre-built file contexts."""
    return Program(contexts)
