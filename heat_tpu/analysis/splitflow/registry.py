"""Static view of the split-semantics registry.

The runtime registry (:mod:`heat_tpu.core._split_semantics`) is built by
executing the op modules; this module recovers the SAME declarations by
**parsing** them — plain ``ast`` over the package source on disk, no jax,
no heat_tpu import.  That is only possible because the declaration forms
were designed for it: ``declare_split_semantics_table`` takes a literal
dict, and the ``@split_semantics("kind", ...)`` decorator takes literal
arguments.  The oracle lane imports the runtime registry in-process and
asserts it equals this parse, so the two views cannot drift.

Analyzed fixture files may carry their own declarations (same forms);
those are merged on top of the package's.
"""

from __future__ import annotations

import ast
import functools
import os
from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple

__all__ = ["StaticSem", "package_registry", "parse_declarations", "static_registry"]

_DECL_TABLE = "declare_split_semantics_table"
_DECL_ONE = "declare_split_semantics"
_DECORATOR = "split_semantics"


@dataclass(frozen=True)
class StaticSem:
    """One statically-recovered declaration: op leaf name → op kind."""

    name: str
    kind: str
    params: Tuple[Tuple[str, object], ...] = ()

    def param(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _decorator_name(dec: ast.AST) -> Optional[str]:
    target = dec.func if isinstance(dec, ast.Call) else dec
    while isinstance(target, ast.Attribute):
        target = target.attr if isinstance(target.attr, str) else target.value
        if isinstance(target, str):
            return target
    if isinstance(target, ast.Name):
        return target.id
    return None


def _params_from_call(call: ast.Call, skip: int) -> Tuple[Tuple[str, object], ...]:
    out = []
    for kw in call.keywords:
        if kw.arg is not None and kw.arg != "module":
            out.append((kw.arg, _literal(kw.value)))
    return tuple(sorted(out))


def parse_declarations(tree: ast.AST) -> Dict[str, StaticSem]:
    """Extract every split-semantics declaration from one parsed module."""
    out: Dict[str, StaticSem] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = _decorator_name(node)
            if fname == _DECL_TABLE and len(node.args) >= 2:
                table = node.args[1]
                if isinstance(table, ast.Dict):
                    for k, v in zip(table.keys, table.values):
                        kind = _literal(k)
                        names = _literal(v)
                        if isinstance(kind, str) and isinstance(names, (tuple, list)):
                            for n in names:
                                if isinstance(n, str):
                                    out[n] = StaticSem(n, kind)
            elif fname == _DECL_ONE and len(node.args) >= 2:
                name, kind = _literal(node.args[0]), _literal(node.args[1])
                if isinstance(name, str) and isinstance(kind, str):
                    out[name] = StaticSem(name, kind, _params_from_call(node, 2))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                dname = _decorator_name(dec) or ""
                if dname == _DECORATOR or dname.endswith("_" + _DECORATOR):
                    kind = _literal(dec.args[0]) if dec.args else None
                    if isinstance(kind, str):
                        out[node.name] = StaticSem(
                            node.name, kind, _params_from_call(dec, 1)
                        )
    return out


def _package_root() -> str:
    # heat_tpu/analysis/splitflow/registry.py -> the heat_tpu package dir
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@functools.lru_cache(maxsize=1)
def package_registry() -> Dict[str, StaticSem]:
    """The full static registry parsed from the heat_tpu package source.

    Walks every ``.py`` under the package (skipping this analysis
    subpackage — its fixtures would pollute the table) and merges the
    declarations.  Cached: the parse is pure and the package source does
    not change within a process."""
    root = _package_root()
    out: Dict[str, StaticSem] = {}
    skip = os.path.join(root, "analysis")
    for base, dirs, files in os.walk(root):
        if base.startswith(skip):
            dirs[:] = []
            continue
        dirs[:] = sorted(d for d in dirs if not d.startswith((".", "__pycache__")))
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(base, f)
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    src = fh.read()
                if "split_semantics" not in src:
                    continue
                out.update(parse_declarations(ast.parse(src, filename=path)))
            except (OSError, SyntaxError):  # spmdlint: disable=SPMD207 -- a transiently unreadable or unparsable file must degrade to "no declarations", not kill the whole lint run
                continue
    return out


def static_registry(trees: Iterable[ast.AST] = ()) -> Dict[str, StaticSem]:
    """Package registry plus declarations found in ``trees`` (analyzed
    fixture files may declare semantics for their own test ops)."""
    out = dict(package_registry())
    for tree in trees:
        out.update(parse_declarations(tree))
    return out
