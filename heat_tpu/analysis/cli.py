"""Command-line front end; thin so ``scripts/spmdlint.py`` stays a stub.

Exit codes: 0 clean (or baseline-covered), 1 new findings, 2 bad usage.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .baseline import DEFAULT_BASELINE, load_baseline, partition, write_baseline
from .core import analyze_paths
from .rules import all_rules


def _repo_root() -> str:
    # heat_tpu/analysis/cli.py -> repo root two levels above the package
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="spmdlint",
        description="Static SPMD-correctness analyzer for heat_tpu "
        "(collective discipline, trace purity, Pallas tiling, jit-cache keys).",
    )
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to analyze (default: the heat_tpu package)",
    )
    p.add_argument(
        "--baseline", nargs="?", const=True, default=None, metavar="FILE",
        help="compare against the committed baseline (optionally at FILE; "
        f"default {DEFAULT_BASELINE} at the repo root) and fail only on "
        "NEW findings",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file from the current findings and exit 0",
    )
    p.add_argument(
        "--no-dynamic", action="store_true",
        help="skip rules that evaluate perm-builder source (SPMD101)",
    )
    p.add_argument(
        "--rule", action="append", default=None, metavar="ID",
        help="run only this rule id (repeatable)",
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule catalog")
    p.add_argument("-q", "--quiet", action="store_true", help="counts only, no per-finding output")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    root = _repo_root()

    if args.list_rules:
        from . import checkers  # noqa: F401  (register rules)

        for r in all_rules():
            dyn = " [dynamic]" if r.dynamic else ""
            print(f"{r.id}  {r.title}{dyn}")
        return 0

    paths = args.paths or [os.path.join(root, "heat_tpu")]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"spmdlint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    findings = analyze_paths(paths, dynamic=not args.no_dynamic, root=root)
    if args.rule:
        findings = [f for f in findings if f.rule in args.rule]

    baseline_path = None
    if args.baseline is not None or args.update_baseline:
        baseline_path = (
            args.baseline
            if isinstance(args.baseline, str)
            else os.path.join(root, DEFAULT_BASELINE)
        )

    if args.update_baseline:
        write_baseline(baseline_path, findings)
        print(f"spmdlint: baseline written to {baseline_path} ({len(findings)} findings)")
        return 0

    if baseline_path is not None:
        new, old, stale = partition(findings, load_baseline(baseline_path))
        if not args.quiet:
            for f in new:
                print(f.render())
            for fp in stale:
                print(f"stale baseline entry (fix it and update the baseline): {fp}")
        print(
            f"spmdlint: {len(new)} new, {len(old)} baselined, "
            f"{len(stale)} stale baseline entries"
        )
        return 1 if new else 0

    if not args.quiet:
        for f in findings:
            print(f.render())
    print(f"spmdlint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
