"""Command-line front end; thin so ``scripts/spmdlint.py`` stays a stub.

Exit codes: 0 clean (or baseline-covered), 1 new findings, 2 bad usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from .baseline import DEFAULT_BASELINE, load_baseline, partition, write_baseline
from .cache import DEFAULT_CACHE_DIR, FindingsCache
from .core import analyze_paths
from .rules import Finding, all_rules


def _repo_root() -> str:
    # heat_tpu/analysis/cli.py -> repo root two levels above the package
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="spmdlint",
        description="Static SPMD-correctness analyzer for heat_tpu "
        "(collective discipline, trace purity, Pallas tiling, jit-cache "
        "keys, interprocedural sharding dataflow).",
    )
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to analyze (default: the heat_tpu package)",
    )
    p.add_argument(
        "--baseline", nargs="?", const=True, default=None, metavar="FILE",
        help="compare against the committed baseline (optionally at FILE; "
        f"default {DEFAULT_BASELINE} at the repo root) and fail only on "
        "NEW findings",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file from the current findings and exit 0",
    )
    p.add_argument(
        "--no-dynamic", action="store_true",
        help="skip rules that evaluate perm-builder source (SPMD101)",
    )
    p.add_argument(
        "--rule", action="append", default=None, metavar="ID",
        help="run only this rule id (repeatable)",
    )
    p.add_argument(
        "--format", choices=("text", "json", "github"), default="text",
        help="finding output format: human text (default), a JSON "
        "document, or GitHub workflow annotations",
    )
    p.add_argument(
        "--cost-report", action="store_true",
        help="print the static comm-cost report (splitflow-derived layout "
        "traffic priced with the runtime cost model) instead of findings; "
        "--format=json emits the machine-readable document",
    )
    p.add_argument(
        "--mesh", type=int, default=8, metavar="N",
        help="mesh size the cost report prices collectives at (default 8)",
    )
    p.add_argument(
        "--precision", default="f32", metavar="MODE",
        help="redistribution wire precision for the cost report: f32 "
        "(default), auto, int8_block, or bf16",
    )
    p.add_argument(
        "--no-cache", action="store_true",
        help="always re-analyze; skip the per-file findings cache",
    )
    p.add_argument(
        "--cache-dir", default=None, metavar="DIR",
        help=f"findings cache location (default {DEFAULT_CACHE_DIR} at the "
        "repo root)",
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule catalog")
    p.add_argument("-q", "--quiet", action="store_true", help="counts only, no per-finding output")
    return p


def _emit(findings: List[Finding], fmt: str, quiet: bool) -> None:
    if fmt == "json":
        print(json.dumps(
            {"findings": [f.to_dict() for f in findings],
             "count": len(findings)},
            indent=2, sort_keys=True,
        ))
        return
    if fmt == "github":
        # workflow-command annotations; one line per finding, grep-stable
        for f in findings:
            msg = f.message + (f" (hint: {f.hint})" if f.hint else "")
            # commas/newlines terminate workflow-command properties
            msg = msg.replace("\n", " ").replace(",", ";")
            print(
                f"::error file={f.path},line={f.line},"
                f"title={f.rule}::{msg}"
            )
        return
    if not quiet:
        for f in findings:
            print(f.render())


def _run_cost_report(args, paths: List[str], root: str) -> int:
    from .core import FileContext, iter_py_files, norm_relpath
    from .splitflow import build_program, cost_report, render_table

    contexts = [
        FileContext(f, relpath=norm_relpath(f, root))
        for f in iter_py_files(paths)
    ]
    program = build_program([c for c in contexts if not c.skip_file])
    precision = None if args.precision in ("f32", "none") else args.precision
    report = cost_report(program, mesh=args.mesh, precision=precision or "f32")
    if args.format == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_table(report))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    root = _repo_root()

    if args.list_rules:
        from .core import _register_all_rules

        _register_all_rules()
        for r in all_rules():
            dyn = " [dynamic]" if r.dynamic else ""
            scope = " [program]" if r.scope == "program" else ""
            print(f"{r.id}  {r.title}{dyn}{scope}")
        return 0

    paths = args.paths or [os.path.join(root, "heat_tpu")]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"spmdlint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    if args.cost_report:
        return _run_cost_report(args, paths, root)

    cache = None
    if not args.no_cache:
        cache = FindingsCache(
            args.cache_dir or os.path.join(root, DEFAULT_CACHE_DIR)
        )

    t0 = time.monotonic()
    findings = analyze_paths(
        paths, dynamic=not args.no_dynamic, root=root, cache=cache,
        rules=args.rule,
    )
    elapsed = time.monotonic() - t0
    timing = f"{elapsed:.2f}s" + (
        f", cache {cache.stats()}" if cache is not None else ", cache off"
    )

    baseline_path = None
    if args.baseline is not None or args.update_baseline:
        baseline_path = (
            args.baseline
            if isinstance(args.baseline, str)
            else os.path.join(root, DEFAULT_BASELINE)
        )

    if args.update_baseline:
        write_baseline(baseline_path, findings)
        print(f"spmdlint: baseline written to {baseline_path} ({len(findings)} findings)")
        return 0

    if baseline_path is not None:
        new, old, stale = partition(findings, load_baseline(baseline_path))
        _emit(new, args.format, args.quiet)
        if args.format == "text" and not args.quiet:
            for fp in stale:
                print(f"stale baseline entry (fix it and update the baseline): {fp}")
        if args.format != "json":
            print(
                f"spmdlint: {len(new)} new, {len(old)} baselined, "
                f"{len(stale)} stale baseline entries  [{timing}]"
            )
        return 1 if new else 0

    _emit(findings, args.format, args.quiet)
    if args.format != "json":
        print(f"spmdlint: {len(findings)} finding(s)  [{timing}]")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
