"""Pairwise distance computations.

Reference: heat/spatial/distance.py:28-475 — ``cdist``/``rbf``/``manhattan``
route into ``_dist``, which hand-rolls a **ring communication** schedule:
with X split=0, each of (size+1)//2 rounds Sends the local block to rank+i,
Recvs from rank−i, computes a tile, and ships the result back to exploit
symmetry (:244-345).

TPU-first formulation: the distance matrix is one global computation.  For
the euclidean metric the quadratic expansion ``|x|² + |y|² − 2xy``
(reference :28-72 uses the same trick locally) turns the hot loop into a
single large matmul on the MXU; GSPMD schedules the inter-shard movement —
on an ICI ring that schedule *is* the reference's ring, chosen by the
compiler.  Row-sharding of X propagates to row-sharding of D.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core import types
from ..core._compile import jitted
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in

__all__ = ["cdist", "manhattan", "rbf", "quadratic_d2"]


def quadratic_d2(xa, ya):
    """Squared euclidean distances via the MXU-native quadratic expansion
    |x|² + |y|² − 2xy, clamped at 0 against rounding (the one shared
    implementation — reference _quadratic_expand, distance.py:40-72)."""
    x2 = jnp.sum(xa * xa, axis=-1, keepdims=True)
    y2 = jnp.sum(ya * ya, axis=-1, keepdims=True).swapaxes(-1, -2)
    return jnp.maximum(x2 + y2 - 2.0 * jnp.matmul(xa, ya.swapaxes(-1, -2)), 0.0)


def _prep(x: DNDarray, y: Optional[DNDarray]):
    sanitize_in(x)
    if x.ndim != 2:
        raise NotImplementedError(f"X should be a 2D DNDarray, but is {x.ndim}D")
    if y is not None:
        sanitize_in(y)
        if y.ndim != 2:
            raise NotImplementedError(f"Y should be a 2D DNDarray, but is {y.ndim}D")
        if x.shape[1] != y.shape[1]:
            raise ValueError(
                f"inputs must have the same number of features, got {x.shape[1]} and {y.shape[1]}"
            )
    promoted = types.promote_types(x.dtype, types.float32)
    xa = x.larray.astype(promoted.jax_type())
    ya = xa if y is None else y.larray.astype(promoted.jax_type())
    return xa, ya, promoted


def _wrap(x: DNDarray, garr, dtype) -> DNDarray:
    split = x.split if x.split == 0 else None
    garr = x.comm.apply_sharding(garr, split)
    return DNDarray(garr, tuple(garr.shape), dtype, split, x.device, x.comm, True)


def _euclidean(xa, ya, quadratic_expansion: bool):
    if quadratic_expansion:
        return jnp.sqrt(quadratic_d2(xa, ya))
    diff = xa[:, None, :] - ya[None, :, :]
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


from ..core._split_semantics import split_semantics as _split_semantics


@_split_semantics("entry_split0")
def cdist(X: DNDarray, Y: Optional[DNDarray] = None, quadratic_expansion: bool = False) -> DNDarray:
    """Pairwise euclidean distances (reference distance.py:166-172).

    ``quadratic_expansion=True`` uses the |x|²+|y|²−2xy form — on TPU this
    is the fast path (a single MXU matmul); the exact broadcast form is the
    default, like the reference's torch.cdist.
    """
    xa, ya, dtype = _prep(X, Y)
    fn = jitted(
        ("dist.euclidean", quadratic_expansion),
        lambda: lambda a, b: _euclidean(a, b, quadratic_expansion),
    )
    return _wrap(X, fn(xa, ya), dtype)


def rbf(
    X: DNDarray,
    Y: Optional[DNDarray] = None,
    sigma: float = 1.0,
    quadratic_expansion: bool = False,
) -> DNDarray:
    """Gaussian (RBF) kernel matrix exp(−d²/2σ²)
    (reference distance.py:173-179)."""
    xa, ya, dtype = _prep(X, Y)

    def _make():
        def _rbf(a, b, sig):
            if quadratic_expansion:
                d2 = quadratic_d2(a, b)
            else:
                diff = a[:, None, :] - b[None, :, :]
                d2 = jnp.sum(diff * diff, axis=-1)
            return jnp.exp(-d2 / (2.0 * sig * sig))

        return _rbf

    fn = jitted(("dist.rbf", quadratic_expansion), _make)
    return _wrap(X, fn(xa, ya, jnp.asarray(sigma, xa.dtype)), dtype)


def manhattan(X: DNDarray, Y: Optional[DNDarray] = None, expand: bool = False) -> DNDarray:
    """Pairwise L1 distances (reference distance.py:180-186)."""
    xa, ya, dtype = _prep(X, Y)
    del expand  # accepted for API parity; one formulation here
    fn = jitted(
        ("dist.manhattan",),
        lambda: lambda a, b: jnp.sum(jnp.abs(a[:, None, :] - b[None, :, :]), axis=-1),
    )
    return _wrap(X, fn(xa, ya), dtype)
