"""heat_tpu.spatial"""
