"""Whole-program compilation over DNDarrays: ``ht.fuse``.

``jitted()`` (:mod:`heat_tpu.core._compile`) compiles each *single* op's
primitive chain, so an eager pipeline of N DNDarray ops still pays N
host↔device round trips — the dispatch tax BENCH dispositions measure at
~1 ms per launch on a tunneled TPU, dwarfing the device compute of small
and medium ops.  ``fuse`` closes the gap the way "Automatic Full
Compilation of Julia Programs and ML Models to Cloud TPUs"
(arXiv:1810.09868) does for whole programs and "Large Scale Distributed
Linear Algebra With TPUs" (arXiv:2112.09017) assumes for its kernels:
trace the entire user pipeline once, compile it into ONE XLA executable,
and replay that for every subsequent call.

How it works
------------
``fuse(fn)`` returns a wrapper that, per call:

1. flattens ``(args, kwargs)`` with DNDarray leaves kept whole, splitting
   every leaf into a *dynamic* operand (the DNDarray's at-rest global
   ``jax.Array`` buffer, or a raw ``jax.Array``/numpy leaf) plus *static*
   metadata (gshape, split, heat dtype, balanced flag — and the value
   itself for non-array leaves);
2. looks up a compiled program keyed on
   ``(fn identity, treedef, per-leaf avals/splits, statics, comm, donate)``
   — ``fn`` identity follows :func:`~heat_tpu.core._compile.cache_stable`,
   so module-level pipelines cache across calls while lambdas/closures get
   a transient (per-call) compile;
3. on a miss, traces ``fn`` once under :func:`~heat_tpu.core._tracing.
   trace_mode`: DNDarrays are rebuilt around the traced buffers, the
   communication layer swaps committed-layout work (``device_put``,
   ``.sharding`` inspection) for ``jax.lax.with_sharding_constraint``
   hints, and any value-forcing operation (``float()``, ``.item()``,
   printing, I/O) raises :class:`FuseTraceError`;
4. replays the compiled program — one device dispatch — and re-wraps the
   output buffers as DNDarrays with the split metadata inferred at trace
   time.

Static metadata is part of the key, so python-scalar arguments that vary
per call (thresholds, axes) each compile their own specialization — pass
them as 0-d DNDarrays/jax arrays if they genuinely vary.

``donate=True`` donates the input buffers to XLA (in-place pipelines):
the caller's input DNDarrays are consumed and must not be used afterwards.

``fuse.trace()`` exposes the bare tracing mode as a context manager — the
communication-layer swap and the value-forcing guard without the
compile-and-cache machinery — for embedding DNDarray code inside a wider
``jax.jit``/``shard_map`` region of your own.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax

from ..telemetry import _core as _tel
from . import _compile
from ._compile import cache_stable
from ._tracing import (
    FuseTraceError,
    applying_layout_plan,
    in_trace,
    record_dispatch,
    trace_mode,
)
from .dndarray import DNDarray

__all__ = ["fuse", "FuseTraceError"]

_FUSE_CACHE: Dict[Tuple, Any] = {}

#: active AOT capture sinks (:func:`heat_tpu.core.aot.capture_programs`):
#: each is a dict keyed by fuse-cache key, fed one entry per distinct
#: cache-keyed call so a warm process can export its executables
_CAPTURE_SINKS: list = []


@contextlib.contextmanager
def _null_ctx():
    yield


def _is_dnd(x: Any) -> bool:
    return isinstance(x, DNDarray)


def _guards():
    """Lazy import of the health-guard seam (the resilience package sits
    above core in the import graph)."""
    from ..resilience import guards

    return guards


class _Program:
    """A traced-and-compiled pipeline plus its output re-wrap recipe.

    ``guarded`` marks programs traced under an active health-guard
    policy: they carry one extra output, the on-device health flag over
    every inexact result buffer.  The guard policy is part of the fuse
    cache key (:func:`heat_tpu.core._compile.context_token`), so a
    guarded and an unguarded trace of the same pipeline never collide.
    """

    __slots__ = ("jfn", "out_treedef", "out_meta", "guarded", "aot_payload")

    def __init__(self, jfn):
        self.jfn = jfn
        self.out_treedef = None
        self.out_meta = None
        self.guarded = False
        # set only on installed programs: the original serialized
        # (payload, in_tree, out_tree) triple, kept so a warm replica can
        # re-export without re-serializing a loaded executable (which
        # XLA cannot soundly deserialize a second time)
        self.aot_payload = None


def _build(fn: Callable, slots: Tuple, treedef, donate: bool) -> _Program:
    """Compile ``fn`` over the leaf layout described by ``slots``.

    ``slots`` entries are ``("dnd", gshape, dtype, split, device, comm,
    balanced)``, ``("arr",)``, or ``("static", value)``; dynamic operands
    are threaded through in slot order.
    """
    program = _Program(None)

    def _runner(operands):
        it = iter(operands)
        leaves = []
        for slot in slots:
            if slot[0] == "dnd":
                _, gshape, dtype, split, device, comm, balanced = slot
                leaves.append(DNDarray(next(it), gshape, dtype, split, device, comm, balanced))
            elif slot[0] == "arr":
                leaves.append(next(it))
            else:
                leaves.append(slot[1])
        args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
        with trace_mode():
            out = fn(*args, **kwargs)
            out_leaves, out_treedef = jax.tree_util.tree_flatten(out, is_leaf=_is_dnd)
            raws, meta = [], []
            for leaf in out_leaves:
                if isinstance(leaf, DNDarray):
                    buf = leaf._buffer
                    # pin the at-rest layout at the program boundary; the
                    # buffer is canonically padded, so the split axis is
                    # divisible and commits genuinely sharded
                    sh = leaf.comm.sharding(buf.ndim, leaf.split)
                    raws.append(jax.lax.with_sharding_constraint(buf, sh))
                    meta.append(
                        ("dnd", leaf.gshape, leaf.dtype, leaf.split, leaf.device,
                         leaf.comm, leaf.balanced)
                    )
                elif isinstance(leaf, jax.Array):
                    raws.append(leaf)
                    meta.append(("raw",))
                else:
                    # trace-time constant (python scalar, string, None-like):
                    # deterministic given the cache key, so bake it in
                    meta.append(("const", leaf))
        program.out_treedef = out_treedef
        program.out_meta = tuple(meta)
        if _guards().active():
            # one extra scalar output: the fused-program health flag —
            # all(isfinite) and below the overflow limit, over every
            # inexact result buffer, computed on device in the same
            # dispatch
            raws.append(_guards().health_flag(raws))
            program.guarded = True
        return tuple(raws)

    program.jfn = jax.jit(_runner, donate_argnums=(0,) if donate else ())
    return program


class _FusedFunction:
    """The callable returned by :func:`fuse`."""

    def __init__(self, fn: Callable, donate: bool = False, layout_plan=None):
        self._fn = fn
        self._donate = bool(donate)
        self._stable = cache_stable(fn)
        # a solved ht.autoshard plan: its decisions steer every resplit
        # inside the trace, and its fingerprint joins the cache key so a
        # planned and an unplanned trace of the same fn never collide
        self._layout_plan = layout_plan
        self._plan_token = layout_plan["fingerprint"] if layout_plan else None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        if in_trace():
            # nested fuse (or inside fuse.trace()): inline into the
            # enclosing program instead of compiling a second one
            return self._fn(*args, **kwargs)
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_dnd)
        operands, slots, keyparts = [], [], []
        comm = None
        for leaf in leaves:
            if isinstance(leaf, DNDarray):
                buf = leaf._buffer
                operands.append(buf)
                slots.append(
                    ("dnd", leaf.gshape, leaf.dtype, leaf.split, leaf.device,
                     leaf.comm, leaf.balanced)
                )
                keyparts.append(
                    ("dnd", tuple(buf.shape), str(buf.dtype), leaf.gshape,
                     leaf.dtype, leaf.split, leaf.balanced, leaf.comm)
                )
                comm = comm if comm is not None else leaf.comm
            elif isinstance(leaf, (jax.Array, np.ndarray)):
                operands.append(leaf)
                slots.append(("arr",))
                keyparts.append(("arr", tuple(leaf.shape), str(leaf.dtype)))
            else:
                slots.append(("static", leaf))
                keyparts.append(("static", leaf))
        slots = tuple(slots)

        program = None
        key = None
        if self._stable and self._cacheable_statics(leaves):
            # context_token(): process-wide state (collective-compression
            # policy, comm overlap, io prefetch — every provider behind
            # _compile.register_key_context) that changes what the traced
            # program computes or how its dispatches are attributed —
            # fused programs re-trace under a new policy, never replay
            key = (self._fn, self._donate, self._plan_token, treedef,
                   tuple(keyparts), comm, _compile.context_token())
            try:
                program = _FUSE_CACHE.get(key)
            except TypeError:  # unhashable static leaf slipped through
                key = None
        if program is None:
            if _tel.enabled:
                _tel.inc("fuse.cache.misses")
            program = _build(self._fn, slots, treedef, self._donate)
            if key is not None:
                _FUSE_CACHE[key] = program
                if _tel.enabled:
                    _tel.gauge("fuse.cache.size", len(_FUSE_CACHE))
        elif _tel.enabled:
            _tel.inc("fuse.cache.hits")

        # AOT capture: operand specs must be snapshotted BEFORE the call
        # (donation may consume the buffers), the entry recorded after it
        # (the first call populates program.out_meta)
        capture_specs = None
        if _CAPTURE_SINKS and key is not None:
            capture_specs = tuple(
                jax.ShapeDtypeStruct(
                    tuple(op.shape), op.dtype,
                    sharding=op.sharding if isinstance(op, jax.Array) else None,
                )
                for op in operands
            )

        # jax.jit is lazy, so the plan context must cover EVERY launch:
        # the first call runs the DNDarray trace (where resplits consult
        # the plan) inside jfn, and jit may silently retrace later
        plan_ctx = (
            applying_layout_plan(self._layout_plan["decisions"])
            if self._layout_plan is not None else _null_ctx()
        )
        with plan_ctx:
            if _tel.enabled:
                # a program whose out_treedef is still unset runs its
                # DNDarray trace + XLA compile inside this first call, so
                # that is the "build" span; later calls replay
                site = "fuse:build" if program.out_treedef is None else "fuse:replay"
                with _tel.span(site, name=getattr(self._fn, "__name__", "<pipeline>")):
                    raws = program.jfn(tuple(operands))
            else:
                raws = program.jfn(tuple(operands))
        record_dispatch()

        if capture_specs is not None:
            entry = {
                "fn": self._fn,
                "donate": self._donate,
                "plan_token": self._plan_token,
                "treedef": treedef,
                "keyparts": tuple(keyparts),
                "comm": comm,
                "program": program,
                "specs": capture_specs,
            }
            for sink in _CAPTURE_SINKS:
                sink.setdefault(key, entry)

        flag = None
        if program.guarded:
            flag = raws[-1]
            raws = raws[:-1]

        it = iter(raws)
        out_leaves = []
        for meta in program.out_meta:
            if meta[0] == "dnd":
                _, gshape, dtype, split, device, comm_, balanced = meta
                out_leaves.append(DNDarray(next(it), gshape, dtype, split, device, comm_, balanced))
            elif meta[0] == "raw":
                out_leaves.append(next(it))
            else:
                out_leaves.append(meta[1])
        result = jax.tree_util.tree_unflatten(program.out_treedef, out_leaves)

        if flag is not None and not bool(flag):
            if self._donate:
                # the unhealthy program consumed its input buffers —
                # there is nothing left to re-run the exact path on
                degrade_fn = None
            else:
                def degrade_fn():
                    from ..comm.compressed import collective_precision

                    # exact-collective re-trace: the policy change flows
                    # into the cache key, so this compiles (and caches)
                    # its own program instead of mutating the fast one
                    with collective_precision("f32"):
                        return self(*args, **kwargs)

            site = f"fuse:{getattr(self._fn, '__name__', '<pipeline>')}"
            return _guards().handle(site, result, degrade_fn)
        return result

    @staticmethod
    def _cacheable_statics(leaves) -> bool:
        """Static leaves must be hashable, and callable statics must have a
        call-stable identity — otherwise every call would add a dead cache
        entry (same rule as jitted keys, spmdlint SPMD401)."""
        for leaf in leaves:
            if isinstance(leaf, (DNDarray, jax.Array, np.ndarray)):
                continue
            if callable(leaf) and not cache_stable(leaf):
                return False
            try:
                hash(leaf)
            except TypeError:
                return False
        return True


def fuse(fn: Optional[Callable] = None, *, donate: bool = False,
         layout_plan=None):
    """Compile a DNDarray pipeline into one XLA program (one dispatch).

    Use as a decorator (``@ht.fuse`` / ``@ht.fuse(donate=True)``) or
    inline (``fused = ht.fuse(my_pipeline)``).  See the module docstring
    for caching, static-argument, and donation semantics.

    ``layout_plan`` is the :func:`heat_tpu.autoshard` seam: a solved plan
    dict (:meth:`heat_tpu.comm._costs.LayoutSolver.solve`) whose decisions
    override the hand-placed resplits during tracing and whose fingerprint
    becomes part of the compile-cache key.
    """
    if fn is None:
        return functools.partial(fuse, donate=donate, layout_plan=layout_plan)
    return _FusedFunction(fn, donate=donate, layout_plan=layout_plan)


#: context-manager variant: bare tracing mode without compile-and-cache
fuse.trace = trace_mode


def fuse_cache_size() -> int:
    """Number of cached fused programs (mainly for tests)."""
    return len(_FUSE_CACHE)


def fuse_clear_cache() -> None:
    """Drop all cached fused programs (mainly for tests)."""
    _FUSE_CACHE.clear()


fuse.cache_size = fuse_cache_size
fuse.clear_cache = fuse_clear_cache
