"""NumPy-style dtype hierarchy for heat_tpu.

Reference: heat/core/types.py:62-688 — a class hierarchy
``generic → bool/number → integer/floating → concrete dtypes`` where each
concrete class is *callable as a cast* (``ht.float32(x)`` converts ``x``),
plus ``canonical_heat_type`` / ``heat_type_of`` normalization,
``promote_types`` over an explicit lattice, ``can_cast`` with the default
"intuitive" rule, and ``finfo``/``iinfo``.

TPU-first deltas from the reference:

* concrete dtypes map to **JAX dtypes** rather than torch dtypes;
* ``bfloat16`` and ``float16`` are first-class (bfloat16 is the native MXU
  input type — the single most important dtype on TPU; the reference has
  neither);
* promotion delegates to JAX's type-promotion lattice
  (``jnp.promote_types``), which matches the torch-style semantics the
  reference implements by hand (int32 + float32 → float32, not numpy's
  float64);
* ``float64``/``int64`` exist because heat_tpu enables ``jax_enable_x64``;
  on real TPU hardware float64 is software-emulated and should be avoided in
  hot paths (defaults everywhere are float32, as in the reference).
"""

from __future__ import annotations

import builtins
import numbers
from typing import Any, Tuple, Union

import numpy as np

import jax.numpy as jnp

__all__ = [
    "generic",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "floating",
    "bool",
    "bool_",
    "uint8",
    "ubyte",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int_",
    "int64",
    "long",
    "float16",
    "half",
    "bfloat16",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "flexible",
    "canonical_heat_type",
    "heat_type_of",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "issubdtype",
    "can_cast",
    "promote_types",
    "result_type",
    "finfo",
    "iinfo",
]


class generic:
    """Root of the dtype hierarchy (reference types.py:62-150).

    Calling a concrete subclass casts its argument:
    ``ht.float32([1, 2])`` → a float32 DNDarray (reference behavior of every
    dtype class's ``__new__``).
    """

    _jax_type = None  # concrete classes override
    _np_type = None

    def __new__(cls, *value, device=None, comm=None):
        if cls._jax_type is None:
            raise TypeError(f"cannot create '{cls.__name__}' instances — abstract dtype")
        from . import factories

        if len(value) == 0:
            value = (0,)
        if len(value) == 1:
            value = value[0]
        return factories.array(value, dtype=cls, device=device, comm=comm)

    @classmethod
    def jax_type(cls):
        """The backing jax/numpy dtype (the analog of the reference's
        ``torch_type``, types.py:160)."""
        if cls._jax_type is None:
            raise TypeError(f"abstract dtype '{cls.__name__}' has no jax type")
        return cls._jax_type

    @classmethod
    def char(cls) -> str:
        return np.dtype(cls._np_type).char if cls._np_type is not None else "?"


class bool(generic):  # noqa: A001 — mirrors the reference's shadowing (types.py:152)
    _jax_type = jnp.bool_
    _np_type = np.bool_


bool_ = bool


class number(generic):
    pass


class integer(number):
    pass


class signedinteger(integer):
    pass


class unsignedinteger(integer):
    pass


class floating(number):
    pass


class flexible(generic):
    """Placeholder branch kept for hierarchy parity (reference types.py:208)."""


class uint8(unsignedinteger):
    _jax_type = jnp.uint8
    _np_type = np.uint8


class int8(signedinteger):
    _jax_type = jnp.int8
    _np_type = np.int8


class int16(signedinteger):
    _jax_type = jnp.int16
    _np_type = np.int16


class int32(signedinteger):
    _jax_type = jnp.int32
    _np_type = np.int32


class int64(signedinteger):
    _jax_type = jnp.int64
    _np_type = np.int64


class float16(floating):
    _jax_type = jnp.float16
    _np_type = np.float16


class bfloat16(floating):
    """TPU-native 16-bit float (8-bit exponent).  Not in the reference —
    added because it is the canonical MXU input type."""

    _jax_type = jnp.bfloat16
    _np_type = jnp.bfloat16  # ml_dtypes-backed numpy scalar type


class float32(floating):
    _jax_type = jnp.float32
    _np_type = np.float32


class float64(floating):
    _jax_type = jnp.float64
    _np_type = np.float64


# aliases (reference types.py:211-240)
ubyte = uint8
byte = int8
short = int16
int = int32  # noqa: A001
int_ = int32
long = int64
half = float16
float = float32  # noqa: A001
float_ = float32
double = float64


_CONCRETE: Tuple[type, ...] = (
    bool,
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
)

# jax/numpy dtype → heat type
__dtype_map = {np.dtype(c._np_type): c for c in _CONCRETE}
__name_map = {c.__name__: c for c in _CONCRETE}
__name_map.update(
    {
        "ubyte": uint8,
        "byte": int8,
        "short": int16,
        "int_": int32,
        "int": int32,
        "long": int64,
        "half": float16,
        "float": float32,
        "double": float64,
        "bool_": bool,
        "b": bool,
        "u1": uint8,
        "i1": int8,
        "i2": int16,
        "i4": int32,
        "i8": int64,
        "f2": float16,
        "f4": float32,
        "f8": float64,
    }
)


def canonical_heat_type(a_type: Any) -> type:
    """Normalize python/numpy/jax/string types to the heat class
    (reference types.py:275-340)."""
    if isinstance(a_type, type) and issubclass(a_type, generic):
        if a_type._jax_type is None:
            raise TypeError(f"data type {a_type!r} is abstract and cannot back an array")
        return a_type
    if a_type is builtins.bool:
        return bool
    if a_type is builtins.int:
        return int32
    if a_type is builtins.float:
        return float32
    if isinstance(a_type, str):
        key = a_type.strip().lower()
        if key in __name_map:
            return __name_map[key]
        try:
            return __dtype_map[np.dtype(key)]
        except (TypeError, KeyError):
            raise TypeError(f"data type {a_type!r} not understood")
    try:
        return __dtype_map[np.dtype(a_type)]
    except (TypeError, KeyError):
        raise TypeError(f"data type {a_type!r} not understood")


def heat_type_of(obj: Any) -> type:
    """Infer the heat type of an array-like / scalar / iterable
    (reference types.py:343-441)."""
    from .dndarray import DNDarray

    if isinstance(obj, DNDarray):
        return obj.dtype
    if isinstance(obj, (jnp.ndarray, np.ndarray)) or hasattr(obj, "dtype"):
        return canonical_heat_type(obj.dtype)
    if isinstance(obj, builtins.bool):
        return bool
    if isinstance(obj, numbers.Integral):
        return int32
    if isinstance(obj, numbers.Real):
        return float32
    if isinstance(obj, (list, tuple)):
        if len(obj) == 0:
            return float32
        arr = np.asarray(obj)
        if arr.dtype == object:
            raise TypeError(f"cannot determine heat type of ragged/object {type(obj)}")
        return _infer_list_type(obj, arr)
    raise TypeError(f"cannot determine heat type of {type(obj)}")


def _float_fits(arr: np.ndarray, ht_type: type) -> builtins.bool:
    """True when every finite value of float64 ``arr`` survives a cast to
    float ``ht_type``: no finite overflow to inf AND no nonzero flush to
    zero (finfo works for float16/bfloat16/float32 alike — bfloat16's is
    ml_dtypes-backed)."""
    info = np.finfo(ht_type._np_type)
    finite = arr[np.isfinite(arr)]
    if not finite.size:
        return True
    mags = np.abs(finite)
    if builtins.float(mags.max()) > builtins.float(info.max):
        return False
    nonzero = mags[mags > 0]
    if nonzero.size and builtins.float(nonzero.min()) < builtins.float(
        info.smallest_subnormal
    ):
        return False
    return True


def _float32_fits(arr: np.ndarray) -> builtins.bool:
    return _float_fits(arr, float32)


def _infer_list_type(obj, arr: np.ndarray) -> type:
    """Heat type of a list/tuple whose numpy image is ``arr``.

    Python-scalar leaves keep the package's 32-bit default (the reference
    scans element TYPES, types.py:343-441; np.asarray would widen
    [1, 2, 3] to int64) — but only when the VALUES fit: a list holding
    2**40 must still type int64, and 1e-300 must not flush to zero.
    Explicitly-typed numpy leaves keep their dtype; mixed lists promote
    per distinct element type.  Value probes are C-speed (min/max on
    ``arr``); the element-type walk only runs for the ambiguous
    int64/float64 dtypes and builds one representative per distinct type.
    """
    if arr.dtype not in (np.int64, np.float64):
        return canonical_heat_type(arr.dtype)  # unambiguous: numpy's probe
    # one representative per distinct leaf (type, dtype), any nesting
    # depth, so flat and nested infer alike (the reference's recursive
    # scan, types.py:343-441, has the same property and the same cost).
    # This walk is Python-speed over every leaf — several times the
    # C-speed np.asarray pass — but it only runs for the ambiguous
    # int64/float64 images, and Python-list ingestion is already the
    # slow path: bulk data should arrive as numpy/jax arrays
    reps: dict = {}
    stack = [obj]
    while stack:
        for el in stack.pop():
            if isinstance(el, (list, tuple)):
                stack.append(el)
            else:
                # arrays of different dtypes share type(el) — key on dtype too
                reps.setdefault((type(el), getattr(el, "dtype", None)), el)
    explicit_types = [
        v for v in reps.values()
        if isinstance(v, (np.generic, np.ndarray)) or hasattr(v, "dtype")
    ]
    if explicit_types:
        # promote one representative per distinct type: python
        # scalars contribute their 32-bit default, explicit numpy
        # leaves their verbatim dtype...
        result = None
        for v in reps.values():
            t = (
                canonical_heat_type(v.dtype)
                if isinstance(v, (np.generic, np.ndarray)) or hasattr(v, "dtype")
                else heat_type_of(v)
            )
            result = t if result is None else promote_types(result, t)
        # ...then re-apply the VALUE guard over the whole list (arr
        # covers every element): [np.int32(1), 2**40] must widen to
        # int64, not truncate through the promoted int32
        if issubclass(result, integer) and arr.dtype == np.int64 and arr.size:
            info = iinfo(result)
            lo, hi = builtins.int(arr.min()), builtins.int(arr.max())
            if lo < info.min or hi > info.max:
                result = promote_types(result, int64)
        elif (
            issubclass(result, floating)
            and result is not float64
            and arr.dtype == np.float64
            and arr.size
            and not _float_fits(arr, result)
        ):
            # generic over the narrow floats: float16/bfloat16 promotes
            # widen minimally (next type that holds every value)
            result = (
                float32
                if result is not float32 and _float_fits(arr, float32)
                else float64
            )
        return result
    # pure python-scalar leaves: 32-bit default, value-range guarded
    if not arr.size:
        return int32 if arr.dtype == np.int64 else float32
    if arr.dtype == np.int64:
        lo, hi = builtins.int(arr.min()), builtins.int(arr.max())
        return int64 if lo < -(2**31) or hi >= 2**31 else int32
    return float32 if _float32_fits(arr) else float64


def heat_type_is_exact(ht_dtype: Any) -> builtins.bool:
    """True for integer/bool types (reference types.py helper)."""
    t = canonical_heat_type(ht_dtype)
    return issubclass(t, integer) or t is bool


def heat_type_is_inexact(ht_dtype: Any) -> builtins.bool:
    """True for floating types."""
    return issubclass(canonical_heat_type(ht_dtype), floating)


def issubdtype(arg1: Any, arg2: type) -> builtins.bool:
    """Hierarchy test, e.g. ``ht.issubdtype(ht.int32, ht.integer)``."""
    try:
        t1 = canonical_heat_type(arg1)
    except TypeError:
        t1 = arg1
    if not (isinstance(t1, type) and issubclass(t1, generic)):
        raise TypeError(f"{arg1!r} is not a heat type")
    return issubclass(t1, arg2)


# ---------------------------------------------------------------------- #
# casting / promotion (reference types.py:444-576)                        #
# ---------------------------------------------------------------------- #
def __width(t: type) -> builtins.int:
    return np.dtype(t._np_type).itemsize * 8


def can_cast(from_: Any, to: Any, casting: str = "intuitive") -> builtins.bool:
    """Casting admissibility (reference types.py:444-539).

    Rules: ``'no'``, ``'safe'``, ``'same_kind'``, ``'unsafe'`` follow numpy;
    the default ``'intuitive'`` = safe **plus** integer→floating of at least
    the same bit width (e.g. int32→float32), matching the reference's
    default rule.
    """
    if not isinstance(casting, str):
        raise TypeError(f"expected casting to be str, found {type(casting)}")
    if casting not in ("no", "safe", "same_kind", "unsafe", "intuitive"):
        # validate BEFORE any early return so a typo'd rule never silently
        # acts as one of the real ones (reference types.py:502-506)
        raise ValueError(f"invalid casting rule {casting!r}")
    if not isinstance(from_, type):
        from_ = heat_type_of(from_)
    src = canonical_heat_type(from_)
    dst = canonical_heat_type(to)
    if casting == "no":
        return src is dst
    if casting == "unsafe":
        return True
    s_np, d_np = np.dtype(src._np_type), np.dtype(dst._np_type)
    if casting == "same_kind":
        if src is bfloat16 or dst is bfloat16:
            return issubclass(dst, floating)
        return np.can_cast(s_np, d_np, casting="same_kind")
    # safe / intuitive
    if src is bfloat16:
        safe = dst in (bfloat16, float32, float64)
    elif dst is bfloat16:
        # bf16 has 8 mantissa bits → represents all integers only up to 256
        safe = src in (bool, uint8, int8)
    else:
        safe = np.can_cast(s_np, d_np, casting="safe")
    if safe or casting == "safe":
        return safe
    # casting == "intuitive": safe + int→float of at least the same width
    if (issubclass(src, integer) or src is bool) and issubclass(dst, floating):
        return __width(dst) >= min(__width(src), 32) or dst in (float32, float64)
    return False


def promote_types(type1: Any, type2: Any) -> type:
    """Smallest type both inputs safely cast to (reference types.py:542-574).

    Delegates to JAX's promotion lattice, which reproduces the
    torch-flavored semantics the reference tabulates by hand
    (int + float32 → float32) and extends it to bfloat16.
    """
    t1 = canonical_heat_type(type1)
    t2 = canonical_heat_type(type2)
    return canonical_heat_type(jnp.promote_types(t1._jax_type, t2._jax_type))


def result_type(*operands) -> type:
    """Promoted type over arbitrarily many operands/scalars (numpy-parity
    helper used throughout the op engine)."""
    t = None
    for op in operands:
        ot = op if isinstance(op, type) and issubclass(op, generic) else heat_type_of(op)
        t = ot if t is None else promote_types(t, ot)
    return t


# ---------------------------------------------------------------------- #
# finfo / iinfo (reference types.py:577-688)                              #
# ---------------------------------------------------------------------- #
class finfo:
    """Machine limits for floating types (reference types.py:577-634)."""

    def __new__(cls, dtype):
        t = canonical_heat_type(dtype)
        if not issubclass(t, floating):
            raise TypeError(f"data type {t.__name__} not inexact")
        info = jnp.finfo(t._jax_type)
        obj = object.__new__(cls)
        obj.bits = info.bits
        obj.eps = builtins.float(info.eps)
        obj.max = builtins.float(info.max)
        obj.min = builtins.float(info.min)
        obj.tiny = builtins.float(info.tiny)
        obj.dtype = t
        return obj

    def __repr__(self):
        return f"finfo(dtype={self.dtype.__name__}, eps={self.eps}, max={self.max})"


class iinfo:
    """Machine limits for integer types (reference types.py:637-688)."""

    def __new__(cls, dtype):
        t = canonical_heat_type(dtype)
        if not (issubclass(t, integer) or t is bool):
            raise TypeError(f"data type {t.__name__} not an integer type")
        info = jnp.iinfo(t._jax_type) if t is not bool else None
        obj = object.__new__(cls)
        if t is bool:
            obj.bits, obj.min, obj.max = 8, 0, 1
        else:
            obj.bits, obj.min, obj.max = info.bits, builtins.int(info.min), builtins.int(info.max)
        obj.dtype = t
        return obj

    def __repr__(self):
        return f"iinfo(dtype={self.dtype.__name__}, min={self.min}, max={self.max})"
