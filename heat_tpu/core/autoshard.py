"""Cost-driven auto-layout: ``ht.autoshard``.

``autoshard(fn)`` is a layer over :func:`heat_tpu.fuse` that stops
treating the hand-written resplit placements as law.  It statically
summarizes ``fn``'s layout traffic (:func:`heat_tpu.analysis.splitflow.
layout_summary` — per-seam shapes, dtypes, hand layouts, and dead-chain
provenance), searches the declared placement space against the comm
layer's own cost model (:class:`heat_tpu.comm._costs.LayoutSolver` —
wire bytes, then :func:`~heat_tpu.comm._costs.critical_path_ms` under
the active overlap policy, then a deterministic layout-rank tie-break),
and compiles the argmin plan into ONE cached program per (arguments ×
comm × policy) signature, exactly like ``fuse`` — the plan fingerprint
joins the cache key.

Because a chain's final placement stays pinned to the hand layout, the
solved pipeline is a drop-in: identical output metadata,
bitwise-identical values, at most the hand plan's wire bytes (the solver
may elide or reroute interior hops, never add mandatory ones —
docs/design.md §21).

Fallback ladder, always safe:

1. summary incomplete (control flow around seams, in-place ``resplit_``,
   helper traffic, unknown shapes) or a grid (>1-D) comm → plain
   ``fuse(fn)``, hand layout untouched;
2. summary complete → ``fuse(fn, layout_plan=plan)``: resplits inside
   the trace consult the plan (:func:`heat_tpu.core._tracing.
   applying_layout_plan`), one dispatch per call, and each call credits
   the plan's modeled bytes to the telemetry wire ledger (traced
   resplits cannot self-account — there is no eager collective to
   observe — and the model IS the runtime's own arithmetic, so ledger
   and plan agree byte-for-byte);
3. ``fn`` cannot trace (:class:`FuseTraceError` — value-forcing host
   code) → eager execution under the same plan: each resplit consumes
   its override at the call site and self-accounts as usual.

The plan is policy-keyed: changing collective precision, redistribution
policy, or the overlap switch re-solves (and re-prices) rather than
replaying a plan optimized for a different cost surface.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Dict, Optional, Tuple

from ..telemetry import _core as _tel
from ._tracing import FuseTraceError, applying_layout_plan
from .dndarray import DNDarray
from .fuse import _FusedFunction

__all__ = ["autoshard"]

#: summary sentinel: "not computed yet" (None is a valid failure result)
_UNSET = object()


def _policy_key(comm) -> Tuple:
    """Everything that changes the cost surface a plan was solved on."""
    from ..comm import (
        get_collective_precision,
        get_collective_threshold,
        get_overlap,
        get_redistribution,
        get_redistribution_threshold,
    )

    return (
        comm,
        get_collective_precision(),
        get_collective_threshold(),
        get_redistribution(),
        get_redistribution_threshold(),
        get_overlap(),
    )


class _AutoshardFunction:
    """The callable returned by :func:`autoshard`."""

    def __init__(self, fn: Callable, donate: bool = False):
        self._fn = fn
        self._donate = bool(donate)
        self._summary: Any = _UNSET
        #: policy key -> ["fused"|"eager", plan, fused callable or None]
        self._programs: Dict[Tuple, list] = {}
        self._plain: Optional[_FusedFunction] = None
        functools.update_wrapper(self, fn)

    # ------------------------------------------------------------------ #
    # static side                                                         #
    # ------------------------------------------------------------------ #
    def _summarize(self):
        """The pipeline's layout-transfer summary, computed once.

        Any static-analysis failure (no retrievable source, dynamically
        built function) degrades to ``None`` — the plain-fuse rung of the
        fallback ladder — never to an exception at call time.
        """
        if self._summary is not _UNSET:
            return self._summary
        summary = None
        try:
            from ..analysis.core import FileContext
            from ..analysis.splitflow import build_program, layout_summary

            path = inspect.getsourcefile(self._fn)
            if path is not None:
                ctx = FileContext(path)
                if not ctx.skip_file:
                    program = build_program([ctx])
                    qualname = self._fn.__qualname__.replace(".<locals>", "")
                    summary = layout_summary(program, qualname)
        except Exception:  # static analysis must never break execution
            summary = None
        if summary is not None and not summary.get("complete"):
            if _tel.enabled:
                _tel.record_event(
                    "autoshard.fallback",
                    site=f"autoshard:{getattr(self._fn, '__name__', '?')}",
                    reason="incomplete-summary",
                    notes=tuple(summary.get("notes", ()))[:4],
                )
            summary = None
        self._summary = summary
        return summary

    def _program(self, comm):
        """The (mode, plan, callable) entry for the active policy."""
        key = _policy_key(comm)
        entry = self._programs.get(key)
        if entry is not None:
            return entry
        summary = self._summarize()
        if summary is None or getattr(comm, "mesh_ndim", 1) > 1:
            # grid plan application is future work (docs/design.md §21):
            # the runtime override seam is 1-D; a grid comm still gets
            # whole-program compilation, just with the hand layout
            entry = ["plain", None, self._plain_fused()]
            self._programs[key] = entry
            return entry

        from ..comm import (
            get_collective_precision,
            get_collective_threshold,
            get_overlap,
        )
        from ..comm._costs import LayoutSolver

        solver = LayoutSolver(
            comm.size,
            precision=get_collective_precision(),
            threshold=get_collective_threshold(),
            overlap=(get_overlap() == "on"),
        )
        plan = solver.solve(summary)
        if _tel.enabled:
            _tel.record_event(
                "autoshard.plan",
                site=f"autoshard:{getattr(self._fn, '__name__', '?')}",
                fingerprint=plan["fingerprint"],
                mesh=plan["mesh"],
                seams=len(plan["decisions"]),
                elided=sum(1 for d in plan["decisions"] if d["elide"]),
                modeled_wire_bytes=plan["modeled_wire_bytes"],
                hand_wire_bytes=plan["hand_wire_bytes"],
            )
            _tel.inc("autoshard.plans.solved")
        fused = _FusedFunction(self._fn, donate=self._donate, layout_plan=plan)
        entry = ["fused", plan, fused]
        self._programs[key] = entry
        return entry

    def _plain_fused(self) -> _FusedFunction:
        if self._plain is None:
            self._plain = _FusedFunction(self._fn, donate=self._donate)
        return self._plain

    def plan(self, comm=None) -> Optional[dict]:
        """The solved plan for ``comm`` (default communicator when
        ``None``) under the CURRENT comm policies — introspection for
        tests, benches, and docs.  ``None`` on the plain-fuse fallback."""
        from .communication import sanitize_comm

        return self._program(sanitize_comm(comm))[1]

    # ------------------------------------------------------------------ #
    # runtime side                                                        #
    # ------------------------------------------------------------------ #
    def __call__(self, *args, **kwargs):
        import jax

        comm = None
        leaves = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, DNDarray)
        )[0]
        from .communication import XlaCommunication, sanitize_comm

        for leaf in leaves:
            if isinstance(leaf, DNDarray):
                comm = leaf.comm
                break
            if comm is None and isinstance(leaf, XlaCommunication):
                comm = leaf
        comm = sanitize_comm(comm)

        entry = self._program(comm)
        mode, plan, fused = entry
        if mode == "plain":
            return fused(*args, **kwargs)
        if mode == "eager":
            with applying_layout_plan(plan["decisions"]):
                return self._fn(*args, **kwargs)

        # fused-with-plan: one dispatch, then credit the plan's modeled
        # bytes to the wire ledger (nothing inside the compiled program
        # can — the collectives were folded in at trace time)
        try:
            result = fused(*args, **kwargs)
        except (FuseTraceError, jax.errors.JAXTypeError):
            # value-forcing host code (iterative fits, data-dependent
            # Python control flow) cannot trace — run the pipeline
            # eagerly under the same plan; each resplit consumes its
            # override at the call site and self-accounts as usual
            entry[0] = "eager"
            entry[2] = None
            if _tel.enabled:
                _tel.record_event(
                    "autoshard.fallback",
                    site=f"autoshard:{getattr(self._fn, '__name__', '?')}",
                    reason="untraceable",
                )
            with applying_layout_plan(plan["decisions"]):
                return self._fn(*args, **kwargs)
        if _tel.enabled:
            self._credit(plan)
        return result

    @staticmethod
    def _credit(plan: dict) -> None:
        for d in plan["decisions"]:
            if d["wire_bytes"] <= 0:
                continue  # elided or zero-traffic seam: nothing shipped
            _tel.account_bytes(
                "resplit", d["mode"] or "f32", d["exact_bytes"], d["wire_bytes"]
            )
            _tel.inc("comm.resplit.autoshard")


def autoshard(fn: Optional[Callable] = None, *, donate: bool = False):
    """Solve the cheapest sharding plan for a pipeline, then compile it.

    Use as a decorator (``@ht.autoshard``) or inline
    (``solved = ht.autoshard(my_pipeline)``).  Output metadata and values
    are identical to the hand-written pipeline; interior layout hops may
    be elided or rerouted when the cost model prices them cheaper.  See
    the module docstring for the fallback ladder and docs/design.md §21
    for search-space and determinism semantics.
    """
    if fn is None:
        return functools.partial(autoshard, donate=donate)
    return _AutoshardFunction(fn, donate=donate)
