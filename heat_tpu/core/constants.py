"""Mathematical constants (reference: heat/core/constants.py)."""

import numpy as np

__all__ = ["e", "Euler", "inf", "Inf", "Infty", "Infinity", "nan", "NaN", "pi"]

e = float(np.e)
"""Euler's number."""
pi = float(np.pi)
"""Archimedes' constant."""
inf = float("inf")
"""IEEE positive infinity."""
nan = float("nan")
"""IEEE not-a-number."""

# aliases (numpy/reference parity; the uppercase module-level names
# INF/NAN/NINF/PI/E mirror reference constants.py:6-10)
Euler = e
Inf = inf
Infty = inf
Infinity = inf
NaN = nan
INF = inf
NAN = nan
NINF = -inf
PI = pi
E = e
