"""sklearn-compatible estimator API.

Reference: heat/core/base.py:5-297 — ``BaseEstimator`` with introspective
``get_params``/``set_params`` plus the fit/predict mixins and estimator-type
predicates.  Pure-Python API contracts; identical semantics here.
"""

from __future__ import annotations

import functools
import inspect
import types as _types
from typing import Any, Dict

from ..telemetry import _core as _tel

__all__ = [
    "BaseEstimator",
    "ClassificationMixin",
    "ClusteringMixin",
    "RegressionMixin",
    "TransformMixin",
    "is_classifier",
    "is_estimator",
    "is_clusterer",
    "is_regressor",
    "is_transformer",
]


def _spanned_method(meth, label: str):
    """Wrap an estimator entry point in a telemetry span.

    The wrapper is a single flag predicate per call while telemetry is
    disabled; enabled, every ``fit``/``predict`` lands in the per-site
    span aggregates under ``fit:<ClassName>`` / ``predict:<ClassName>``
    (the class is resolved at call time, so subclasses inheriting a
    wrapped method report under their own name)."""

    @functools.wraps(meth)
    def wrapper(self, *args, **kwargs):
        if not _tel.enabled:
            return meth(self, *args, **kwargs)
        with _tel.span(f"{label}:{type(self).__name__}"):
            return meth(self, *args, **kwargs)

    wrapper._telemetry_wrapped = True
    return wrapper


class BaseEstimator:
    """Base class for all estimators (reference base.py:5-90)."""

    def __init_subclass__(cls, **kwargs):
        # every concrete estimator's fit/predict emits a telemetry span
        # automatically — no per-estimator instrumentation to forget
        super().__init_subclass__(**kwargs)
        for name in ("fit", "predict"):
            meth = cls.__dict__.get(name)
            if (
                isinstance(meth, _types.FunctionType)
                and not getattr(meth, "_telemetry_wrapped", False)
            ):
                setattr(cls, name, _spanned_method(meth, name))

    @classmethod
    def _parameter_names(cls):
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        return sorted(
            p.name
            for p in sig.parameters.values()
            if p.name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        )

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        """Parameters of this estimator (reference base.py:30-55)."""
        params = {}
        for name in self._parameter_names():
            value = getattr(self, name, None)
            if deep and hasattr(value, "get_params"):
                for sub_name, sub_value in value.get_params().items():
                    params[f"{name}__{sub_name}"] = sub_value
            params[name] = value
        return params

    def set_params(self, **params) -> "BaseEstimator":
        """Set estimator parameters (reference base.py:56-90)."""
        if not params:
            return self
        valid = self.get_params(deep=True)
        nested = {}
        for key, value in params.items():
            key, delim, sub_key = key.partition("__")
            if key not in valid:
                raise ValueError(f"Invalid parameter {key} for estimator {self}")
            if delim:
                nested.setdefault(key, {})[sub_key] = value
            else:
                setattr(self, key, value)
                valid[key] = value
        for key, sub_params in nested.items():
            getattr(self, key).set_params(**sub_params)
        return self

    def __repr__(self, N_CHAR_MAX: int = 700) -> str:
        params = ", ".join(f"{k}={v!r}" for k, v in sorted(self.get_params(deep=False).items()))
        return f"{self.__class__.__name__}({params})"[:N_CHAR_MAX]

    def _checkpoint_attrs(self):
        """Instance attributes :func:`heat_tpu.save_estimator` persists
        beyond the constructor params.  Default: every public ``*_``
        attribute (the sklearn fitted convention).  Estimators whose
        fitted state lives in private storage override this."""
        return [
            n for n in vars(self) if n.endswith("_") and not n.startswith("_")
        ]

    def save(self, path: str) -> None:
        """Checkpoint this estimator — constructor params plus fitted
        state — to one HDF5 file (extension; the reference persists data
        only, SURVEY §5.4).  See :func:`heat_tpu.save_estimator`."""
        from .checkpoint import save_estimator

        save_estimator(self, path)

    @classmethod
    def load(cls, path: str) -> "BaseEstimator":
        """Restore an estimator saved with :meth:`save`; raises TypeError
        if the checkpoint holds a different estimator class than ``cls``
        (call ``BaseEstimator.load`` / ``ht.load_estimator`` to accept
        any)."""
        from .checkpoint import load_estimator

        est = load_estimator(path)
        if cls is not BaseEstimator and not isinstance(est, cls):
            raise TypeError(
                f"{path} holds a {type(est).__name__}, not a {cls.__name__}"
            )
        return est


class ClassificationMixin:
    """fit/predict contract for classifiers (reference base.py:92-141)."""

    _estimator_type = "classifier"

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


class ClusteringMixin:
    """fit/fit_predict contract for clusterers (reference base.py:142-177)."""

    _estimator_type = "clusterer"

    def fit(self, x):
        raise NotImplementedError()

    def fit_predict(self, x):
        self.fit(x)
        return self.predict(x)


class RegressionMixin:
    """fit/predict contract for regressors (reference base.py:178-227)."""

    _estimator_type = "regressor"

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


class TransformMixin:
    """fit/transform contract (numpy/sklearn-parity extension)."""

    def fit(self, x):
        raise NotImplementedError()

    def transform(self, x):
        raise NotImplementedError()

    def fit_transform(self, x):
        self.fit(x)
        return self.transform(x)


def is_estimator(obj) -> bool:
    """(reference base.py:228-245)"""
    return isinstance(obj, BaseEstimator)


def is_classifier(obj) -> bool:
    """(reference base.py:246-262)"""
    return getattr(obj, "_estimator_type", None) == "classifier"


def is_clusterer(obj) -> bool:
    """(reference base.py:263-279)"""
    return getattr(obj, "_estimator_type", None) == "clusterer"


def is_regressor(obj) -> bool:
    """(reference base.py:280-297)"""
    return getattr(obj, "_estimator_type", None) == "regressor"


def is_transformer(obj) -> bool:
    """TransformMixin predicate (extension)."""
    return isinstance(obj, TransformMixin)
