"""Device abstraction for heat_tpu.

Reference: heat/core/devices.py:9-135 — there, a ``Device`` names a torch
device per MPI process, with GPUs assigned round-robin by rank
(devices.py:66-74).  Here a :class:`Device` names a **JAX platform** whose
entire device set forms the mesh; placement of individual shards is XLA's
job, so there is no per-rank device arithmetic.  ``ht.cpu`` always exists,
``ht.tpu`` exists when TPU hardware (or an emulated TPU platform) is
present, and ``ht.gpu`` when CUDA/ROCm devices are visible.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = ["Device", "cpu", "get_device", "sanitize_device", "use_device"]


class Device:
    """A logical compute platform binding arrays to a device mesh.

    Parameters
    ----------
    device_type : str
        Platform name understood by JAX: ``'cpu'``, ``'tpu'``, ``'gpu'``.

    Reference: heat/core/devices.py:9-56 (``Device`` with device_type/
    device_id/torch_device); the id is dropped because a single controller
    addresses every device of the platform through the mesh.
    """

    def __init__(self, device_type: str):
        self.__device_type = str(device_type).strip().lower()

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def platform(self) -> str:
        """JAX platform name (alias of :attr:`device_type`)."""
        return self.__device_type

    def jax_devices(self):
        """All JAX devices of this platform (the mesh population)."""
        return jax.devices(self.__device_type)

    def __str__(self) -> str:
        return self.__device_type

    def __repr__(self) -> str:
        return f"device({self.__device_type})"

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type
        if isinstance(other, str):
            return self.device_type == other.strip().lower()
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self.device_type)


# ---------------------------------------------------------------------- #
# platform singletons (reference devices.py:59-74)                        #
# ---------------------------------------------------------------------- #
cpu = Device("cpu")
"""The CPU device — always available (reference devices.py:59)."""

__registry = {"cpu": cpu}

# name -> Device | None, filled on first access.  Probing calls
# jax.devices(), which initializes the XLA backend — deferring it keeps
# `import heat_tpu` backend-free so jax.distributed / init_multihost can
# run first (jax requires distributed init before any backend touch).
_probe_cache: dict = {}


def __probe_platform(name: str) -> Optional[Device]:
    try:
        if jax.devices(name):
            dev = Device(name)
            __registry[name] = dev
            return dev
    except RuntimeError:
        pass
    return None


def _accelerator(name: str) -> Optional[Device]:
    """The 'tpu'/'gpu' singleton, probed lazily (None when absent).

    The experimental 'axon' tunnel platform exposes TPU chips under a
    custom platform name; it surfaces as ``tpu`` when the canonical name
    is absent."""
    if name not in _probe_cache:
        dev = __probe_platform(name)
        if dev is None and name == "tpu":
            dev = __probe_platform("axon")
            if dev is not None:
                __registry["tpu"] = dev
        _probe_cache[name] = dev
    return _probe_cache[name]


def __getattr__(name: str):
    """PEP 562: ``devices.tpu`` / ``devices.gpu`` are probed on first
    access, mirroring the reference's conditional ``gpu`` singleton
    (devices.py:66-74) without touching the backend at import time.

    Trade-off: star-imports (``from heat_tpu import *``) do not consult
    this hook, so they bind only ``cpu``; use attribute access
    (``ht.tpu``) for accelerators — the lazy probe is what keeps
    ``import heat_tpu`` backend-free for :func:`ht.init_multihost`."""
    if name in ("tpu", "gpu"):
        return _accelerator(name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__default_device: Device = None


def _accelerator_or_cpu() -> Device:
    for name in ("tpu", "gpu"):
        dev = _accelerator(name)
        if dev is not None:
            return dev
    return cpu


def get_device() -> Device:
    """The process-global default device (reference devices.py:80-89).
    Defaults to the best available platform: tpu > gpu > cpu."""
    global __default_device
    if __default_device is None:
        __default_device = _accelerator_or_cpu()
    return __default_device


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """Set the process-global default device (reference devices.py:124-135)."""
    global __default_device
    __default_device = sanitize_device(device) if device is not None else _accelerator_or_cpu()


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """Normalize a device argument, substituting the default for None
    (reference devices.py:92-121)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    name = str(device).strip().lower()
    if name in __registry:
        return __registry[name]
    # route tpu/gpu through the lazy singleton (it knows the axon->tpu
    # platform aliasing); other names probe directly
    dev = _accelerator(name) if name in ("tpu", "gpu") else __probe_platform(name)
    if dev is not None:
        return dev
    raise ValueError(f"Unknown device or platform not available: {device!r}")
