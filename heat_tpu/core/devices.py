"""Device abstraction for heat_tpu.

Reference: heat/core/devices.py:9-135 — there, a ``Device`` names a torch
device per MPI process, with GPUs assigned round-robin by rank
(devices.py:66-74).  Here a :class:`Device` names a **JAX platform** whose
entire device set forms the mesh; placement of individual shards is XLA's
job, so there is no per-rank device arithmetic.  ``ht.cpu`` always exists,
``ht.tpu`` exists when TPU hardware (or an emulated TPU platform) is
present, and ``ht.gpu`` when CUDA/ROCm devices are visible.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = ["Device", "cpu", "get_device", "sanitize_device", "use_device"]


class Device:
    """A logical compute platform binding arrays to a device mesh.

    Parameters
    ----------
    device_type : str
        Platform name understood by JAX: ``'cpu'``, ``'tpu'``, ``'gpu'``.

    Reference: heat/core/devices.py:9-56 (``Device`` with device_type/
    device_id/torch_device); the id is dropped because a single controller
    addresses every device of the platform through the mesh.
    """

    def __init__(self, device_type: str):
        self.__device_type = str(device_type).strip().lower()

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def platform(self) -> str:
        """JAX platform name (alias of :attr:`device_type`)."""
        return self.__device_type

    def jax_devices(self):
        """All JAX devices of this platform (the mesh population)."""
        return jax.devices(self.__device_type)

    def __str__(self) -> str:
        return self.__device_type

    def __repr__(self) -> str:
        return f"device({self.__device_type})"

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type
        if isinstance(other, str):
            return self.device_type == other.strip().lower()
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self.device_type)


# ---------------------------------------------------------------------- #
# platform singletons (reference devices.py:59-74)                        #
# ---------------------------------------------------------------------- #
cpu = Device("cpu")
"""The CPU device — always available (reference devices.py:59)."""

__registry = {"cpu": cpu}


def __probe_platform(name: str) -> Optional[Device]:
    try:
        if jax.devices(name):
            dev = Device(name)
            __registry[name] = dev
            return dev
    except RuntimeError:
        pass
    return None


tpu = __probe_platform("tpu")
"""The TPU device, or None when no TPU platform is present (analogous to the
conditional ``gpu`` singleton, reference devices.py:66-74)."""

gpu = __probe_platform("gpu")
"""The GPU device, or None when no GPU platform is present."""

# the experimental 'axon' tunnel platform exposes TPU chips under a custom
# platform name; surface it as `tpu` when the canonical name is absent
if tpu is None:
    for _plat in ("axon",):
        _dev = __probe_platform(_plat)
        if _dev is not None:
            tpu = _dev
            __registry["tpu"] = _dev
            break

# export the accelerator singletons that exist, mirroring the reference's
# conditional `gpu` definition (devices.py:66-74): present => importable
# as ht.tpu / ht.gpu, absent => the attribute stays None and unexported
if tpu is not None:
    __all__.append("tpu")
if gpu is not None:
    __all__.append("gpu")

__default_device: Device = None


def _accelerator_or_cpu() -> Device:
    if tpu is not None:
        return tpu
    if gpu is not None:
        return gpu
    return cpu


def get_device() -> Device:
    """The process-global default device (reference devices.py:80-89).
    Defaults to the best available platform: tpu > gpu > cpu."""
    global __default_device
    if __default_device is None:
        __default_device = _accelerator_or_cpu()
    return __default_device


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """Set the process-global default device (reference devices.py:124-135)."""
    global __default_device
    __default_device = sanitize_device(device) if device is not None else _accelerator_or_cpu()


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """Normalize a device argument, substituting the default for None
    (reference devices.py:92-121)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    name = str(device).strip().lower()
    if name in __registry:
        return __registry[name]
    dev = __probe_platform(name)
    if dev is not None:
        return dev
    raise ValueError(f"Unknown device or platform not available: {device!r}")
