"""Distributed QR decomposition.

Reference: heat/core/linalg/qr.py:10-988 — a tiled CAQR over
``SquareDiagTiles`` with per-tile Householder factorizations, pairwise tile
row merges, async Q-factor shipping, and a column-cyclic split=1 loop.

TPU-first design (per SURVEY.md §7 build plan, item 8): **TSQR**
(communication-avoiding tall-skinny QR).  For a row-split matrix, each shard
computes a local QR; the stacked R factors are QR'd again; one round of
all-gather replaces the reference's point-to-point tile choreography.  The
merge tree is expressed with ``shard_map`` when the row count divides the
mesh, falling back to XLA's own lowering otherwise.  split=1 and replicated
inputs use on-device ``jnp.linalg.qr`` directly (same as reference
split=None, qr.py:70-94).
"""

from __future__ import annotations

import collections
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from .. import factories, types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")


def _tsqr(a: DNDarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-stage TSQR on the mesh (replaces reference qr.py:303-816).

    Stage 1: per-shard local QR inside shard_map (runs on every device in
    parallel).  Stage 2: the (size·n, n) stack of R factors — tiny — is
    QR'd once, and local Qs are corrected by the matching R-block.
    """
    comm = a.comm
    mesh = comm.mesh
    axis = comm.axis_name
    m, n = a.shape
    size = comm.size
    arr = a.larray

    if size == 1 or m % size != 0 or m // size < n:
        # not shard-decomposable: one on-device QR (XLA distributes)
        q, r = jnp.linalg.qr(arr)
        return q, r

    def _local_qr(block):
        q, r = jnp.linalg.qr(block)
        return q, r

    local_qr = jax.shard_map(
        _local_qr,
        mesh=mesh,
        in_specs=PartitionSpec(axis, None),
        out_specs=(PartitionSpec(axis, None), PartitionSpec(axis, None)),
    )
    q1, r1 = jax.jit(local_qr)(arr)  # q1: (m, n) row-split; r1: (size*n, n)

    # stage 2 on the gathered R stack (size*n × n — small, replicated)
    r1_full = comm.allgather(r1)
    q2, r = jnp.linalg.qr(r1_full)  # q2: (size*n, n)

    # combine: each shard's Q_local @ Q2-block
    from .basics import _precision

    def _combine(q1_blk, q2_blk):
        return jnp.matmul(q1_blk, q2_blk, precision=_precision())

    combine = jax.shard_map(
        _combine,
        mesh=mesh,
        in_specs=(PartitionSpec(axis, None), PartitionSpec(axis, None)),
        out_specs=PartitionSpec(axis, None),
    )
    q = jax.jit(combine)(q1, q2)
    return q, r


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> QR:
    """Reduced QR factorization ``a = Q @ R`` (reference qr.py:10-302).

    ``tiles_per_proc`` is accepted for API parity; the TSQR formulation has
    no tile-count knob (the reference uses it to trade latency for
    parallelism inside its tile grid, qr.py:31-36).
    """
    sanitize_in(a)
    if not isinstance(tiles_per_proc, (int, np.integer)):
        raise TypeError(f"tiles_per_proc must be an int, got {type(tiles_per_proc)}")
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D DNDarray, got {a.ndim}-d")

    dtype = a.dtype if types.heat_type_is_inexact(a.dtype) else types.float32
    arr = a.larray.astype(dtype.jax_type())

    if a.split == 0 and a.shape[0] >= a.shape[1]:
        aa = a if a.dtype is dtype else a.astype(dtype)
        q_g, r_g = _tsqr(aa if aa.larray is arr else DNDarray(arr, a.shape, dtype, a.split, a.device, a.comm, True))
    else:
        # replicated, split=1, or wide matrices: on-device QR, XLA plans
        # the distribution (reference split=1 loop qr.py:817-988)
        q_g, r_g = jnp.linalg.qr(arr)

    comm, device = a.comm, a.device
    if not calc_q:
        r_split = a.split if a.split == 1 else None
        r = DNDarray(comm.apply_sharding(r_g, r_split), tuple(r_g.shape), dtype, r_split, device, comm, True)
        return QR(None, r)

    q_split = 0 if a.split == 0 else a.split
    q = DNDarray(comm.apply_sharding(q_g, q_split), tuple(q_g.shape), dtype, q_split, device, comm, True)
    r_split = None if a.split != 1 else 1
    r = DNDarray(comm.apply_sharding(r_g, r_split), tuple(r_g.shape), dtype, r_split, device, comm, True)
    return QR(q, r)
