"""Distributed QR decomposition.

Reference: heat/core/linalg/qr.py:10-988 — a tiled CAQR over
``SquareDiagTiles`` with per-tile Householder factorizations, pairwise tile
row merges, async Q-factor shipping, and a column-cyclic split=1 loop.

TPU-first design (per SURVEY.md §7 build plan, item 8):

* **split=0 (row-sharded), m ≥ n: TSQR** (communication-avoiding
  tall-skinny QR).  Each shard computes a local QR; the stacked R factors
  are QR'd again; one all-gather replaces the reference's point-to-point
  tile choreography.  Non-divisible row counts go through the canonical
  zero-padding (``comm.pad_to_shards``): zero rows leave R untouched and —
  because the stage-2 Q's rows matching zero R-stack rows vanish — drop
  out of Q exactly, so ragged TSQR is exact for full-column-rank inputs
  (the same caveat any QR has for deficient ones).
* **split=1 (column-sharded), m ≥ n: blocked CGS2** — a panel loop in the
  spirit of the reference's column-cyclic ``__split1_qr_loop``
  (qr.py:817-988): each panel is orthogonalized against the accumulated Q
  by two classical Gram-Schmidt projections (MXU matmuls; provably stable
  for κ(A) ≲ 1/√ε) and factored locally.  ``tiles_per_proc`` subdivides
  each mesh position's panel, matching the reference's latency/parallelism
  knob (qr.py:31-36).
* replicated or wide (m < n) inputs use on-device ``jnp.linalg.qr`` (same
  as reference split=None, qr.py:70-94).

The one remaining distributed fallback — split=0 with more than
``m / n`` devices, where shards are wider than tall and TSQR's local QR
does not reduce — gathers with a ``UserWarning`` (the R stack would be as
large as the matrix itself, so gathering is also the bandwidth-optimal
choice there).
"""

from __future__ import annotations

import collections
import warnings
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from .. import factories, types
from .._compile import jitted
from .._jax_compat import shard_map
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from .._split_semantics import split_semantics as _split_semantics
from ...telemetry import _core as _tel

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")

# compiled replicated-golden twins, keyed on (mesh, shape, dtype, tiles,
# arm) — a plain dict, NOT the production jit cache: twin runs must not
# record dispatches (tests gate the kernel's count at exactly one)
_REFERENCE_CACHE: dict = {}


def _tsqr_program(comm):
    """The two-stage TSQR pipeline as a traceable ``f(x) -> (q, r)`` over
    a shard-padded row-split operand: per-shard local QR inside shard_map,
    a second QR of the small (size·n, n) R stack, and the Q-correction
    matmul.  Module-level so bench.py can embed the EXACT production
    compute graph inside its single-dispatch timing region; :func:`_tsqr`
    wraps it in the keyed-jit cache.  A single-device mesh degenerates to
    one on-device QR (what :func:`qr` dispatches there)."""
    if comm.size == 1:
        return jnp.linalg.qr

    mesh = comm.mesh
    axis = comm.axis_name

    from .basics import _precision

    def _local_qr(block):
        q, r = jnp.linalg.qr(block)
        return q, r  # plain tuple: QRResult confuses shard_map out_specs

    local_qr = shard_map(
        _local_qr,
        mesh=mesh,
        in_specs=PartitionSpec(axis, None),
        out_specs=(PartitionSpec(axis, None), PartitionSpec(axis, None)),
    )

    def _combine(q1_blk, q2_blk):
        return jnp.matmul(q1_blk, q2_blk, precision=_precision())

    combine = shard_map(
        _combine,
        mesh=mesh,
        in_specs=(PartitionSpec(axis, None), PartitionSpec(axis, None)),
        out_specs=PartitionSpec(axis, None),
    )

    def _f(x):
        q1, r1 = local_qr(x)  # q1: (padded_m, n) row-split; r1: (size*n, n)
        # stage 2 on the R stack (size*n × n — small, replicated)
        r1_full = jax.lax.with_sharding_constraint(r1, comm.sharding(2, None))
        q2, r = jnp.linalg.qr(r1_full)  # q2: (size*n, n)
        q = combine(q1, q2)
        return q, r

    return _f


def _tsqr(a: DNDarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-stage TSQR on the mesh (replaces reference qr.py:303-816).

    Stage 1: per-shard local QR inside shard_map (runs on every device in
    parallel).  Stage 2: the (size·n, n) stack of R factors — tiny — is
    QR'd again, and local Qs are corrected by the matching R-block.
    Handles any row count via canonical zero-padding.
    """
    comm = a.comm
    m, n = a.shape
    size = comm.size
    arr = a.larray

    if size == 1:
        return jnp.linalg.qr(arr)
    if comm.shard_width(m) < n:
        # shards wider than tall: local QR would not reduce and the R
        # stack would match the full matrix — gather and factor once
        warnings.warn(
            f"qr: {m}x{n} split=0 over {size} devices leaves shards with "
            f"fewer rows ({comm.shard_width(m)}) than columns ({n}); "
            "gathering for a single on-device QR (use fewer devices or a "
            "taller matrix for distributed TSQR)",
            stacklevel=3,
        )
        return jnp.linalg.qr(arr)

    arr_p = comm.pad_to_shards(arr, axis=0)
    q, r = jitted(("qr.tsqr", comm), lambda: _tsqr_program(comm))(arr_p)
    return comm.unpad(q, m, 0), r


def _cgs2_split1(a: DNDarray, tiles_per_proc: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked classical Gram-Schmidt with reorthogonalization over column
    panels (the TPU formulation of the reference's column-cyclic split=1
    loop, qr.py:817-988).

    Panels follow the mesh layout (one per position, subdivided by
    ``tiles_per_proc``), so each projection is a large MXU matmul whose
    collectives GSPMD schedules over ICI; no panel is ever gathered.
    """
    comm = a.comm
    m, n = a.shape
    arr = a.larray

    # panel plan: each position's column block, split into tiles_per_proc
    c = comm.shard_width(n)
    bounds = []
    for r in range(comm.size):
        start, stop = r * c, min((r + 1) * c, n)
        if start >= stop:
            continue
        width = stop - start
        t = max(1, min(int(tiles_per_proc), width))
        tw = -(-width // t)
        for j in range(t):
            s2 = start + j * tw
            e2 = min(s2 + tw, stop)
            if s2 < e2:
                bounds.append((s2, e2))

    def make():
        from .basics import _precision

        def _f(x):
            q_panels = []
            rows = []
            q_acc = None  # (m, k) accumulated orthonormal columns
            for (s, e) in bounds:
                panel = x[:, s:e]
                if q_acc is None:
                    y = jnp.zeros((0, e - s), x.dtype)
                else:
                    # CGS2: project out the accumulated basis twice
                    y1 = jnp.matmul(q_acc.T, panel, precision=_precision())
                    panel = panel - jnp.matmul(q_acc, y1, precision=_precision())
                    y2 = jnp.matmul(q_acc.T, panel, precision=_precision())
                    panel = panel - jnp.matmul(q_acc, y2, precision=_precision())
                    y = y1 + y2
                qk, rkk = jnp.linalg.qr(panel)
                q_panels.append(qk)
                # R rows for this panel: [Y; Rkk; 0] padded to n rows later
                rows.append((s, e, y, rkk))
                q_acc = qk if q_acc is None else jnp.concatenate([q_acc, qk], axis=1)
                q_acc = jax.lax.with_sharding_constraint(
                    q_acc, comm.sharding(2, 1 if q_acc.shape[1] % comm.size == 0 else None)
                )
            q = jnp.concatenate(q_panels, axis=1)
            r_full = jnp.zeros((n, n), x.dtype)
            for (s, e, y, rkk) in rows:
                if y.shape[0]:
                    r_full = r_full.at[: y.shape[0], s:e].set(y)
                r_full = r_full.at[s:e, s:e].set(rkk)
            return q, r_full

        return _f

    key = ("qr.cgs2", comm, tuple(bounds), (m, n), str(arr.dtype))
    return jitted(key, make)(arr)


def _mm(a, b):
    """Matmul pinned behind an optimization barrier — the grid QR/SVD
    twin discipline's determinism primitive.  XLA CPU decides a dot's
    emission (library GEMM vs inlined fusion loop, with different
    accumulation orders) from its fusion CONTEXT, so the same matmul can
    produce different bits inside the shard_map kernel and the
    replicated golden simulation.  Barriers on the operands and the
    result pin every twin-sensitive dot as a standalone op in BOTH
    programs, making the pair bitwise-reproducible (without them the
    ragged-panel shapes in tests/test_linalg2d.py diverge by 1 ulp)."""
    a, b = jax.lax.optimization_barrier((a, b))
    return jax.lax.optimization_barrier(jnp.matmul(a, b))


def _sumsq(x):
    """Sum of squares pinned behind optimization barriers — same
    rationale as :func:`_mm`, for reductions: XLA CPU's reduce emission
    also depends on fusion context, and the QDWH convergence scalars
    (norm scale, delta) feed every subsequent bit of the iteration."""
    t = jax.lax.optimization_barrier(x * x)
    return jax.lax.optimization_barrier(jnp.sum(t))


def _grid_panel_schedule(n: int, c: int, tiles_per_proc: int):
    """Enrich :func:`heat_tpu.comm._costs.grid_panel_bounds` with each
    panel's padded-global column start and the per-mesh-column valid
    counts — the static facts the kernel, the wire model, and the
    replicated golden all iterate in lock-step."""
    from ...comm._costs import grid_panel_bounds

    nloc = -(-n // c)
    bounds = tuple(
        (jc, lo, nb, jc * nloc + lo)
        for (jc, lo, nb) in grid_panel_bounds(n, c, tiles_per_proc)
    )
    vcs = tuple(min(nloc, max(0, n - jc * nloc)) for jc in range(c))
    return nloc, bounds, vcs


def _caqr_shard_body(a_loc, *, ax0, ax1, r, c, nloc, bounds, vcs, overlapped):
    """Per-device body of the grid blocked/CAQR QR — called inside a
    shard_map over the r×c mesh (axes ``ax0``/``ax1`` bound), and reused
    verbatim by the QDWH SVD's inner factorization (svd.py).

    Panel ownership algebra (docs/design.md §23): columns live
    block-distributed along the mesh columns in chunks of ``nloc``;
    ``bounds`` holds ``(owner, local offset, width, global start)`` per
    panel over REAL columns only (pad columns are never factored — a
    factored zero column would produce garbage orthonormal directions
    that corrupt every trailing real column).  Per panel:

    1. masked-psum broadcast of the owner's panel along the mesh columns
       (owner block + zero blocks — any-order exact);
    2. BCGS2 reorthogonalization against the accumulated basis (skipped
       on the first panel): the projection coefficients are reduced down
       the mesh rows and the correction/coefficient bundle combined
       along the columns, both via all-gather + index-ordered local sums
       (a psum's internal reduction order is unspecified and would break
       the bitwise twin);
    3. TSQR down the mesh rows: local QR, all-gather of the small R
       stack, second QR, Q-correction matmul;
    4. trailing update via the W = Qpᵀ·A coefficients (reduced down the
       rows in index order), applied as TWO column-disjoint masked
       subtracts — next panel, then the rest — in BOTH arms, so the
       overlap arm can factor panel ``p+1`` between them (distance-2
       lookahead) while every column still sees the identical op
       sequence, keeping the two arms bitwise-equal.

    Q columns and the panel's R diagonal block are written at factor
    time (the lookahead factor of ``p+1`` must see the basis including
    panel ``p``); R's trailing rows get the W coefficients and R's
    second-projection rows the BCGS2 coefficients via ``.add`` — each R
    entry receives at most two addends from zero, and two-term IEEE
    addition commutes, so the arms' different write orders agree
    bitwise.  Returns ``(q_loc, r_loc)`` with ``r_loc`` of padded shape
    ``(c*nloc, nloc)``, bit-identical down the mesh rows.
    """
    mloc = a_loc.shape[0]
    Np = c * nloc
    dt = a_loc.dtype
    i = jax.lax.axis_index(ax0)
    j = jax.lax.axis_index(ax1)
    ids = jnp.arange(nloc)
    col_gids = j * nloc + ids
    valid = ids < jnp.asarray(vcs)[j]
    row_valid = np.zeros((Np,), dtype=bool)
    for jc in range(c):
        row_valid[jc * nloc : jc * nloc + vcs[jc]] = True
    row_valid = jnp.asarray(row_valid)
    zero = jnp.zeros((), dt)

    def bcast_cols(x, owner):
        return jax.lax.psum(jnp.where(owner == j, x, zero), ax1)

    def rowsum(x):
        g = jax.lax.all_gather(x, ax0)
        acc = g[0]
        for b in range(1, r):
            acc = acc + g[b]
        return acc

    def factor(p, a_cur, q_acc, r_acc):
        jc, lo, nb, gstart = bounds[p]
        pan = bcast_cols(jax.lax.slice_in_dim(a_cur, lo, lo + nb, axis=1), jc)
        if p:
            z_loc = rowsum(_mm(q_acc.T, pan))
            prev = valid & (col_gids < gstart)
            z_loc = jnp.where(prev[:, None], z_loc, zero)
            bundle = jnp.concatenate([_mm(q_acc, z_loc), z_loc], axis=0)
            g = jax.lax.all_gather(bundle, ax1)  # (c, mloc+nloc, nb)
            corr = g[0, :mloc]
            for b in range(1, c):
                corr = corr + g[b, :mloc]
            z_full = jnp.reshape(g[:, mloc:], (Np, nb))
            pan = pan - corr
            zmask = (row_valid & (jnp.arange(Np) < gstart))[:, None]
            r_add = jnp.zeros_like(r_acc).at[:, lo : lo + nb].set(
                jnp.where(zmask, z_full, zero)
            )
            r_acc = r_acc + jnp.where(jc == j, r_add, zero)
        q1, r1 = jnp.linalg.qr(pan)
        st = jax.lax.all_gather(r1, ax0, tiled=True)  # (r*nb, nb)
        q2, rp = jnp.linalg.qr(st)
        qp = _mm(q1, jax.lax.dynamic_slice_in_dim(q2, i * nb, nb, 0))
        q_acc = jnp.where(jc == j, q_acc.at[:, lo : lo + nb].set(qp), q_acc)
        r_blk = jnp.zeros_like(r_acc).at[gstart : gstart + nb, lo : lo + nb].set(rp)
        r_acc = r_acc + jnp.where(jc == j, r_blk, zero)
        return qp, q_acc, r_acc

    def masks(p):
        _jc, _lo, nb, gstart = bounds[p]
        trail = valid & (col_gids >= gstart + nb)
        if p + 1 < len(bounds):
            _, _, nbn, gsn = bounds[p + 1]
            nxt = valid & (col_gids >= gsn) & (col_gids < gsn + nbn)
        else:
            nxt = jnp.zeros_like(trail)
        return trail, nxt, trail & ~nxt

    a_cur = a_loc
    q_acc = jnp.zeros_like(a_loc)
    r_acc = jnp.zeros((Np, nloc), dt)
    P = len(bounds)
    if not overlapped:
        for p in range(P):
            qp, q_acc, r_acc = factor(p, a_cur, q_acc, r_acc)
            _jc, _lo, nb, gstart = bounds[p]
            trail, nxt, rest = masks(p)
            w = rowsum(_mm(qp.T, a_cur))
            a_cur = a_cur - _mm(qp, jnp.where(nxt[None, :], w, zero))
            a_cur = a_cur - _mm(qp, jnp.where(rest[None, :], w, zero))
            r_acc = r_acc.at[gstart : gstart + nb, :].add(
                jnp.where(trail[None, :], w, zero)
            )
    else:
        qp, q_acc, r_acc = factor(0, a_cur, q_acc, r_acc)
        for p in range(P):
            _jc, _lo, nb, gstart = bounds[p]
            trail, nxt, rest = masks(p)
            w = rowsum(_mm(qp.T, a_cur))
            a_cur = a_cur - _mm(qp, jnp.where(nxt[None, :], w, zero))
            if p + 1 < P:
                qn, q_acc, r_acc = factor(p + 1, a_cur, q_acc, r_acc)
            a_cur = a_cur - _mm(qp, jnp.where(rest[None, :], w, zero))
            r_acc = r_acc.at[gstart : gstart + nb, :].add(
                jnp.where(trail[None, :], w, zero)
            )
            if p + 1 < P:
                qp = qn
    return q_acc, r_acc


def _caqr_sim(blocks, *, r, c, nloc, bounds, vcs, overlapped):
    """Lockstep replicated simulation of :func:`_caqr_shard_body` — the
    bitwise golden twin (PR 11 discipline).  ``blocks[(i, j)]`` holds the
    ``(mloc, nloc)`` shard of mesh position ``(i, j)``; every collective
    is replayed op-for-op: the masked psum as an index-ordered sum of
    the owner block plus explicit zero blocks (mirroring psum's ``-0 +
    +0 = +0`` normalization), all-gathers as index-ordered stacks.
    Returns ``(q_blocks, r_blocks)`` matching the kernel bit-for-bit."""
    mloc = blocks[(0, 0)].shape[0]
    Np = c * nloc
    dt = blocks[(0, 0)].dtype
    zero = jnp.zeros((), dt)
    col_gids = {j: j * nloc + jnp.arange(nloc) for j in range(c)}
    valid = {j: jnp.arange(nloc) < jnp.asarray(vcs)[j] for j in range(c)}
    row_valid = np.zeros((Np,), dtype=bool)
    for jc in range(c):
        row_valid[jc * nloc : jc * nloc + vcs[jc]] = True
    row_valid = jnp.asarray(row_valid)

    def bcast_cols(vals_row, owner):
        acc = vals_row[0] if owner == 0 else jnp.where(False, vals_row[0], zero)
        for jp in range(1, c):
            acc = acc + (
                vals_row[jp] if owner == jp else jnp.where(False, vals_row[jp], zero)
            )
        return acc

    def rowsum(vals_col):
        acc = vals_col[0]
        for b in range(1, r):
            acc = acc + vals_col[b]
        return acc

    def factor(p, a_cur, q_acc, r_acc):
        jc, lo, nb, gstart = bounds[p]
        pan = {}
        for i in range(r):
            row = [
                jax.lax.slice_in_dim(a_cur[(i, jp)], lo, lo + nb, axis=1)
                for jp in range(c)
            ]
            p_i = bcast_cols(row, jc)
            for j in range(c):
                pan[(i, j)] = p_i
        qp = {}
        if p:
            z = {}
            for j in range(c):
                for i in range(r):
                    z[(i, j)] = rowsum(
                        [
                            _mm(q_acc[(b, j)].T, pan[(b, j)])
                            for b in range(r)
                        ]
                    )
            for j in range(c):
                prev = valid[j] & (col_gids[j] < gstart)
                for i in range(r):
                    z[(i, j)] = jnp.where(prev[:, None], z[(i, j)], zero)
            for i in range(r):
                bundles = [
                    jnp.concatenate(
                        [_mm(q_acc[(i, jp)], z[(i, jp)]), z[(i, jp)]],
                        axis=0,
                    )
                    for jp in range(c)
                ]
                g = jnp.stack(bundles)  # all_gather along the mesh columns
                corr = g[0, :mloc]
                for b in range(1, c):
                    corr = corr + g[b, :mloc]
                z_full = jnp.reshape(g[:, mloc:], (Np, nb))
                for j in range(c):
                    pan[(i, j)] = pan[(i, j)] - corr
                zmask = (row_valid & (jnp.arange(Np) < gstart))[:, None]
                r_add = jnp.zeros((Np, nloc), dt).at[:, lo : lo + nb].set(
                    jnp.where(zmask, z_full, zero)
                )
                for j in range(c):
                    r_acc[(i, j)] = r_acc[(i, j)] + (
                        r_add if jc == j else jnp.where(False, r_add, zero)
                    )
        for j in range(c):
            q1s, r1s = {}, {}
            for i in range(r):
                q1s[i], r1s[i] = jnp.linalg.qr(pan[(i, j)])
            st = jnp.concatenate([r1s[b] for b in range(r)], axis=0)
            q2, rp = jnp.linalg.qr(st)
            for i in range(r):
                qp[(i, j)] = _mm(
                    q1s[i], jax.lax.dynamic_slice_in_dim(q2, i * nb, nb, 0)
                )
                if jc == j:
                    q_acc[(i, j)] = q_acc[(i, j)].at[:, lo : lo + nb].set(qp[(i, j)])
                r_blk = jnp.zeros((Np, nloc), dt).at[
                    gstart : gstart + nb, lo : lo + nb
                ].set(rp)
                r_acc[(i, j)] = r_acc[(i, j)] + (
                    r_blk if jc == j else jnp.where(False, r_blk, zero)
                )
        return qp

    def masks(p, j):
        _jc, _lo, nb, gstart = bounds[p]
        trail = valid[j] & (col_gids[j] >= gstart + nb)
        if p + 1 < len(bounds):
            _, _, nbn, gsn = bounds[p + 1]
            nxt = valid[j] & (col_gids[j] >= gsn) & (col_gids[j] < gsn + nbn)
        else:
            nxt = jnp.zeros_like(trail)
        return trail, nxt, trail & ~nxt

    def wcoeffs(qp, a_cur):
        w = {}
        for j in range(c):
            for i in range(r):
                w[(i, j)] = rowsum(
                    [_mm(qp[(b, j)].T, a_cur[(b, j)]) for b in range(r)]
                )
        return w

    def update(qp, a_cur, r_acc, p, which):
        for j in range(c):
            trail, nxt, rest = masks(p, j)
            mask = {"next": nxt, "rest": rest}[which]
            for i in range(r):
                a_cur[(i, j)] = a_cur[(i, j)] - _mm(
                    qp[(i, j)], jnp.where(mask[None, :], w[(i, j)], zero)
                )

    a_cur = dict(blocks)
    q_acc = {k: jnp.zeros_like(v) for k, v in blocks.items()}
    r_acc = {k: jnp.zeros((Np, nloc), dt) for k in blocks}
    P = len(bounds)
    if not overlapped:
        for p in range(P):
            qp = factor(p, a_cur, q_acc, r_acc)
            _jc, _lo, nb, gstart = bounds[p]
            w = wcoeffs(qp, a_cur)
            update(qp, a_cur, r_acc, p, "next")
            update(qp, a_cur, r_acc, p, "rest")
            for j in range(c):
                trail = masks(p, j)[0]
                for i in range(r):
                    r_acc[(i, j)] = r_acc[(i, j)].at[gstart : gstart + nb, :].add(
                        jnp.where(trail[None, :], w[(i, j)], zero)
                    )
    else:
        qp = factor(0, a_cur, q_acc, r_acc)
        for p in range(P):
            _jc, _lo, nb, gstart = bounds[p]
            w = wcoeffs(qp, a_cur)
            update(qp, a_cur, r_acc, p, "next")
            if p + 1 < P:
                qn = factor(p + 1, a_cur, q_acc, r_acc)
            update(qp, a_cur, r_acc, p, "rest")
            for j in range(c):
                trail = masks(p, j)[0]
                for i in range(r):
                    r_acc[(i, j)] = r_acc[(i, j)].at[gstart : gstart + nb, :].add(
                        jnp.where(trail[None, :], w[(i, j)], zero)
                    )
            if p + 1 < P:
                qp = qn
    return q_acc, r_acc


def _grid_qr_reference(arr, mesh_shape, *, tiles_per_proc=1, overlapped=False):
    """Replicated golden twin of the grid CAQR: runs the exact panel
    schedule of :func:`_grid_qr_fn` on an unsharded operand via
    :func:`_caqr_sim` and reassembles the padded global ``(q, r)`` —
    bitwise-equal to the kernel's outputs (bench.py and
    tests/test_linalg2d.py pin this).

    The whole simulation runs as ONE jitted program: eager per-op
    execution changes XLA CPU's fusion context and with it the emission
    of small dots, so an unjitted twin diverges by 1 ulp on ragged
    panels even with :func:`_mm`'s barriers in place."""
    r, c = mesh_shape
    m, n = arr.shape
    mloc = -(-m // r)
    nloc, bounds, vcs = _grid_panel_schedule(n, c, tiles_per_proc)
    Mp, Np = r * mloc, c * nloc

    def run(x):
        x = jnp.pad(x, ((0, Mp - m), (0, Np - n)))
        blocks = {
            (i, j): x[i * mloc : (i + 1) * mloc, j * nloc : (j + 1) * nloc]
            for i in range(r)
            for j in range(c)
        }
        qb, rb = _caqr_sim(
            blocks, r=r, c=c, nloc=nloc, bounds=bounds, vcs=vcs,
            overlapped=overlapped,
        )
        q = jnp.concatenate(
            [
                jnp.concatenate([qb[(i, j)] for j in range(c)], axis=1)
                for i in range(r)
            ],
            axis=0,
        )
        r_full = jnp.concatenate([rb[(0, j)] for j in range(c)], axis=1)
        return q, r_full[:n]

    key = (mesh_shape, (m, n), str(arr.dtype), tiles_per_proc, overlapped)
    fn = _REFERENCE_CACHE.get(key)
    if fn is None:
        fn = _REFERENCE_CACHE[key] = jax.jit(run)
    return fn(arr)


def _grid_qr_fn(comm, bounds, vcs, overlapped, nloc, n, shape, dtype_str):
    """The grid CAQR as ONE cached shard_map program ``f(a_padded) ->
    (q, r)``: Q on the ``(ax0, ax1)`` grid, R column-sharded with true
    row count (replicated down the mesh rows bit-identically)."""
    key = ("qr.grid", comm, bounds, vcs, shape, dtype_str, overlapped)

    def make():
        ax0, ax1 = comm.axis_names
        r, c = comm.mesh_shape

        def kern(a_loc):
            q_loc, r_loc = _caqr_shard_body(
                a_loc,
                ax0=ax0,
                ax1=ax1,
                r=r,
                c=c,
                nloc=nloc,
                bounds=bounds,
                vcs=vcs,
                overlapped=overlapped,
            )
            return q_loc, r_loc[:n]

        return shard_map(
            kern,
            mesh=comm.mesh,
            in_specs=(PartitionSpec(ax0, ax1),),
            out_specs=(PartitionSpec(ax0, ax1), PartitionSpec(None, ax1)),
            check_vma=False,
        )

    return jitted(key, make)


def _grid_qr(a: DNDarray, jt, tiles_per_proc: int):
    """Dispatch wrapper of the grid blocked/CAQR QR (operand splits
    ``(0, 1)``, ``m >= n``): ships the ZEROED buffer (pad rows/columns
    must be exact zeros — pads in a factored panel would corrupt the
    basis), launches the one cached program, credits the telemetry
    ledger with figures straight from
    :func:`heat_tpu.comm._costs.grid_qr_model` (delegation keeps
    accounted and modeled bytes byte-identical), and times the dispatch
    under the overlap policy."""
    from ...comm import _costs
    from ...comm.overlap import overlap_enabled, timed_dispatch

    comm = a.comm
    m, n = a.shape
    r, c = comm.mesh_shape
    mloc = -(-m // r)
    nloc, bounds, vcs = _grid_panel_schedule(n, c, int(tiles_per_proc))
    nb_max = max(b[2] for b in bounds)
    if mloc < nb_max:
        raise ValueError(
            f"qr: grid CAQR needs row shards at least as tall as the widest "
            f"column panel: {m}x{n} over the {r}x{c} mesh leaves "
            f"({mloc}, {nloc}) shards with {mloc} rows < panel width "
            f"{nb_max}; use a taller matrix, a flatter mesh, or raise "
            f"tiles_per_proc"
        )
    arr = a._zeroed_buffer()
    if arr.dtype != jt:
        arr = arr.astype(jt)
    ov = overlap_enabled(len(bounds))
    fn = _grid_qr_fn(
        comm, bounds, vcs, ov, nloc, n, tuple(map(int, arr.shape)), str(arr.dtype)
    )
    if _tel.enabled:
        model = _costs.grid_qr_model(
            m, n, (r, c), tiles_per_proc=int(tiles_per_proc), overlap=ov
        )
        _tel.account_bytes(
            "qr2d", "f32", model["exact_wire_bytes"], model["wire_bytes"]
        )
        with _tel.span(
            "comm:qr2d", mesh=f"{r}x{c}", panels=len(bounds), overlap=ov
        ):
            return timed_dispatch("qr2d", ov, lambda: fn(arr))
    return timed_dispatch("qr2d", ov, lambda: fn(arr))


@_split_semantics("entry_qr")
def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> QR:
    """Reduced QR factorization ``a = Q @ R`` (reference qr.py:10-302).

    ``tiles_per_proc`` subdivides each mesh position's column panel in the
    split=1 path (the reference's latency/parallelism knob, qr.py:31-36);
    the split=0 TSQR formulation has no tile-count knob and ignores it.
    """
    sanitize_in(a)
    if not isinstance(tiles_per_proc, (int, np.integer)):
        raise TypeError(f"tiles_per_proc must be an int, got {type(tiles_per_proc)}")
    if tiles_per_proc < 1:
        raise ValueError(f"tiles_per_proc must be >= 1, got {tiles_per_proc}")
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D DNDarray, got {a.ndim}-d")

    dtype = a.dtype if types.heat_type_is_inexact(a.dtype) else types.float32

    comm = a.comm
    if comm.mesh_ndim == 2 and comm.size > 1 and a.splits == (0, 1):
        # grid blocked/CAQR QR on the r×c mesh (arXiv 2112.09017's dense
        # QR at pod scale): panel TSQR down the mesh columns + trailing
        # update, one cached dispatch, bitwise-pinned overlap arm
        m, n = a.shape
        if m < n:
            r_m, c_m = comm.mesh_shape
            raise ValueError(
                f"qr: wide inputs have no grid formulation: {m}x{n} with "
                f"splits (0, 1) on the {r_m}x{c_m} mesh — factor the "
                f"transpose (resplit its layout to (0, 1)) and transpose "
                f"back, or use svd for the spectral path"
            )
        q_arr, r_arr = _grid_qr(a, dtype.jax_type(), int(tiles_per_proc))
        r_nd = DNDarray(r_arr, (n, n), dtype, (None, 1), a.device, comm, True)
        if not calc_q:
            return QR(None, r_nd)
        q_nd = DNDarray(q_arr, (m, n), dtype, (0, 1), a.device, comm, True)
        return QR(q_nd, r_nd)

    arr = a.larray.astype(dtype.jax_type())
    aa = a if (a.dtype is dtype and arr is a.larray) else DNDarray(
        arr, a.shape, dtype, a.split, a.device, a.comm, True
    )

    if a.split == 0 and a.shape[0] >= a.shape[1]:
        q_g, r_g = _tsqr(aa)
    elif a.split == 1 and a.shape[0] >= a.shape[1] and a.comm.size > 1:
        q_g, r_g = _cgs2_split1(aa, int(tiles_per_proc))
    else:
        # replicated or wide matrices: on-device QR, XLA plans the
        # distribution (reference split=None, qr.py:70-94)
        q_g, r_g = jnp.linalg.qr(arr)

    comm, device = a.comm, a.device
    if not calc_q:
        r_split = a.split if a.split == 1 else None
        r = DNDarray(comm.apply_sharding(r_g, r_split), tuple(r_g.shape), dtype, r_split, device, comm, True)
        return QR(None, r)

    q_split = a.split
    q = DNDarray(comm.apply_sharding(q_g, q_split), tuple(q_g.shape), dtype, q_split, device, comm, True)
    r_split = None if a.split != 1 else 1
    r = DNDarray(comm.apply_sharding(r_g, r_split), tuple(r_g.shape), dtype, r_split, device, comm, True)
    return QR(q, r)
