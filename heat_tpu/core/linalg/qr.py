"""Distributed QR decomposition.

Reference: heat/core/linalg/qr.py:10-988 — a tiled CAQR over
``SquareDiagTiles`` with per-tile Householder factorizations, pairwise tile
row merges, async Q-factor shipping, and a column-cyclic split=1 loop.

TPU-first design (per SURVEY.md §7 build plan, item 8):

* **split=0 (row-sharded), m ≥ n: TSQR** (communication-avoiding
  tall-skinny QR).  Each shard computes a local QR; the stacked R factors
  are QR'd again; one all-gather replaces the reference's point-to-point
  tile choreography.  Non-divisible row counts go through the canonical
  zero-padding (``comm.pad_to_shards``): zero rows leave R untouched and —
  because the stage-2 Q's rows matching zero R-stack rows vanish — drop
  out of Q exactly, so ragged TSQR is exact for full-column-rank inputs
  (the same caveat any QR has for deficient ones).
* **split=1 (column-sharded), m ≥ n: blocked CGS2** — a panel loop in the
  spirit of the reference's column-cyclic ``__split1_qr_loop``
  (qr.py:817-988): each panel is orthogonalized against the accumulated Q
  by two classical Gram-Schmidt projections (MXU matmuls; provably stable
  for κ(A) ≲ 1/√ε) and factored locally.  ``tiles_per_proc`` subdivides
  each mesh position's panel, matching the reference's latency/parallelism
  knob (qr.py:31-36).
* replicated or wide (m < n) inputs use on-device ``jnp.linalg.qr`` (same
  as reference split=None, qr.py:70-94).

The one remaining distributed fallback — split=0 with more than
``m / n`` devices, where shards are wider than tall and TSQR's local QR
does not reduce — gathers with a ``UserWarning`` (the R stack would be as
large as the matrix itself, so gathering is also the bandwidth-optimal
choice there).
"""

from __future__ import annotations

import collections
import warnings
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from .. import factories, types
from .._compile import jitted
from .._jax_compat import shard_map
from ..dndarray import DNDarray
from ..sanitation import sanitize_in

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")


def _tsqr_program(comm):
    """The two-stage TSQR pipeline as a traceable ``f(x) -> (q, r)`` over
    a shard-padded row-split operand: per-shard local QR inside shard_map,
    a second QR of the small (size·n, n) R stack, and the Q-correction
    matmul.  Module-level so bench.py can embed the EXACT production
    compute graph inside its single-dispatch timing region; :func:`_tsqr`
    wraps it in the keyed-jit cache.  A single-device mesh degenerates to
    one on-device QR (what :func:`qr` dispatches there)."""
    if comm.size == 1:
        return jnp.linalg.qr

    mesh = comm.mesh
    axis = comm.axis_name

    from .basics import _precision

    def _local_qr(block):
        q, r = jnp.linalg.qr(block)
        return q, r  # plain tuple: QRResult confuses shard_map out_specs

    local_qr = shard_map(
        _local_qr,
        mesh=mesh,
        in_specs=PartitionSpec(axis, None),
        out_specs=(PartitionSpec(axis, None), PartitionSpec(axis, None)),
    )

    def _combine(q1_blk, q2_blk):
        return jnp.matmul(q1_blk, q2_blk, precision=_precision())

    combine = shard_map(
        _combine,
        mesh=mesh,
        in_specs=(PartitionSpec(axis, None), PartitionSpec(axis, None)),
        out_specs=PartitionSpec(axis, None),
    )

    def _f(x):
        q1, r1 = local_qr(x)  # q1: (padded_m, n) row-split; r1: (size*n, n)
        # stage 2 on the R stack (size*n × n — small, replicated)
        r1_full = jax.lax.with_sharding_constraint(r1, comm.sharding(2, None))
        q2, r = jnp.linalg.qr(r1_full)  # q2: (size*n, n)
        q = combine(q1, q2)
        return q, r

    return _f


def _tsqr(a: DNDarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-stage TSQR on the mesh (replaces reference qr.py:303-816).

    Stage 1: per-shard local QR inside shard_map (runs on every device in
    parallel).  Stage 2: the (size·n, n) stack of R factors — tiny — is
    QR'd again, and local Qs are corrected by the matching R-block.
    Handles any row count via canonical zero-padding.
    """
    comm = a.comm
    m, n = a.shape
    size = comm.size
    arr = a.larray

    if size == 1:
        return jnp.linalg.qr(arr)
    if comm.shard_width(m) < n:
        # shards wider than tall: local QR would not reduce and the R
        # stack would match the full matrix — gather and factor once
        warnings.warn(
            f"qr: {m}x{n} split=0 over {size} devices leaves shards with "
            f"fewer rows ({comm.shard_width(m)}) than columns ({n}); "
            "gathering for a single on-device QR (use fewer devices or a "
            "taller matrix for distributed TSQR)",
            stacklevel=3,
        )
        return jnp.linalg.qr(arr)

    arr_p = comm.pad_to_shards(arr, axis=0)
    q, r = jitted(("qr.tsqr", comm), lambda: _tsqr_program(comm))(arr_p)
    return comm.unpad(q, m, 0), r


def _cgs2_split1(a: DNDarray, tiles_per_proc: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked classical Gram-Schmidt with reorthogonalization over column
    panels (the TPU formulation of the reference's column-cyclic split=1
    loop, qr.py:817-988).

    Panels follow the mesh layout (one per position, subdivided by
    ``tiles_per_proc``), so each projection is a large MXU matmul whose
    collectives GSPMD schedules over ICI; no panel is ever gathered.
    """
    comm = a.comm
    m, n = a.shape
    arr = a.larray

    # panel plan: each position's column block, split into tiles_per_proc
    c = comm.shard_width(n)
    bounds = []
    for r in range(comm.size):
        start, stop = r * c, min((r + 1) * c, n)
        if start >= stop:
            continue
        width = stop - start
        t = max(1, min(int(tiles_per_proc), width))
        tw = -(-width // t)
        for j in range(t):
            s2 = start + j * tw
            e2 = min(s2 + tw, stop)
            if s2 < e2:
                bounds.append((s2, e2))

    def make():
        from .basics import _precision

        def _f(x):
            q_panels = []
            rows = []
            q_acc = None  # (m, k) accumulated orthonormal columns
            for (s, e) in bounds:
                panel = x[:, s:e]
                if q_acc is None:
                    y = jnp.zeros((0, e - s), x.dtype)
                else:
                    # CGS2: project out the accumulated basis twice
                    y1 = jnp.matmul(q_acc.T, panel, precision=_precision())
                    panel = panel - jnp.matmul(q_acc, y1, precision=_precision())
                    y2 = jnp.matmul(q_acc.T, panel, precision=_precision())
                    panel = panel - jnp.matmul(q_acc, y2, precision=_precision())
                    y = y1 + y2
                qk, rkk = jnp.linalg.qr(panel)
                q_panels.append(qk)
                # R rows for this panel: [Y; Rkk; 0] padded to n rows later
                rows.append((s, e, y, rkk))
                q_acc = qk if q_acc is None else jnp.concatenate([q_acc, qk], axis=1)
                q_acc = jax.lax.with_sharding_constraint(
                    q_acc, comm.sharding(2, 1 if q_acc.shape[1] % comm.size == 0 else None)
                )
            q = jnp.concatenate(q_panels, axis=1)
            r_full = jnp.zeros((n, n), x.dtype)
            for (s, e, y, rkk) in rows:
                if y.shape[0]:
                    r_full = r_full.at[: y.shape[0], s:e].set(y)
                r_full = r_full.at[s:e, s:e].set(rkk)
            return q, r_full

        return _f

    key = ("qr.cgs2", comm, tuple(bounds), (m, n), str(arr.dtype))
    return jitted(key, make)(arr)


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> QR:
    """Reduced QR factorization ``a = Q @ R`` (reference qr.py:10-302).

    ``tiles_per_proc`` subdivides each mesh position's column panel in the
    split=1 path (the reference's latency/parallelism knob, qr.py:31-36);
    the split=0 TSQR formulation has no tile-count knob and ignores it.
    """
    sanitize_in(a)
    if not isinstance(tiles_per_proc, (int, np.integer)):
        raise TypeError(f"tiles_per_proc must be an int, got {type(tiles_per_proc)}")
    if tiles_per_proc < 1:
        raise ValueError(f"tiles_per_proc must be >= 1, got {tiles_per_proc}")
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D DNDarray, got {a.ndim}-d")

    dtype = a.dtype if types.heat_type_is_inexact(a.dtype) else types.float32
    arr = a.larray.astype(dtype.jax_type())
    aa = a if (a.dtype is dtype and arr is a.larray) else DNDarray(
        arr, a.shape, dtype, a.split, a.device, a.comm, True
    )

    if a.split == 0 and a.shape[0] >= a.shape[1]:
        q_g, r_g = _tsqr(aa)
    elif a.split == 1 and a.shape[0] >= a.shape[1] and a.comm.size > 1:
        q_g, r_g = _cgs2_split1(aa, int(tiles_per_proc))
    else:
        # replicated or wide matrices: on-device QR, XLA plans the
        # distribution (reference split=None, qr.py:70-94)
        q_g, r_g = jnp.linalg.qr(arr)

    comm, device = a.comm, a.device
    if not calc_q:
        r_split = a.split if a.split == 1 else None
        r = DNDarray(comm.apply_sharding(r_g, r_split), tuple(r_g.shape), dtype, r_split, device, comm, True)
        return QR(None, r)

    q_split = a.split
    q = DNDarray(comm.apply_sharding(q_g, q_split), tuple(q_g.shape), dtype, q_split, device, comm, True)
    r_split = None if a.split != 1 else 1
    r = DNDarray(comm.apply_sharding(r_g, r_split), tuple(r_g.shape), dtype, r_split, device, comm, True)
    return QR(q, r)
