"""Singular value decomposition.

Reference: heat/core/linalg/svd.py:1 — a **stub** (one commented line); SVD
does not exist in HeAT 0.5.1.  Implemented here because the rebuild's
baseline configs exercise it (BASELINE.md target 5: "linalg.qr + SVD on
tall-skinny split DNDarray").

Algorithm: always reduce via QR first (TSQR when row-split — see qr.py),
then factor the small triangular R **on device** — the standard
communication-avoiding SVD.  Only the tiny (n, n) R ever reaches the SVD
kernel, so the MXU carries all the real work (QR + the Q·Ur matmul) and
the decomposition adds zero host syncs: round 2 factored R on the host
because ``jnp.linalg.svd`` SIGABRT'd the then-current XLA TPU compiler
(TransposeFolding CHECK), which cost two tunnel round-trips per call —
~125 ms of the ~116 ms r2 benchmark pair was that readback.  The current
toolchain lowers SVD correctly (verified against numpy singular values
and reconstruction at 1e-5); set ``HEAT_TPU_HOST_SVD=1`` to restore the
host fallback on a toolchain where the crash resurfaces.  Wide matrices
factor transposed and swap U/V.
"""

from __future__ import annotations

import collections
import os

import numpy as np
import jax as _jax
from functools import partial as _partial
from .._jax_compat import enable_x64 as _enable_x64
_x64_off = _partial(_enable_x64, False)
import jax.numpy as jnp


@_jax.jit
def _jitted_svd(a):
    # one persistent jit: a fresh lambda per call would recompile the SVD
    # every invocation (~1.2 s each on the TPU)
    return jnp.linalg.svd(a, full_matrices=False)


@_jax.jit
def _jitted_singvals(a):
    return jnp.linalg.svd(a, compute_uv=False)

from .. import types
from ..dndarray import DNDarray
from ..fuse import fuse
from ..sanitation import sanitize_in
from .qr import qr as _qr

__all__ = ["svd"]

#: element cap for the silent wide-shard pre-resplit below — 1M elements
#: (4 MB f32) replicates harmlessly; anything larger keeps qr's gather
#: warning as the memory signal
_SMALL_RESPLIT_MAX = 1 << 20

SVD = collections.namedtuple("SVD", "U, S, V")


def _host_svd() -> bool:
    """True when the escape hatch back to host-side SVD of R is on."""
    return os.environ.get("HEAT_TPU_HOST_SVD", "0") == "1"


def _small_svd(r: jnp.ndarray):
    """SVD of the reduced (n, n) triangular factor: on device by default,
    on the host behind ``HEAT_TPU_HOST_SVD=1`` (see module docstring).

    The on-device lowering runs under ``jax.enable_x64(False)``: with x64
    on (this package's default policy) the compute_uv SVD lowering still
    SIGABRTs the XLA TPU compiler, while the identical f32 program with
    x64 off compiles and matches numpy to 1e-4 — the operands are f32
    either way, so the context changes internal index dtypes only."""
    if _host_svd() or r.dtype == jnp.float64:
        # float64 R factors on the host: the x64-off context below would
        # silently downcast them, and the TPU has no f64 hardware — LAPACK
        # on an (n, n) triangle is the right tool (one tiny transfer)
        ur, s, vt = np.linalg.svd(np.asarray(r), full_matrices=False)
        return jnp.asarray(ur, r.dtype), jnp.asarray(s, r.dtype), jnp.asarray(vt, r.dtype)
    with _x64_off():
        return _jitted_svd(r)


def _small_singvals(r: jnp.ndarray):
    """Singular values of the reduced factor, same device/host policy and
    x64 guard as :func:`_small_svd` (an f64 lowering under the package's
    x64-on default is the documented crash combination on TPU)."""
    if _host_svd() or r.dtype == jnp.float64:
        return jnp.asarray(np.linalg.svd(np.asarray(r), compute_uv=False), r.dtype)
    with _x64_off():
        return _jitted_singvals(r)


def _svd_pipeline(a: DNDarray, osplit, dtype, compute_uv: bool):
    """The tall (m ≥ n) QR-first SVD chain over a sanitized operand.

    Module-level so :func:`heat_tpu.fuse` can compile the whole thing —
    resplit heuristic, (TS)QR, small SVD, Q·Ur correction, layout commits —
    into one program per (shape, split, dtype) signature; :func:`svd`
    routes the host-SVD/f64 configurations through it eagerly instead
    (their R factors round-trip through LAPACK, which cannot trace).
    """
    comm, device = a.comm, a.device
    m, n = a.shape

    if (
        a.split == 0
        and comm.size > 1
        and comm.shard_width(m) < n
        and m * n <= _SMALL_RESPLIT_MAX
    ):
        # small-intermediate rule (ML callers: spectral embeddings, tiny
        # covariance factors): shards would be wider than tall, so TSQR
        # would gather behind a warning per fit.  Make the layout call
        # HERE, once and silently — but ONLY for genuinely small matrices
        # (the element cap): replication is the plan either way, and a
        # LARGE wide-shard matrix must keep qr's gather warning as the
        # memory signal.  U is re-sharded to the caller's split below, so
        # the public contract is unchanged
        a = a.resplit(None)

    if not compute_uv:
        _, r = _qr(a if a.dtype is dtype else a.astype(dtype))
        s_arr = _small_singvals(r.larray).astype(dtype.jax_type())
        return DNDarray(s_arr, tuple(s_arr.shape), dtype, None, device, comm, True)

    q, r = _qr(a if a.dtype is dtype else a.astype(dtype))
    ur, s, vt = _small_svd(r.larray)
    from .basics import _precision

    u = jnp.matmul(q.larray, ur.astype(dtype.jax_type()), precision=_precision())
    u_split = osplit if osplit == 0 else None  # caller's layout, even after
    u = comm.apply_sharding(u, u_split)        # the small-matrix resplit
    U = DNDarray(u, (m, n), dtype, u_split, device, comm, True)
    s_arr = s.astype(dtype.jax_type())
    S = DNDarray(s_arr, (n,), dtype, None, device, comm, True)
    v = jnp.transpose(vt).astype(dtype.jax_type())
    V = DNDarray(v, (n, n), dtype, None, device, comm, True)
    return SVD(U, S, V)


_fused_svd_pipeline = fuse(_svd_pipeline)


from .._split_semantics import split_semantics as _split_semantics


@_split_semantics("entry_svd")
def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """Reduced SVD ``a = U @ diag(S) @ V.T``.

    Returns the namedtuple ``SVD(U, S, V)``; with ``compute_uv=False`` only
    ``S`` (as a DNDarray).  The on-device configurations compile the whole
    QR→SVD→correction chain into one fused program (one device dispatch
    per call after warmup); the host-SVD escape hatch and float64 operands
    keep the eager chain, since their small factor legitimately visits
    LAPACK mid-pipeline.
    """
    sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"svd requires a 2-D DNDarray, got {a.ndim}-d")
    if full_matrices:
        raise NotImplementedError("full_matrices=True is not supported (reduced SVD only)")

    dtype = a.dtype if types.heat_type_is_inexact(a.dtype) else types.float32
    m, n = a.shape

    if m < n:
        # wide: factor the transpose, swap U and V
        if not compute_uv:
            return svd(a.T, compute_uv=False)
        res = svd(a.T, compute_uv=True)
        return SVD(res.V, res.S, res.U)

    impl = _svd_pipeline if _host_svd() or dtype is types.float64 else _fused_svd_pipeline
    return impl(a, a.split, dtype, compute_uv)
