"""Singular value decomposition.

Reference: heat/core/linalg/svd.py:1 — a **stub** (one commented line); SVD
does not exist in HeAT 0.5.1.  Implemented here because the rebuild's
baseline configs exercise it (BASELINE.md target 5: "linalg.qr + SVD on
tall-skinny split DNDarray").

Algorithm: always reduce via QR first (TSQR when row-split — see qr.py),
then factor the small triangular R on the host.  This is the standard
communication-avoiding SVD and it also sidesteps a hard constraint of the
current TPU toolchain: lowering ``jnp.linalg.svd`` crashes the XLA TPU
compiler (TransposeFolding CHECK failure → SIGABRT, observed on
libtpu/v5e), so no SVD is ever compiled for the accelerator — only QR and
matmul are, both of which the MXU handles natively.  Wide matrices factor
transposed and swap U/V.
"""

from __future__ import annotations

import collections

import numpy as np
import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from .qr import qr as _qr

__all__ = ["svd"]

SVD = collections.namedtuple("SVD", "U, S, V")


def _reduced_svd_factors(a: DNDarray, dtype):
    """QR-reduce then host-SVD the small R: returns (Q, Ur, S, Vt) with
    Q on-device and the rest as numpy arrays."""
    q, r = _qr(a if a.dtype is dtype else a.astype(dtype))
    ur, s, vt = np.linalg.svd(np.asarray(r.larray), full_matrices=False)
    return q, ur, s, vt


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """Reduced SVD ``a = U @ diag(S) @ V.T``.

    Returns the namedtuple ``SVD(U, S, V)``; with ``compute_uv=False`` only
    ``S`` (as a DNDarray).
    """
    sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"svd requires a 2-D DNDarray, got {a.ndim}-d")
    if full_matrices:
        raise NotImplementedError("full_matrices=True is not supported (reduced SVD only)")

    dtype = a.dtype if types.heat_type_is_inexact(a.dtype) else types.float32
    comm, device = a.comm, a.device
    m, n = a.shape

    if m < n:
        # wide: factor the transpose, swap U and V
        if not compute_uv:
            return svd(a.T, compute_uv=False)
        res = svd(a.T, compute_uv=True)
        return SVD(res.V, res.S, res.U)

    if not compute_uv:
        _, r = _qr(a if a.dtype is dtype else a.astype(dtype))
        s = np.linalg.svd(np.asarray(r.larray), compute_uv=False)
        s_arr = jnp.asarray(s, dtype=dtype.jax_type())
        return DNDarray(s_arr, tuple(s_arr.shape), dtype, None, device, comm, True)

    q, ur, s, vt = _reduced_svd_factors(a, dtype)
    from .basics import _precision

    u = jnp.matmul(q.larray, jnp.asarray(ur, dtype=dtype.jax_type()), precision=_precision())
    u = comm.apply_sharding(u, a.split if a.split == 0 else None)
    U = DNDarray(u, (m, n), dtype, a.split if a.split == 0 else None, device, comm, True)
    S = DNDarray(jnp.asarray(s, dtype=dtype.jax_type()), (n,), dtype, None, device, comm, True)
    V = DNDarray(jnp.asarray(vt.T, dtype=dtype.jax_type()), (n, n), dtype, None, device, comm, True)
    return SVD(U, S, V)
