"""Singular value decomposition.

Reference: heat/core/linalg/svd.py:1 — a **stub** (one commented line); SVD
does not exist in HeAT 0.5.1.  Implemented here because the rebuild's
baseline configs exercise it (BASELINE.md target 5: "linalg.qr + SVD on
tall-skinny split DNDarray").

Algorithm: always reduce via QR first (TSQR when row-split — see qr.py),
then factor the small triangular R **on device** — the standard
communication-avoiding SVD.  Only the tiny (n, n) R ever reaches the SVD
kernel, so the MXU carries all the real work (QR + the Q·Ur matmul) and
the decomposition adds zero host syncs: round 2 factored R on the host
because ``jnp.linalg.svd`` SIGABRT'd the then-current XLA TPU compiler
(TransposeFolding CHECK), which cost two tunnel round-trips per call —
~125 ms of the ~116 ms r2 benchmark pair was that readback.  The current
toolchain lowers SVD correctly (verified against numpy singular values
and reconstruction at 1e-5); set ``HEAT_TPU_HOST_SVD=1`` to restore the
host fallback on a toolchain where the crash resurfaces.  Wide matrices
factor transposed and swap U/V.
"""

from __future__ import annotations

import collections
import os

import numpy as np
import jax as _jax
from functools import partial as _partial
from .._jax_compat import enable_x64 as _enable_x64
_x64_off = _partial(_enable_x64, False)
import jax.numpy as jnp


@_jax.jit
def _jitted_svd(a):
    # one persistent jit: a fresh lambda per call would recompile the SVD
    # every invocation (~1.2 s each on the TPU)
    return jnp.linalg.svd(a, full_matrices=False)


@_jax.jit
def _jitted_singvals(a):
    return jnp.linalg.svd(a, compute_uv=False)

from .. import types
from ..dndarray import DNDarray
from ..fuse import fuse
from ..sanitation import sanitize_in
from .qr import qr as _qr

__all__ = ["svd"]

#: element cap for the silent wide-shard pre-resplit below — 1M elements
#: (4 MB f32) replicates harmlessly; anything larger keeps qr's gather
#: warning as the memory signal
_SMALL_RESPLIT_MAX = 1 << 20

SVD = collections.namedtuple("SVD", "U, S, V")


def _host_svd() -> bool:
    """True when the escape hatch back to host-side SVD of R is on."""
    return os.environ.get("HEAT_TPU_HOST_SVD", "0") == "1"


def _small_svd(r: jnp.ndarray):
    """SVD of the reduced (n, n) triangular factor: on device by default,
    on the host behind ``HEAT_TPU_HOST_SVD=1`` (see module docstring).

    The on-device lowering runs under ``jax.enable_x64(False)``: with x64
    on (this package's default policy) the compute_uv SVD lowering still
    SIGABRTs the XLA TPU compiler, while the identical f32 program with
    x64 off compiles and matches numpy to 1e-4 — the operands are f32
    either way, so the context changes internal index dtypes only."""
    if _host_svd() or r.dtype == jnp.float64:
        # float64 R factors on the host: the x64-off context below would
        # silently downcast them, and the TPU has no f64 hardware — LAPACK
        # on an (n, n) triangle is the right tool (one tiny transfer)
        ur, s, vt = np.linalg.svd(np.asarray(r), full_matrices=False)
        return jnp.asarray(ur, r.dtype), jnp.asarray(s, r.dtype), jnp.asarray(vt, r.dtype)
    with _x64_off():
        return _jitted_svd(r)


def _small_singvals(r: jnp.ndarray):
    """Singular values of the reduced factor, same device/host policy and
    x64 guard as :func:`_small_svd` (an f64 lowering under the package's
    x64-on default is the documented crash combination on TPU)."""
    if _host_svd() or r.dtype == jnp.float64:
        return jnp.asarray(np.linalg.svd(np.asarray(r), compute_uv=False), r.dtype)
    with _x64_off():
        return _jitted_singvals(r)


def _svd_pipeline(a: DNDarray, osplit, dtype, compute_uv: bool):
    """The tall (m ≥ n) QR-first SVD chain over a sanitized operand.

    Module-level so :func:`heat_tpu.fuse` can compile the whole thing —
    resplit heuristic, (TS)QR, small SVD, Q·Ur correction, layout commits —
    into one program per (shape, split, dtype) signature; :func:`svd`
    routes the host-SVD/f64 configurations through it eagerly instead
    (their R factors round-trip through LAPACK, which cannot trace).
    """
    comm, device = a.comm, a.device
    m, n = a.shape

    if (
        a.split == 0
        and comm.size > 1
        and comm.shard_width(m) < n
        and m * n <= _SMALL_RESPLIT_MAX
    ):
        # small-intermediate rule (ML callers: spectral embeddings, tiny
        # covariance factors): shards would be wider than tall, so TSQR
        # would gather behind a warning per fit.  Make the layout call
        # HERE, once and silently — but ONLY for genuinely small matrices
        # (the element cap): replication is the plan either way, and a
        # LARGE wide-shard matrix must keep qr's gather warning as the
        # memory signal.  U is re-sharded to the caller's split below, so
        # the public contract is unchanged
        a = a.resplit(None)

    if not compute_uv:
        _, r = _qr(a if a.dtype is dtype else a.astype(dtype))
        s_arr = _small_singvals(r.larray).astype(dtype.jax_type())
        return DNDarray(s_arr, tuple(s_arr.shape), dtype, None, device, comm, True)

    q, r = _qr(a if a.dtype is dtype else a.astype(dtype))
    ur, s, vt = _small_svd(r.larray)
    from .basics import _precision

    u = jnp.matmul(q.larray, ur.astype(dtype.jax_type()), precision=_precision())
    u_split = osplit if osplit == 0 else None  # caller's layout, even after
    u = comm.apply_sharding(u, u_split)        # the small-matrix resplit
    U = DNDarray(u, (m, n), dtype, u_split, device, comm, True)
    s_arr = s.astype(dtype.jax_type())
    S = DNDarray(s_arr, (n,), dtype, None, device, comm, True)
    v = jnp.transpose(vt).astype(dtype.jax_type())
    V = DNDarray(v, (n, n), dtype, None, device, comm, True)
    return SVD(U, S, V)


_fused_svd_pipeline = fuse(_svd_pipeline)


# ---------------------------------------------------------------------------
# grid (2-D mesh) QDWH polar-decomposition SVD — arXiv 2112.09017's route
# to record-scale SVD: a dynamically-weighted Halley iteration built on the
# grid blocked QR, then an eigendecomposition of the small symmetric factor
# ---------------------------------------------------------------------------

import jax

from .._compile import jitted as _jitted
from .._jax_compat import shard_map as _shard_map
from jax.sharding import PartitionSpec as _P
from ...telemetry import _core as _tel
from .qr import (
    _caqr_shard_body as _caqr_body,
    _caqr_sim,
    _grid_panel_schedule,
    _mm,
    _sumsq,
)

#: static trip cap of the QDWH while_loop — the cubic ``l`` recurrence
#: reaches ``1 - eps`` from any f64 floor in <= 9 iterations, so 12 bounds
#: both dtypes with margin; the telemetry model is credited for exactly
#: this worst case (``qdwh_svd_model(iterations=_QDWH_MAXIT)``)
_QDWH_MAXIT = 12


def _qdwh_coeffs(l):
    """The dynamically-weighted Halley coefficients ``(a, b, c, l')`` from
    the lower bound ``l`` on the current polar iterate's smallest singular
    value (Nakatsukasa/Bai/Gygi's closed form).  Shared verbatim by the
    kernel and the replicated golden — the convergence decision must be
    bitwise-identical in both programs (docs/design.md §23)."""
    l2 = l * l
    d = jnp.cbrt((4.0 * (1.0 - l2)) / (l2 * l2))
    a = jnp.sqrt(1.0 + d) + 0.5 * jnp.sqrt(
        8.0 - 4.0 * d + (8.0 * (2.0 - l2)) / (l2 * jnp.sqrt(1.0 + d))
    )
    b = (a - 1.0) ** 2 / 4.0
    c = a + b - 1.0
    ln = jnp.minimum(l * (a + b * l2) / (1.0 + c * l2), 1.0)
    return a, b, c, ln


def _qdwh_tols(n, np_dtype):
    """Static convergence tolerances: iterate while the lower bound is
    measurably below 1 OR successive polar iterates still move more than
    rounding at the ``sqrt(n)``-element Frobenius scale."""
    eps = float(np.finfo(np_dtype).eps)
    return eps / n, 10.0 * eps, 10.0 * eps * float(n) ** 0.5


def _grid_svd_fn(comm, shape, n, dtype_str, overlapped):
    """The QDWH polar SVD as ONE cached shard_map program ``f(a_padded)
    -> (u, s, v)`` over a ``(0, 1)``-laid-out tall operand.

    Per device: scale by the Frobenius norm (scalar all-gathers + ordered
    sums down both mesh axes — deterministic, unlike a bare psum), then a
    ``jax.lax.while_loop`` whose carry holds ``(X, l, k, delta)`` — the
    ``l`` lower-bound recurrence rides the carry, convergence is decided
    ON DEVICE (no host syncs, SPMD202-clean), and the static trip cap
    ``_QDWH_MAXIT`` bounds the program.  Each iteration stacks
    ``[sqrt(c)·X; I]`` (the identity block INCLUDES the pad diagonal —
    pad unit columns keep every panel full rank and provably wash out of
    the combine: their Q1 columns are exactly zero), runs the grid CAQR
    body (:func:`heat_tpu.core.linalg.qr._caqr_shard_body` — the same
    code the public grid QR dispatches), and combines ``X' = (b/c)·X +
    ((a - b/c)/sqrt(c))·Q1·Q2ᵀ`` in ``c`` panel-ordered steps of masked
    column broadcasts.  Epilogue: ``H = UpᵀA`` assembled via ordered
    gathers, symmetrized, eigendecomposed per device (replicated inputs
    give replicated outputs bit-for-bit), and ``U = Up·V`` reduced in
    mesh-column order."""
    key = ("svd.qdwh", comm, shape, n, dtype_str, _QDWH_MAXIT, overlapped)

    def make():
        ax0, ax1 = comm.axis_names
        r, c = comm.mesh_shape
        mloc = shape[0] // r
        nloc = shape[1] // c
        Np = c * nloc
        nploc = -(-Np // r)
        Npr = r * nploc
        qnloc, qbounds, qvcs = _grid_panel_schedule(Np, c, 1)
        l0, ltol, dtol = _qdwh_tols(n, np.dtype(dtype_str))

        def kern(a_loc):
            dt = a_loc.dtype
            i = jax.lax.axis_index(ax0)
            j = jax.lax.axis_index(ax1)
            zero = jnp.zeros((), dt)

            def scalar_reduce(v):
                g0 = jax.lax.all_gather(v, ax0)
                acc = g0[0]
                for b in range(1, r):
                    acc = acc + g0[b]
                g1 = jax.lax.all_gather(acc, ax1)
                acc = g1[0]
                for b in range(1, c):
                    acc = acc + g1[b]
                return acc

            def bcast_cols(x, owner):
                return jax.lax.psum(jnp.where(owner == j, x, zero), ax1)

            def colsum(x):
                g = jax.lax.all_gather(x, ax1)
                acc = g[0]
                for b in range(1, c):
                    acc = acc + g[b]
                return acc

            def gather_cols(x):
                g = jax.lax.all_gather(x, ax1)  # (c, rows, cols)
                return jnp.reshape(
                    jnp.moveaxis(g, 0, 1), (x.shape[0], c * x.shape[1])
                )

            alpha = jnp.sqrt(scalar_reduce(_sumsq(a_loc)))
            alpha = jnp.where(alpha > 0, alpha, jnp.ones((), dt))
            x0 = a_loc / alpha
            row_gid = i * nploc + jnp.arange(nploc)[:, None]
            col_gid = j * nloc + jnp.arange(nloc)[None, :]
            eye_block = (row_gid == col_gid).astype(dt)

            def cond(carry):
                _x, l, k, delta = carry
                return (k < _QDWH_MAXIT) & (
                    (delta > dtol) | (jnp.abs(1.0 - l) > ltol)
                )

            def body(carry):
                x, l, k, _delta = carry
                ca, cb, cc, ln = _qdwh_coeffs(l)
                sc = jnp.sqrt(cc).astype(dt)
                stacked = jnp.concatenate([sc * x, eye_block], axis=0)
                q_loc, _r_loc = _caqr_body(
                    stacked,
                    ax0=ax0,
                    ax1=ax1,
                    r=r,
                    c=c,
                    nloc=qnloc,
                    bounds=qbounds,
                    vcs=qvcs,
                    overlapped=overlapped,
                )
                q1 = q_loc[:mloc]
                q2f = jax.lax.all_gather(q_loc[mloc:], ax0, tiled=True)
                acc = jnp.zeros((mloc, Npr), dt)
                for t in range(c):
                    acc = acc + _mm(
                        bcast_cols(q1, t), bcast_cols(q2f, t).T
                    )
                m_loc = jax.lax.dynamic_slice_in_dim(acc, j * nloc, nloc, 1)
                ca = ca.astype(dt)
                cb = cb.astype(dt)
                cc = cc.astype(dt)
                x_new = (cb / cc) * x + ((ca - cb / cc) / sc) * m_loc
                delta = jnp.sqrt(scalar_reduce(_sumsq(x_new - x)))
                return x_new, ln.astype(l.dtype), k + 1, delta

            init = (
                x0,
                jnp.asarray(l0, x0.dtype),
                jnp.zeros((), jnp.int32),
                jnp.asarray(jnp.inf, x0.dtype),
            )
            up_loc, _l, _k, _delta = jax.lax.while_loop(cond, body, init)

            a_full = gather_cols(a_loc)  # (mloc, Np)
            g = jax.lax.all_gather(_mm(up_loc.T, a_full), ax0)
            h_rows = g[0]
            for b in range(1, r):
                h_rows = h_rows + g[b]  # (nloc, Np)
            h_full = jnp.reshape(
                jax.lax.all_gather(h_rows, ax1, tiled=True), (Np, Np)
            )
            h = h_full[:n, :n]
            hs = 0.5 * (h + h.T)
            evals, evecs = jnp.linalg.eigh(hs)
            s = evals[::-1]
            v = evecs[:, ::-1]
            vp = jnp.zeros((Np, Np), dt).at[:n, :n].set(v)
            u_part = _mm(
                up_loc, jax.lax.dynamic_slice_in_dim(vp, j * nloc, nloc, 0)
            )
            u_full = colsum(u_part)  # (mloc, Np)
            u_loc = jax.lax.dynamic_slice_in_dim(u_full, j * nloc, nloc, 1)
            return u_loc, s, v

        return _shard_map(
            kern,
            mesh=comm.mesh,
            in_specs=(_P(ax0, ax1),),
            out_specs=(_P(ax0, ax1), _P(), _P()),
            check_vma=False,
        )

    return _jitted(key, make)


def _grid_svd(a: DNDarray, dtype, compute_uv: bool):
    """Dispatch wrapper of the grid QDWH SVD: early guard with shapes and
    mesh in the message, zeroed buffer, one cached program, telemetry
    credited straight from :func:`heat_tpu.comm._costs.qdwh_svd_model`
    (op ``svd2d``), timed under the overlap policy."""
    from ...comm import _costs
    from ...comm.overlap import overlap_enabled, timed_dispatch

    comm, device = a.comm, a.device
    m, n = a.shape
    r, c = comm.mesh_shape
    mloc = -(-m // r)
    nloc = -(-n // c)
    Np = c * nloc
    nploc = -(-Np // r)
    if mloc + nploc < nloc:
        raise ValueError(
            f"svd: grid QDWH needs stacked shards at least as tall as a "
            f"column panel: {m}x{n} over the {r}x{c} mesh stacks "
            f"({mloc} + {nploc}) rows against panel width {nloc}; use a "
            f"taller matrix or a flatter mesh"
        )
    arr = a._zeroed_buffer()
    jt = dtype.jax_type()
    if arr.dtype != jt:
        arr = arr.astype(jt)
    ov = overlap_enabled(c)
    fn = _grid_svd_fn(comm, tuple(map(int, arr.shape)), n, str(arr.dtype), ov)
    if _tel.enabled:
        model = _costs.qdwh_svd_model(m, n, (r, c), iterations=_QDWH_MAXIT)
        _tel.account_bytes(
            "svd2d", "f32", model["exact_wire_bytes"], model["wire_bytes"]
        )
        with _tel.span(
            "comm:svd2d",
            mesh=f"{r}x{c}",
            iterations=_QDWH_MAXIT,
            overlap=ov,
        ):
            u_arr, s_arr, v_arr = timed_dispatch("svd2d", ov, lambda: fn(arr))
    else:
        u_arr, s_arr, v_arr = timed_dispatch("svd2d", ov, lambda: fn(arr))
    S = DNDarray(s_arr, (n,), dtype, None, device, comm, True)
    if not compute_uv:
        return S
    U = DNDarray(u_arr, (m, n), dtype, (0, 1), device, comm, True)
    V = DNDarray(v_arr, (n, n), dtype, None, device, comm, True)
    return SVD(U, S, V)


def _qdwh_svd_reference(arr, mesh_shape):
    """Replicated golden twin of the grid QDWH SVD: simulates the mesh's
    blocks in lockstep — the while_loop (same carry, same tolerances,
    same coefficient math, so the trip decisions agree bitwise), the
    stacked CAQR via :func:`heat_tpu.core.linalg.qr._caqr_sim`, the
    panel-ordered combine with explicit zero-block additions mirroring
    the masked psums, and the eigh epilogue.  One jitted program (eager
    execution changes XLA CPU's dot emission — see ``_mm``).  Returns
    ``(u_padded, s, v)`` bitwise-equal to the kernel's outputs.

    The golden replays the SERIAL panel order only: the kernel's overlap
    arm is pinned bitwise to its serial arm (asserted directly in
    tests/bench), so one canonical golden covers both.  Simulating the
    reordered overlap schedule inside this much larger program trips
    XLA CPU's fusion-context sensitivity in ops beyond the barriered
    matmuls/reductions — the two sim arms match bitwise in a minimal
    program but not embedded here, so we don't embed the second arm."""
    from .qr import _REFERENCE_CACHE

    r, c = mesh_shape
    m, n = arr.shape
    mloc = -(-m // r)
    nloc = -(-n // c)
    Mp, Np = r * mloc, c * nloc
    nploc = -(-Np // r)
    Npr = r * nploc
    qnloc, qbounds, qvcs = _grid_panel_schedule(Np, c, 1)
    l0, ltol, dtol = _qdwh_tols(n, np.dtype(arr.dtype.name))

    def run(x):
        dt = x.dtype
        zero = jnp.zeros((), dt)
        x = jnp.pad(x, ((0, Mp - m), (0, Np - n)))
        blocks = {
            (i, j): x[i * mloc : (i + 1) * mloc, j * nloc : (j + 1) * nloc]
            for i in range(r)
            for j in range(c)
        }

        def scalar_reduce(parts):
            # parts[(i, j)] -> the same gather order as the kernel: down
            # the mesh rows first, then along the columns
            col_acc = {}
            for j in range(c):
                acc = parts[(0, j)]
                for b in range(1, r):
                    acc = acc + parts[(b, j)]
                col_acc[j] = acc
            acc = col_acc[0]
            for b in range(1, c):
                acc = acc + col_acc[b]
            return acc

        def bcast_cols(vals_row, owner):
            acc = vals_row[0] if owner == 0 else jnp.where(False, vals_row[0], zero)
            for jp in range(1, c):
                acc = acc + (
                    vals_row[jp]
                    if owner == jp
                    else jnp.where(False, vals_row[jp], zero)
                )
            return acc

        alpha = jnp.sqrt(
            scalar_reduce({k: _sumsq(v) for k, v in blocks.items()})
        )
        alpha = jnp.where(alpha > 0, alpha, jnp.ones((), dt))
        x0 = {k: v / alpha for k, v in blocks.items()}
        eye = {
            (i, j): (
                (i * nploc + jnp.arange(nploc)[:, None])
                == (j * nloc + jnp.arange(nloc)[None, :])
            ).astype(dt)
            for i in range(r)
            for j in range(c)
        }

        def cond(carry):
            _x, l, k, delta = carry
            return (k < _QDWH_MAXIT) & (
                (delta > dtol) | (jnp.abs(1.0 - l) > ltol)
            )

        def body(carry):
            xb, l, k, _delta = carry
            ca, cb, cc, ln = _qdwh_coeffs(l)
            sc = jnp.sqrt(cc).astype(dt)
            stacked = {
                k2: jnp.concatenate([sc * xb[k2], eye[k2]], axis=0)
                for k2 in xb
            }
            qb, _rb = _caqr_sim(
                stacked,
                r=r,
                c=c,
                nloc=qnloc,
                bounds=qbounds,
                vcs=qvcs,
                overlapped=False,
            )
            q2f = {
                j: jnp.concatenate(
                    [qb[(b, j)][mloc:] for b in range(r)], axis=0
                )
                for j in range(c)
            }
            ca = ca.astype(dt)
            cb = cb.astype(dt)
            cc = cc.astype(dt)
            x_new = {}
            for i in range(r):
                acc = jnp.zeros((mloc, Npr), dt)
                for t in range(c):
                    q1_pan = bcast_cols(
                        [qb[(i, jp)][:mloc] for jp in range(c)], t
                    )
                    q2f_pan = bcast_cols([q2f[jp] for jp in range(c)], t)
                    acc = acc + _mm(q1_pan, q2f_pan.T)
                for j in range(c):
                    m_loc = jax.lax.dynamic_slice_in_dim(
                        acc, j * nloc, nloc, 1
                    )
                    x_new[(i, j)] = (cb / cc) * xb[(i, j)] + (
                        (ca - cb / cc) / sc
                    ) * m_loc
            delta = jnp.sqrt(
                scalar_reduce({k2: _sumsq(x_new[k2] - xb[k2]) for k2 in xb})
            )
            return x_new, ln.astype(l.dtype), k + 1, delta

        init = (
            x0,
            jnp.asarray(l0, dt),
            jnp.zeros((), jnp.int32),
            jnp.asarray(jnp.inf, dt),
        )
        up, _l, _k, _delta = jax.lax.while_loop(cond, body, init)

        a_full = {
            i: jnp.concatenate([blocks[(i, j)] for j in range(c)], axis=1)
            for i in range(r)
        }
        h_rows = {}
        for j in range(c):
            acc = _mm(up[(0, j)].T, a_full[0])
            for b in range(1, r):
                acc = acc + _mm(up[(b, j)].T, a_full[b])
            h_rows[j] = acc
        h_full = jnp.concatenate([h_rows[j] for j in range(c)], axis=0)
        h = h_full[:n, :n]
        hs = 0.5 * (h + h.T)
        evals, evecs = jnp.linalg.eigh(hs)
        s = evals[::-1]
        v = evecs[:, ::-1]
        vp = jnp.zeros((Np, Np), dt).at[:n, :n].set(v)
        u_rows = []
        for i in range(r):
            parts = [
                _mm(
                    up[(i, j)],
                    jax.lax.dynamic_slice_in_dim(vp, j * nloc, nloc, 0),
                )
                for j in range(c)
            ]
            acc = parts[0]
            for b in range(1, c):
                acc = acc + parts[b]
            u_rows.append(acc)
        u = jnp.concatenate(u_rows, axis=0)  # (Mp, Np)
        return u, s, v

    key = ("qdwh", mesh_shape, (m, n), str(arr.dtype))
    fn = _REFERENCE_CACHE.get(key)
    if fn is None:
        fn = _REFERENCE_CACHE[key] = _jax.jit(run)
    return fn(arr)


from .._split_semantics import split_semantics as _split_semantics


@_split_semantics("entry_svd")
def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """Reduced SVD ``a = U @ diag(S) @ V.T``.

    Returns the namedtuple ``SVD(U, S, V)``; with ``compute_uv=False`` only
    ``S`` (as a DNDarray).  The on-device configurations compile the whole
    QR→SVD→correction chain into one fused program (one device dispatch
    per call after warmup); the host-SVD escape hatch and float64 operands
    keep the eager chain, since their small factor legitimately visits
    LAPACK mid-pipeline.
    """
    sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"svd requires a 2-D DNDarray, got {a.ndim}-d")
    if full_matrices:
        raise NotImplementedError("full_matrices=True is not supported (reduced SVD only)")

    dtype = a.dtype if types.heat_type_is_inexact(a.dtype) else types.float32
    m, n = a.shape

    comm = a.comm
    if comm.mesh_ndim == 2 and comm.size > 1 and a.splits in ((0, 1), (1, 0)):
        # grid QDWH polar SVD (arXiv 2112.09017): wide inputs factor the
        # transpose — its (1, 0) layout is re-committed to (0, 1) by one
        # planned redistribution — and swap U with V; the generic wide
        # recursion below cannot do this (a.T's tuple layout would fall
        # into the 1-D tall chain and gather)
        if m < n:
            res = svd(a.T.resplit((0, 1)), compute_uv=compute_uv)
            if not compute_uv:
                return res
            return SVD(res.V, res.S, res.U)
        if a.splits == (1, 0):
            a = a.resplit((0, 1))
        return _grid_svd(a, dtype, compute_uv)

    if m < n:
        # wide: factor the transpose, swap U and V
        if not compute_uv:
            return svd(a.T, compute_uv=False)
        res = svd(a.T, compute_uv=True)
        return SVD(res.V, res.S, res.U)

    impl = _svd_pipeline if _host_svd() or dtype is types.float64 else _fused_svd_pipeline
    return impl(a, a.split, dtype, compute_uv)
