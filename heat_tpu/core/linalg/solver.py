"""Iterative solvers: conjugate gradients and Lanczos.

Reference: heat/core/linalg/solver.py:8-184 — pure compositions of matmul
and reductions; the distributed work all happens inside those primitives,
which is equally true here.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import factories, types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from . import basics

__all__ = ["cg", "lanczos"]


@jax.jit
def _cg_loop(arr, bv, xv):
    """Full conjugate-gradient iteration on device; jitted once at module
    level so repeat solves of the same shape replay the cached program."""
    # stable carry dtype: promote all operands to one inexact type up front
    ctype = jnp.result_type(arr.dtype, bv.dtype, xv.dtype, jnp.float32)
    arr, bv, xv = arr.astype(ctype), bv.astype(ctype), xv.astype(ctype)
    r0 = bv - arr @ xv
    init = (jnp.int32(0), xv, r0, r0, jnp.dot(r0, r0))

    def cond(s):
        it, _, _, _, rsold = s
        # ~(x < tol) rather than x >= tol: NaN must keep iterating so bad
        # inputs propagate instead of silently returning x0
        return jnp.logical_and(it < bv.shape[0], ~(jnp.sqrt(rsold) < 1e-10))

    def body(s):
        it, x, r, p, rsold = s
        Ap = arr @ p
        alpha = rsold / jnp.dot(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = jnp.dot(r, r)
        p = r + (rsnew / rsold) * p
        return it + 1, x, r, p, rsnew

    _, x, _, _, _ = jax.lax.while_loop(cond, body, init)
    return x


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for SPD ``A`` (reference solver.py:8-73)."""
    sanitize_in(A)
    sanitize_in(b)
    sanitize_in(x0)
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    # the whole iteration as ONE device while_loop (the reference,
    # solver.py:39-52, pays three host round-trips per step for the
    # .item() reductions; here the convergence test stays on device)
    xres = _cg_loop(A.larray, b.larray, x0.larray)
    x = DNDarray(
        x0.comm.apply_sharding(xres, x0.split),
        tuple(xres.shape),
        types.canonical_heat_type(xres.dtype),
        x0.split,
        x0.device,
        x0.comm,
        True,
    )
    if out is not None:
        out.larray = x.larray
        return out
    return x


@jax.jit
def _lanczos_segment(arr, R, start, stop, carry):
    """Lanczos steps ``[start, stop)`` as ONE device program.

    The reference (solver.py:74-184) — and this module until the fuse PR —
    decided breakdown-restart on the host with ``float(beta)``, a blocking
    device→host sync per iteration.  Here the decision is a ``jnp.where``
    select between the normal step and a restart candidate drawn from the
    pre-generated random matrix ``R`` (one column per iteration), so the
    steps run as a single ``fori_loop`` with zero host syncs.

    Re-enterable: the carry ``(V, T, w, v_prev)`` comes in explicitly and
    the ``fori_loop`` bounds are dynamic — a plain call runs one segment
    with ``(1, m)``; a checkpointed call replays THIS program segment by
    segment (snapshotting the carry plus the restart matrix ``R`` between
    segments), which is what makes resume bitwise-exact.

    The full re-orthogonalization projects against ALL m columns of V:
    columns ≥ i are still zero, so their coefficients vanish and the
    projection equals the reference's ``V[:, :i]`` slice — this is what
    lets the loop body stay shape-static inside ``fori_loop``.
    """

    def body(i, state):
        V, T, w, v_prev = state
        beta = jnp.linalg.norm(w)
        breakdown = beta < 1e-10
        # restart candidate: random column re-orthogonalized against V
        # (reference :120-130); computed unconditionally — a lax.cond would
        # re-trace both branches anyway and the extra matvec is noise next
        # to the m host syncs this loop used to pay
        vr = jnp.take(R, i, axis=1).astype(arr.dtype)
        vr = vr - V @ (V.T @ vr)
        vr_nrm = jnp.linalg.norm(vr)
        vr = jnp.where(vr_nrm > 0, vr / vr_nrm, vr)
        w = jnp.where(breakdown, vr, w / jnp.where(breakdown, 1.0, beta))
        # full re-orthogonalization (reference :140-152)
        w = w - V @ (V.T @ w)
        nrm = jnp.linalg.norm(w)
        w = jnp.where(nrm > 0, w / nrm, w)
        V = V.at[:, i].set(w)
        wnew = arr @ w
        alpha = jnp.dot(wnew, w)
        w_next = wnew - alpha * w - beta * v_prev
        T = T.at[i, i].set(alpha)
        T = T.at[i - 1, i].set(beta)
        T = T.at[i, i - 1].set(beta)
        return V, T, w_next, w

    return jax.lax.fori_loop(start, stop, body, carry)


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
    checkpoint_every: int = 0,
    checkpoint_path: Optional[str] = None,
    resume=False,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization with full re-orthogonalization
    (reference solver.py:74-184).  Returns (V, T) with ``T = V.T A V``
    tridiagonal, ``V`` the (n, m) orthonormal Krylov basis.

    The reference re-orthogonalizes rank-locally and Allreduces dot
    products (:140-152); here the inner products on the sharded vectors
    compile to all-reduces automatically, and the whole m-step iteration —
    including the breakdown-restart decision, formerly a ``float(beta)``
    host sync per step — runs as one compiled device loop.

    With ``checkpoint_every=N`` the iteration runs in N-step segments of
    the same compiled program, snapshotting the carry (and the
    breakdown-restart matrix, so restart draws replay too) to
    ``checkpoint_path`` between segments; ``resume=True`` restarts from
    the snapshot and finishes bitwise-identical to an uninterrupted run.
    ``resume="elastic"`` additionally accepts a snapshot taken at a
    different mesh size (the Lanczos carry is replicated, so migration
    is a pass-through).
    """
    sanitize_in(A)
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")
    if not isinstance(m, int) or m <= 0:
        raise RuntimeError("m must be a positive integer")

    n = A.shape[0]
    arr = A.larray.astype(jnp.float32 if types.heat_type_is_exact(A.dtype) else A.larray.dtype)

    from .. import random
    from ...resilience import elastic as _elastic
    from ...resilience.resume import LoopCheckpointer

    ckpt = LoopCheckpointer(
        checkpoint_path, checkpoint_every, "lanczos",
        {"n": int(n), "m": int(m)}, comm=A.comm,
        splits={"i": None, "V": None, "T": None, "w": None,
                "v_prev": None, "R": None},
    )
    if resume:
        state, _ = ckpt.load(elastic=resume == "elastic")
        R = jnp.asarray(state["R"], jnp.float32)
        carry = (
            jnp.asarray(state["V"], arr.dtype),
            jnp.asarray(state["T"], arr.dtype),
            jnp.asarray(state["w"], arr.dtype),
            jnp.asarray(state["v_prev"], arr.dtype),
        )
        it = int(state["i"])
    else:
        if v0 is None:
            # draws land on A's communicator so sub-mesh fits (elastic
            # recovery on a shrunk device set) don't mix device sets
            v = random.rand(
                n, dtype=types.float32, device=A.device, comm=A.comm
            ).larray
            v = v / jnp.linalg.norm(v)
        else:
            sanitize_in(v0)
            v = v0.larray / jnp.linalg.norm(v0.larray)
        v = v.astype(arr.dtype)
        # breakdown-restart candidates, one per iteration (drawn per fit,
        # used on device only when the matching step actually breaks down)
        R = random.rand(
            n, m, dtype=types.float32, device=A.device, comm=A.comm
        ).larray

        V = jnp.zeros((n, m), dtype=arr.dtype).at[:, 0].set(v)
        w0 = arr @ v
        alpha0 = jnp.dot(w0, v)
        T = jnp.zeros((m, m), dtype=arr.dtype).at[0, 0].set(alpha0)
        carry = (V, T, w0 - alpha0 * v, v)
        it = 1

    while it < m:
        stop = ckpt.stop(it, m)
        with _elastic.dispatch_guard("lanczos.seg", A.comm):
            carry = _lanczos_segment(arr, R, jnp.int32(it), jnp.int32(stop), carry)
        it = stop
        if it >= m:
            break
        ckpt.tick(
            it,
            {"i": jnp.int32(it), "V": carry[0], "T": carry[1],
             "w": carry[2], "v_prev": carry[3], "R": R},
        )
    V, T = carry[0], carry[1]

    comm, device = A.comm, A.device
    V_nd = DNDarray(comm.apply_sharding(V, 0 if A.split is not None else None), (n, m),
                    types.canonical_heat_type(V.dtype), 0 if A.split is not None else None,
                    device, comm, True)
    T_nd = DNDarray(T, (m, m), types.canonical_heat_type(T.dtype), None, device, comm, True)
    if V_out is not None:
        V_out.larray = V_nd.larray
        T_out.larray = T_nd.larray
        return V_out, T_out
    return V_nd, T_nd
