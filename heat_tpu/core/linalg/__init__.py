"""Distributed linear algebra (reference: heat/core/linalg/__init__.py)."""

from . import basics, solver
from .basics import *
from .qr import qr, QR
from .solver import *
from .svd import svd, SVD
