"""Distributed linear algebra basics.

Reference: heat/core/linalg/basics.py:16-1269.  The centerpiece there is a
780-line hand-written block-distributed SUMMA ``matmul`` covering all four
split combinations with Isend/Irecv block exchanges (:285-787), whose point
is an O(n²/p) per-rank memory guarantee.  GSPMD does NOT honor that
guarantee: measured on an 8-device mesh, its plan for splits 00/01/11
all-gathers one full operand per device (f32[1024,1024] at m=k=n=1024) —
fine at laptop scale, an OOM at pod scale.  So 2-D matmuls on those combos
run an explicit ring SUMMA (``_summa``: shard_map + ppermute, p rounds,
one visiting shard at a time — the reference's schedule re-expressed as an
ICI ring program), pinned by HLO assertions in tests/test_hlo_matmul.py.
Split 10 and everything else (vectors, batched) keep the compiler plan:
there GSPMD's single result all-reduce IS the right schedule.  The module
keeps the reference's *semantics* throughout: dtype promotion, the
vector/matrix edge cases, and the result-split rules for every split
combination (basics.py:168-283).
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import numpy as np

import jax.numpy as jnp

from jax.sharding import PartitionSpec

from .. import factories, types
from .._compile import jitted
from .._jax_compat import pcast, shard_map
from .._tracing import record_dispatch
from ..communication import sanitize_comm
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from ..stride_tricks import sanitize_axis
from ...telemetry import _core as _tel

__all__ = [
    "dot",
    "get_matmul_precision",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "set_matmul_precision",
    "transpose",
    "tril",
    "triu",
    "vector_norm",
]

# On TPU the MXU's default matmul precision is bfloat16-accumulate, which is
# far below the reference's float32 torch numerics (observed: ||QR - A||
# ~0.3 instead of ~1e-5 on a 1024×16 factorization).  This framework is a
# numerics-parity analytics stack first, so linalg defaults to 'highest'
# (fp32 accumulation via multiple MXU passes); benchmarks that want raw MXU
# throughput can switch to 'default' (bf16) or 'float32' (3-pass).
_MATMUL_PRECISION = "highest"


def set_matmul_precision(precision: str) -> None:
    """Set the MXU precision for all linalg matmuls:
    'default' (bf16 inputs), 'float32', or 'highest'."""
    global _MATMUL_PRECISION
    if precision not in ("default", "float32", "highest"):
        raise ValueError(f"invalid precision {precision!r}")
    _MATMUL_PRECISION = precision


def get_matmul_precision() -> str:
    """The current MXU matmul precision for linalg ops."""
    return _MATMUL_PRECISION


def _precision():
    return None if _MATMUL_PRECISION == "default" else _MATMUL_PRECISION


def _result_split_matmul(a: DNDarray, b: DNDarray, out_ndim: int) -> Optional[int]:
    """Result-split rule for matmul, mirroring reference basics.py:168-283:
    split=0 @ anything → row-split result; anything @ split=1 → col-split;
    a.split=1 @ b.split=0 contracts the split axis → split=None (the
    all-reduce case)."""
    if out_ndim == 0:
        return None
    if a.split == 0 and a.ndim > 1:
        return 0
    if b.split is not None and b.ndim > 1 and b.split == b.ndim - 1:
        return out_ndim - 1
    if a.split is not None or b.split is not None:
        # contraction over the split axis (or vector operands): replicate,
        # XLA will have inserted the psum
        return None
    return None


def _summa_fn(sa: int, sb: int, comm, precision, chunk: int):
    """The jitted shard_map ring-matmul program for one split combo —
    cached per (combo, comm, precision, chunk), and exposed so the HLO
    tests lower the EXACT production program (tests/test_hlo_matmul.py).
    ``chunk`` is the rotating operand's shard width along its split axis;
    the padded global widths are ``chunk * comm.size``."""
    import jax
    from jax.sharding import PartitionSpec as P

    key = (sa, sb, comm, precision, chunk)
    cached = _SUMMA_CACHE.get(key)
    if cached is not None:
        return cached

    p, mesh, axis = comm.size, comm.mesh, comm.axis_name
    perm = [(i, (i + 1) % p) for i in range(p)]

    if (sa, sb) == (0, 0):
        # A (Mp/p, Kp) stationary; B's k-shards (Kp/p, N) rotate — chunk
        # r of A's columns multiplies the shard that originated at r
        def kern(a_loc, b_blk):
            my = jax.lax.axis_index(axis)

            def body(r, carry):
                b_blk, acc = carry
                origin = (my - r) % p
                a_chunk = jax.lax.dynamic_slice_in_dim(
                    a_loc, origin * chunk, chunk, 1
                )
                acc = acc + jnp.matmul(a_chunk, b_blk, precision=precision)
                return jax.lax.ppermute(b_blk, axis, perm), acc

            acc0 = pcast(
                jnp.zeros((a_loc.shape[0], b_blk.shape[1]), a_loc.dtype),
                (axis,), to="varying",
            )
            _, acc = jax.lax.fori_loop(0, p, body, (b_blk, acc0))
            return acc

        ins, outs = (P(axis, None), P(axis, None)), P(axis, None)
    elif (sa, sb) == (0, 1):
        # A (Mp/p, K) stationary; B's column shards (K, Np/p) rotate,
        # each landing in its own slice of the (Mp/p, Np) result columns
        def kern(a_loc, b_blk):
            my = jax.lax.axis_index(axis)

            def body(r, carry):
                b_blk, acc = carry
                origin = (my - r) % p
                prod = jnp.matmul(a_loc, b_blk, precision=precision)
                col = origin * chunk  # axis_index dtype; zero must match
                acc = jax.lax.dynamic_update_slice(
                    acc, prod, (jnp.zeros((), col.dtype), col)
                )
                return jax.lax.ppermute(b_blk, axis, perm), acc

            acc0 = pcast(
                jnp.zeros((a_loc.shape[0], chunk * p), a_loc.dtype),
                (axis,), to="varying",
            )
            _, acc = jax.lax.fori_loop(0, p, body, (b_blk, acc0))
            return acc

        ins, outs = (P(axis, None), P(None, axis)), P(axis, None)
    else:
        # (1, 1): B (Kp, Np/p) stationary; A's k-shards (M, Kp/p) rotate,
        # each contracting against its slice of B's rows
        def kern(a_blk, b_loc):
            my = jax.lax.axis_index(axis)

            def body(r, carry):
                a_blk, acc = carry
                origin = (my - r) % p
                b_chunk = jax.lax.dynamic_slice_in_dim(
                    b_loc, origin * chunk, chunk, 0
                )
                acc = acc + jnp.matmul(a_blk, b_chunk, precision=precision)
                return jax.lax.ppermute(a_blk, axis, perm), acc

            acc0 = pcast(
                jnp.zeros((a_blk.shape[0], b_loc.shape[1]), a_blk.dtype),
                (axis,), to="varying",
            )
            _, acc = jax.lax.fori_loop(0, p, body, (a_blk, acc0))
            return acc

        ins, outs = (P(None, axis), P(None, axis)), P(None, axis)

    fn = jax.jit(shard_map(kern, mesh=mesh, in_specs=ins, out_specs=outs))
    _SUMMA_CACHE[key] = fn
    return fn


#: (sa, sb, comm, precision, chunk) -> jitted program; comm objects are
#: long-lived singletons, so this never grows past a handful of entries
_SUMMA_CACHE: dict = {}


def _summa(aa, ba, sa: int, sb: int, comm, precision):
    """Ring (SUMMA-style) matmul for the split combinations where GSPMD
    chooses to ALL-GATHER a full operand — split 00, 01 and 11 (verified
    in HLO: a `f32[m,k]`/`f32[k,n]` all-gather per device, i.e. O(n²)
    per-device memory; the reference's hand-written SUMMA,
    basics.py:285-787, guarantees O(n²/p)).

    One operand stays stationary; the other's shards rotate around the
    mesh ring with ``ppermute`` (p rounds), each round contributing one
    block product.  Per-device memory: own shards + one visiting shard +
    the local result block — the reference's guarantee, on ICI.

    ``aa``/``ba`` are the PADDED buffers (split axes at canonical width);
    non-split contraction axes are zero-padded here when ragged, and the
    pad region always multiplies those zeros, so the at-rest buffers'
    unspecified pad values never reach the result.  Returns the padded
    sharded result and its split.
    """
    p = comm.size
    if (sa, sb) == (0, 0):
        Kp = comm.padded_size(aa.shape[1])
        if Kp != aa.shape[1]:
            aa = jnp.pad(aa, ((0, 0), (0, Kp - aa.shape[1])))
            aa = comm.apply_sharding(aa, 0)
        chunk = Kp // p
        out_split = 0
    elif (sa, sb) == (0, 1):
        chunk = ba.shape[1] // p  # ba padded on its split axis already
        out_split = 0
    else:  # (1, 1)
        Kp = aa.shape[1]
        if ba.shape[0] != Kp:
            ba = jnp.pad(ba, ((0, Kp - ba.shape[0]), (0, 0)))
            ba = comm.apply_sharding(ba, 1)
        chunk = Kp // p
        out_split = 1
    out = _summa_fn(sa, sb, comm, precision, chunk)(aa, ba)
    return out, out_split


def _summa_grid_fn(comm, precision, w: int, overlapped: bool, layout: str = "grid"):
    """The jitted grid-SUMMA program for an r×c mesh — cached per
    (comm, precision, panel width, overlap arm, layout) like
    :func:`_summa_fn`.

    ``layout="grid"``: both operands carry splits ``(0, 1)``: local A is
    ``(Mp/r, Kp/c)`` and local B ``(Kp/r, Np/c)`` with ``Kp = r*c*w``.
    Panel ``t`` of the k axis lives on mesh column ``t // r`` of A (local
    offset ``(t % r) * w``) and on mesh row ``t // c`` of B (offset
    ``(t % c) * w``); each of the ``L = r*c`` steps broadcasts the two
    panels with a masked psum (exact: one owner's values plus zeros) and
    accumulates one ``(Mp/r, w) @ (w, Np/c)`` block product — per-device
    memory O(mn/rc) plus two panels.  The overlap arm issues panel
    ``t+1``'s broadcasts before consuming panel ``t`` (the
    double-buffering discipline of docs/design.md §18); the accumulation
    order is identical, so the two arms are bitwise-equal.

    ``layout="rowcol"``: A splits ``(0, None)`` — local ``(Mp/r, Kp)`` —
    against B splits ``(None, 1)`` — local ``(Kp, Np/c)``.  Every device
    already holds the full contraction extent for its output block, so
    the SAME L-panel accumulation runs rank-local with ZERO collectives;
    keeping the panel order (rather than one monolithic matmul) is what
    pins the result bitwise to the shared replicated twin.

    ``layout="colrow"``: A splits ``(None, 1)`` — local ``(Mp, Kp/c)``
    (the k axis sharded along the mesh columns) — against B splits
    ``(0, None)`` — local ``(Kp/r, Np)``.  The owner of panel ``t``
    slices its own row/column block of the panel before the masked psum,
    so the broadcasts ship exactly the grid schedule's bytes and the
    accumulation order is again panel-identical."""
    import jax
    from jax.sharding import PartitionSpec as P

    key = ("2d", comm, precision, w, overlapped, layout)
    cached = _SUMMA_CACHE.get(key)
    if cached is not None:
        return cached

    r, c = comm.mesh_shape
    ax0, ax1 = comm.axis_names
    L = r * c

    if layout == "rowcol":

        def panels(a_loc, b_loc, t):
            a_pan = jax.lax.dynamic_slice_in_dim(a_loc, t * w, w, 1)
            b_pan = jax.lax.dynamic_slice_in_dim(b_loc, t * w, w, 0)
            return a_pan, b_pan

    elif layout == "colrow":

        def panels(a_loc, b_loc, t):
            mloc = a_loc.shape[0] // r
            nloc = b_loc.shape[1] // c
            i = jax.lax.axis_index(ax0)
            j = jax.lax.axis_index(ax1)
            a_cand = jax.lax.dynamic_slice_in_dim(
                jax.lax.dynamic_slice_in_dim(a_loc, i * mloc, mloc, 0),
                (t % r) * w, w, 1,
            )
            a_pan = jax.lax.psum(
                jnp.where(t // r == j, a_cand, jnp.zeros((), a_cand.dtype)),
                ax1,
            )
            b_cand = jax.lax.dynamic_slice_in_dim(
                jax.lax.dynamic_slice_in_dim(b_loc, j * nloc, nloc, 1),
                (t % c) * w, w, 0,
            )
            b_pan = jax.lax.psum(
                jnp.where(t // c == i, b_cand, jnp.zeros((), b_cand.dtype)),
                ax0,
            )
            return a_pan, b_pan

    else:

        def panels(a_loc, b_loc, t):
            a_cand = jax.lax.dynamic_slice_in_dim(a_loc, (t % r) * w, w, 1)
            a_pan = jax.lax.psum(
                jnp.where(t // r == jax.lax.axis_index(ax1), a_cand,
                          jnp.zeros((), a_cand.dtype)),
                ax1,
            )
            b_cand = jax.lax.dynamic_slice_in_dim(b_loc, (t % c) * w, w, 0)
            b_pan = jax.lax.psum(
                jnp.where(t // c == jax.lax.axis_index(ax0), b_cand,
                          jnp.zeros((), b_cand.dtype)),
                ax0,
            )
            return a_pan, b_pan

    def kern(a_loc, b_loc):
        if layout == "colrow":
            out_shape = (a_loc.shape[0] // r, b_loc.shape[1] // c)
        else:
            out_shape = (a_loc.shape[0], b_loc.shape[1])
        acc0 = pcast(
            jnp.zeros(out_shape, a_loc.dtype),
            (ax0, ax1), to="varying",
        )
        if overlapped:

            def body(t, carry):
                a_pan, b_pan, acc = carry
                nxt = panels(a_loc, b_loc, jnp.minimum(t + 1, L - 1))
                acc = acc + jnp.matmul(a_pan, b_pan, precision=precision)
                return nxt + (acc,)

            first = panels(a_loc, b_loc, 0)
            _, _, acc = jax.lax.fori_loop(0, L, body, first + (acc0,))
        else:

            def body(t, acc):
                a_pan, b_pan = panels(a_loc, b_loc, t)
                return acc + jnp.matmul(a_pan, b_pan, precision=precision)

            acc = jax.lax.fori_loop(0, L, body, acc0)
        return acc

    in_specs = {
        "grid": (P(ax0, ax1), P(ax0, ax1)),
        "rowcol": (P(ax0, None), P(None, ax1)),
        "colrow": (P(None, ax1), P(ax0, None)),
    }[layout]
    fn = jax.jit(
        shard_map(
            kern, mesh=comm.mesh,
            in_specs=in_specs,
            out_specs=P(ax0, ax1),
            check_vma=False,
        )
    )
    _SUMMA_CACHE[key] = fn
    return fn


def _summa_grid(aa, ba, dims, comm, precision, layout: str = "grid"):
    """Dispatch wrapper of the grid SUMMA: pads both operands' k axes to
    the panel grid ``Kp = r*c*w`` (``w = ceil(k / (r*c))``; ``Kp`` is >=
    both at-rest padded k extents, so the pad only grows and stays
    divisible), commits the layout's splits, and launches the ONE
    compiled program — explicitly counted via :func:`record_dispatch`,
    credited to the telemetry ledger with figures straight from
    :func:`heat_tpu.comm._costs.summa_grid_model` (delegation keeps the
    accounted and modeled bytes byte-identical), and timed under the
    overlap policy.

    ``layout`` picks the operand schedule (see :func:`_summa_grid_fn`):
    ``"grid"`` for ``(0,1)×(0,1)``, ``"rowcol"`` for ``(0,None)×(None,1)``
    (rank-local, zero wire — the overlap policy is moot, so the serial
    arm always runs), ``"colrow"`` for ``(None,1)×(0,None)``."""
    import jax

    from ...comm import _costs
    from ...comm.overlap import overlap_enabled, timed_dispatch

    m, k, n = dims
    r, c = comm.mesh_shape
    L = r * c
    w = -(-k // L)
    Kp = L * w
    if aa.shape[1] != Kp:
        aa = jnp.pad(aa, ((0, 0), (0, Kp - aa.shape[1])))
    if ba.shape[0] != Kp:
        ba = jnp.pad(ba, ((0, Kp - ba.shape[0]), (0, 0)))
    if layout == "colrow":
        # the unsharded result axes must land on the r×c output grid
        Mp = r * (-(-m // r))
        Np = c * (-(-n // c))
        if aa.shape[0] != Mp:
            aa = jnp.pad(aa, ((0, Mp - aa.shape[0]), (0, 0)))
        if ba.shape[1] != Np:
            ba = jnp.pad(ba, ((0, 0), (0, Np - ba.shape[1])))
    splits_a, splits_b = {
        "grid": ((0, 1), (0, 1)),
        "rowcol": ((0, None), (None, 1)),
        "colrow": ((None, 1), (0, None)),
    }[layout]
    aa = comm.apply_sharding(aa, splits_a)
    ba = comm.apply_sharding(ba, splits_b)
    ov = overlap_enabled(L) if layout != "rowcol" else False
    fn = _summa_grid_fn(comm, precision, w, ov, layout)
    if isinstance(aa, jax.core.Tracer) or isinstance(ba, jax.core.Tracer):
        return fn(aa, ba)
    record_dispatch()
    if _tel.enabled:
        model = _costs.summa_grid_model(m, k, n, (r, c), overlap=ov, layout=layout)
        _tel.account_bytes(
            "summa2d", "f32", model["exact_wire_bytes"], model["wire_bytes"]
        )
        with _tel.span("comm:summa2d", mesh=f"{r}x{c}", panels=L, layout=layout):
            return timed_dispatch("summa2d", ov, lambda: fn(aa, ba))
    return timed_dispatch("summa2d", ov, lambda: fn(aa, ba))


def matmul(
    a: DNDarray,
    b: DNDarray,
    out: Optional[DNDarray] = None,
    precision: Optional[str] = None,
) -> DNDarray:
    """Matrix product of two DNDarrays (reference basics.py:71-787).

    All four split combinations are supported.  For 2-D operands with
    splits 00/01/11 on a 1-D mesh a ring SUMMA (shard_map + ppermute)
    keeps per-device memory at O(1/p) — GSPMD's plan for those combos
    all-gathers a full operand (see _summa).  Split 10 contracts the
    shared axis: GSPMD's single result all-reduce IS the right schedule
    there, and the other cases (vectors, batched) keep the compiler plan
    too.  On a 2-D (grid) mesh, operands both laid out splits ``(0, 1)``
    run the grid SUMMA (:func:`_summa_grid_fn`): k-panel broadcasts on
    the row/column sub-rings, one compiled dispatch, per-device memory
    O(mn/rc + panels) — the payoff workload of arXiv 2112.09017.

    ``out`` receives the result values in place.  ``precision`` overrides
    the process-wide matmul precision for this call (``'default'`` |
    ``'float32'`` | ``'highest'``, see :func:`set_matmul_precision`).
    """
    sanitize_in(a)
    sanitize_in(b)
    if precision is None:
        prec = _precision()
    elif precision in ("default", "float32", "highest"):
        prec = None if precision == "default" else precision
    else:
        raise ValueError(f"invalid precision {precision!r}")
    if a.ndim == 0 or b.ndim == 0:
        raise ValueError("matmul does not accept 0-d operands (use mul)")
    # numpy contraction rule: last axis of a against b's second-to-last
    # (or only) axis — mismatches are the reference's ValueError contract
    # (basics.py:83-96), not a backend TypeError
    k_a = a.shape[-1]
    k_b = b.shape[-2] if b.ndim >= 2 else b.shape[0]
    if k_a != k_b:
        raise ValueError(
            f"matmul shape mismatch: {a.shape} @ {b.shape} "
            f"(contracting {k_a} vs {k_b})"
        )
    # batched operands: leading dims must broadcast, same ValueError contract
    if a.ndim > 2 or b.ndim > 2:
        batch_a = a.shape[:-2] if a.ndim > 2 else ()
        batch_b = b.shape[:-2] if b.ndim > 2 else ()
        for da, db in zip(reversed(batch_a), reversed(batch_b)):
            if da != db and da != 1 and db != 1:
                raise ValueError(
                    f"matmul batch dimensions do not broadcast: "
                    f"{a.shape} @ {b.shape} ({da} vs {db})"
                )
    promoted = types.promote_types(a.dtype, b.dtype)
    jt = promoted.jax_type()
    comm = a.comm
    grid_layout = None
    if a.ndim == 2 and b.ndim == 2 and comm.mesh_ndim == 2 and comm.size > 1:
        if a.splits == (0, 1) and b.splits == (0, 1):
            grid_layout = "grid"
        elif a.splits == (0, None) and b.splits == (None, 1):
            grid_layout = "rowcol"
        elif a.splits == (None, 1) and b.splits == (0, None):
            grid_layout = "colrow"
    if grid_layout is not None:
        # grid SUMMA on the r×c mesh — "grid" for (0,1)×(0,1) operands,
        # plus the rank-local schedules: "rowcol" (0,None)×(None,1) runs
        # the same panel accumulation with ZERO wire, "colrow"
        # (None,1)×(0,None) ships the grid schedule's bytes while eliding
        # the two planned redistributions.  BOTH operands ship the ZEROED
        # buffer — at-rest pad values are unspecified and can be
        # non-finite, and 0 * inf = NaN would poison the k-sum (the same
        # discipline as the 1-D combos below)
        aa = a._zeroed_buffer()
        ba = b._zeroed_buffer()
        aa = aa.astype(jt) if aa.dtype != jt else aa
        ba = ba.astype(jt) if ba.dtype != jt else ba
        garr = _summa_grid(
            aa, ba, (a.shape[0], a.shape[1], b.shape[1]), comm, prec,
            grid_layout,
        )
        result = DNDarray(
            garr, (a.shape[0], b.shape[1]), promoted, (0, 1), a.device, comm, True
        )
    elif (
        a.ndim == 2
        and b.ndim == 2
        and comm.mesh_ndim == 1
        and comm.size > 1
        and (a.split, b.split) in ((0, 0), (0, 1), (1, 1))
    ):
        # ring SUMMA: O(1/p) per-device memory where GSPMD would
        # all-gather a full operand (tests/test_hlo_matmul.py pins this)
        # the operand whose SPLIT axis is the contraction axis ships the
        # ZEROED buffer: at-rest pad values are unspecified and can be
        # non-finite (ht.log leaves -inf pad rows), and 0 * inf = NaN
        # would poison every real output element through the k-sum
        zero_a = (a.split, b.split) == (1, 1)  # a's axis 1 == k
        zero_b = (a.split, b.split) == (0, 0)  # b's axis 0 == k
        aa = (a._zeroed_buffer() if zero_a else a._buffer).astype(jt)
        ba = (b._zeroed_buffer() if zero_b else b._buffer).astype(jt)
        garr, split = _summa(aa, ba, a.split, b.split, comm, prec)
        if (a.split, b.split) == (0, 1):
            garr = garr[:, : b.shape[1]]  # drop B's column padding
        result = DNDarray(
            garr, (a.shape[0], b.shape[1]), promoted, split, a.device, comm, True
        )
    else:
        aa = a.larray.astype(jt)
        ba = b.larray.astype(jt)
        garr = jnp.matmul(aa, ba, precision=prec)
        split = _result_split_matmul(a, b, garr.ndim)
        garr = comm.apply_sharding(garr, split)
        result = DNDarray(
            garr, tuple(garr.shape), promoted, split, a.device, comm, True
        )
    if out is not None:
        sanitize_in(out)
        out.larray = result.larray
        return out
    return result


def dot(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None):
    """Dot product (reference basics.py:16-70: 1-D = local dot + Allreduce;
    2-D delegates to matmul; scalars multiply)."""
    if isinstance(a, DNDarray) and isinstance(b, DNDarray):
        if a.ndim == 0 or b.ndim == 0:
            from .. import arithmetics

            return arithmetics.mul(a, b)
        if a.ndim == 1 and b.ndim == 1:
            res = jnp.dot(a.larray, b.larray, precision=_precision())
            result = DNDarray(
                res, (), types.promote_types(a.dtype, b.dtype), None, a.device, a.comm, True
            )
            if out is not None:
                out.larray = result.larray
                return out
            return result
        return matmul(a, b, out=out)
    from .. import arithmetics

    return arithmetics.mul(a, b)


def matrix_norm(a: DNDarray, ord=None) -> DNDarray:
    """Frobenius norm of a matrix (numpy-parity helper over the reference's
    single ``norm``, basics.py:788-811)."""
    sanitize_in(a)
    res = jnp.linalg.norm(a.larray.astype(jnp.float32) if types.heat_type_is_exact(a.dtype) else a.larray, ord=ord)
    return DNDarray(res, (), types.canonical_heat_type(res.dtype), None, a.device, a.comm, True)


def _psum_scalar(s, axes):
    """Allreduce a scalar partial over every sharded mesh axis.

    Pass-through collective helper: ``axes`` is bound at the call site
    from the comm's ``axis_names`` for exactly the mesh axes the
    enclosing shard_map shards over, so the call site carries the
    axis-name proof (the spec itself comes from ``comm.spec`` and is not
    statically visible to the linter)."""
    import jax

    return jax.lax.psum(s, axes)


def norm(a: DNDarray) -> DNDarray:
    """Frobenius/2-norm of the whole array
    (reference basics.py:788-811: sqrt of distributed dot).

    Returns a 0-d DNDarray.  Sharded inputs (any 1-D split or grid splits
    tuple) reduce via an exact psum of per-shard partial sums of squares
    inside ONE jitted program — no host round trip and no device-wide
    gather.  The old implementation coerced the traced value through
    ``float(jnp.sqrt(...))``, the SPMD202 host-sync shape
    (tests/test_spmdlint.py pins the regression fixture); callers that
    want a python scalar apply ``float()`` to the returned 0-d array,
    which is then an explicit, caller-chosen sync point."""
    sanitize_in(a)
    comm = a.comm
    dtype = a.dtype if types.heat_type_is_inexact(a.dtype) else types.float32
    jt = dtype.jax_type()
    splits = a.splits
    sharded = comm.size > 1 and a.ndim > 0 and any(g is not None for g in splits)
    if not sharded:
        arr = a.larray
        key = ("linalg.norm", comm, a.ndim, str(arr.dtype), str(jt))

        def make():
            def _f(x):
                x = x.astype(jt) if x.dtype != jt else x
                return jnp.sqrt(jnp.sum(x * x))

            return _f

        res = jitted(key, make)(arr)
    else:
        # pads of every sharded dim are forced to zero so the local
        # sum-of-squares is exact over real elements only
        arr = a._zeroed_buffer()
        spec = comm.spec(a.ndim, splits)
        axes = tuple(
            comm.axis_names[g] for g in splits if g is not None
        )
        key = (
            "linalg.norm", comm, splits,
            tuple(int(s) for s in arr.shape), str(arr.dtype), str(jt),
        )

        def make():
            def kern(x):
                x = x.astype(jt) if x.dtype != jt else x
                return jnp.sqrt(_psum_scalar(jnp.sum(x * x), axes))

            return shard_map(
                kern, mesh=comm.mesh, in_specs=(spec,),
                out_specs=PartitionSpec(), check_vma=False,
            )

        res = jitted(key, make)(arr)
    return DNDarray(res, (), dtype, None, a.device, comm, True)


def vector_norm(a: DNDarray, ord=2) -> DNDarray:
    """Vector p-norm (numpy-parity helper)."""
    sanitize_in(a)
    arr = a.larray
    if types.heat_type_is_exact(a.dtype):
        arr = arr.astype(jnp.float32)
    res = jnp.linalg.norm(arr.reshape(-1), ord=ord)
    return DNDarray(res, (), types.canonical_heat_type(res.dtype), None, a.device, a.comm, True)


def outer(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, split: Optional[int] = None) -> DNDarray:
    """Outer product of two vectors (reference basics.py:812-1050 — a ring
    exchange of the smaller operand; here one sharded jnp.outer, with the
    requested result split applied)."""
    sanitize_in(a)
    sanitize_in(b)
    promoted = types.promote_types(a.dtype, b.dtype)
    garr = jnp.outer(a.larray.astype(promoted.jax_type()), b.larray.astype(promoted.jax_type()))
    if split is None:
        split = 0 if (a.split is not None or b.split is not None) else None
    split = sanitize_axis(garr.shape, split)
    garr = a.comm.apply_sharding(garr, split)
    result = DNDarray(garr, tuple(garr.shape), promoted, split, a.device, a.comm, True)
    if out is not None:
        out.larray = result.larray
        return out
    return result


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of vector a onto vector b (reference basics.py:1051-1077)."""
    sanitize_in(a)
    sanitize_in(b)
    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"projection requires 1-D vectors, got {a.ndim}-d and {b.ndim}-d")
    from .. import arithmetics

    scale = dot(a, b).item() / dot(b, b).item()
    return arithmetics.mul(b, scale)


def transpose(a: DNDarray, axes: Optional[List[int]] = None) -> DNDarray:
    """Permute axes (reference basics.py:1078-1146: local permute + split
    remap)."""
    sanitize_in(a)
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    else:
        axes = tuple(int(ax) % a.ndim for ax in axes)
        if len(axes) != a.ndim or len(set(axes)) != a.ndim:
            raise ValueError("axes do not match array")
    garr = jnp.transpose(a.larray, axes)
    split = axes.index(a.split) if a.split is not None else None
    garr = a.comm.apply_sharding(garr, split)
    return DNDarray(garr, tuple(garr.shape), a.dtype, split, a.device, a.comm, a.balanced)


def __tri_op(m: DNDarray, k: int, op) -> DNDarray:
    """Shared tril/triu core (reference basics.py:1147-1221 — per-rank
    diagonal offsets; here one global masked op)."""
    sanitize_in(m)
    if m.ndim < 2:
        # numpy semantics: a 1-D input becomes a 2-D matrix replicating the vector
        garr = op(jnp.vstack([m.larray] * m.shape[0]), k=k)
        split = m.split
        garr = m.comm.apply_sharding(garr, split)
        return DNDarray(garr, tuple(garr.shape), m.dtype, split, m.device, m.comm, True)
    garr = op(m.larray, k=k)
    garr = m.comm.apply_sharding(garr, m.split)
    return DNDarray(garr, tuple(garr.shape), m.dtype, m.split, m.device, m.comm, m.balanced)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower-triangular part (reference basics.py:1222-1246)."""
    return __tri_op(m, k, jnp.tril)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper-triangular part (reference basics.py:1247-1269)."""
    return __tri_op(m, k, jnp.triu)


# split semantics for heat_tpu.analysis.splitflow (see core/_split_semantics.py)
from .._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {
        "matmul": ("matmul", "dot"),
        "transpose": ("transpose",),
        "elementwise": ("tril", "triu"),
    },
)
