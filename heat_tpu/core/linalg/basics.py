"""Distributed linear algebra basics.

Reference: heat/core/linalg/basics.py:16-1269.  The centerpiece there is a
780-line hand-written block-distributed SUMMA ``matmul`` covering all four
split combinations with Isend/Irecv block exchanges (:285-787).  On TPU the
same computation is ``jnp.matmul`` on sharded global arrays: GSPMD's SPMD
partitioner emits the SUMMA-equivalent collective schedule (all-gather or
reduce-scatter per block) tuned for the MXU and ICI topology — beating a
hand-rolled schedule is exactly what the compiler is for.  What this module
keeps from the reference is the *semantics*: dtype promotion, the
vector/matrix edge cases, and the result-split rules for every split
combination (basics.py:168-283).
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import numpy as np

import jax.numpy as jnp

from .. import factories, types
from ..communication import sanitize_comm
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from ..stride_tricks import sanitize_axis

__all__ = [
    "dot",
    "get_matmul_precision",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "set_matmul_precision",
    "transpose",
    "tril",
    "triu",
    "vector_norm",
]

# On TPU the MXU's default matmul precision is bfloat16-accumulate, which is
# far below the reference's float32 torch numerics (observed: ||QR - A||
# ~0.3 instead of ~1e-5 on a 1024×16 factorization).  This framework is a
# numerics-parity analytics stack first, so linalg defaults to 'highest'
# (fp32 accumulation via multiple MXU passes); benchmarks that want raw MXU
# throughput can switch to 'default' (bf16) or 'float32' (3-pass).
_MATMUL_PRECISION = "highest"


def set_matmul_precision(precision: str) -> None:
    """Set the MXU precision for all linalg matmuls:
    'default' (bf16 inputs), 'float32', or 'highest'."""
    global _MATMUL_PRECISION
    if precision not in ("default", "float32", "highest"):
        raise ValueError(f"invalid precision {precision!r}")
    _MATMUL_PRECISION = precision


def get_matmul_precision() -> str:
    """The current MXU matmul precision for linalg ops."""
    return _MATMUL_PRECISION


def _precision():
    return None if _MATMUL_PRECISION == "default" else _MATMUL_PRECISION


def _result_split_matmul(a: DNDarray, b: DNDarray, out_ndim: int) -> Optional[int]:
    """Result-split rule for matmul, mirroring reference basics.py:168-283:
    split=0 @ anything → row-split result; anything @ split=1 → col-split;
    a.split=1 @ b.split=0 contracts the split axis → split=None (the
    all-reduce case)."""
    if out_ndim == 0:
        return None
    if a.split == 0 and a.ndim > 1:
        return 0
    if b.split is not None and b.ndim > 1 and b.split == b.ndim - 1:
        return out_ndim - 1
    if a.split is not None or b.split is not None:
        # contraction over the split axis (or vector operands): replicate,
        # XLA will have inserted the psum
        return None
    return None


def matmul(a: DNDarray, b: DNDarray, allow_resplit: bool = False) -> DNDarray:
    """Matrix product of two DNDarrays (reference basics.py:71-787).

    All four split combinations are supported; the compiler plans the block
    exchanges that basics.py:420-745 performs manually.  Vector operands
    follow numpy semantics (reference fast paths :168-283).
    """
    sanitize_in(a)
    sanitize_in(b)
    if a.ndim == 0 or b.ndim == 0:
        raise ValueError("matmul does not accept 0-d operands (use mul)")
    # numpy contraction rule: last axis of a against b's second-to-last
    # (or only) axis — mismatches are the reference's ValueError contract
    # (basics.py:83-96), not a backend TypeError
    k_a = a.shape[-1]
    k_b = b.shape[-2] if b.ndim >= 2 else b.shape[0]
    if k_a != k_b:
        raise ValueError(
            f"matmul shape mismatch: {a.shape} @ {b.shape} "
            f"(contracting {k_a} vs {k_b})"
        )
    # batched operands: leading dims must broadcast, same ValueError contract
    if a.ndim > 2 or b.ndim > 2:
        batch_a = a.shape[:-2] if a.ndim > 2 else ()
        batch_b = b.shape[:-2] if b.ndim > 2 else ()
        for da, db in zip(reversed(batch_a), reversed(batch_b)):
            if da != db and da != 1 and db != 1:
                raise ValueError(
                    f"matmul batch dimensions do not broadcast: "
                    f"{a.shape} @ {b.shape} ({da} vs {db})"
                )
    promoted = types.promote_types(a.dtype, b.dtype)
    aa = a.larray.astype(promoted.jax_type())
    ba = b.larray.astype(promoted.jax_type())
    garr = jnp.matmul(aa, ba, precision=_precision())
    split = _result_split_matmul(a, b, garr.ndim)
    comm = a.comm
    garr = comm.apply_sharding(garr, split)
    return DNDarray(
        garr, tuple(garr.shape), promoted, split, a.device, comm, True
    )


def dot(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None):
    """Dot product (reference basics.py:16-70: 1-D = local dot + Allreduce;
    2-D delegates to matmul; scalars multiply)."""
    if isinstance(a, DNDarray) and isinstance(b, DNDarray):
        if a.ndim == 0 or b.ndim == 0:
            from .. import arithmetics

            return arithmetics.mul(a, b)
        if a.ndim == 1 and b.ndim == 1:
            res = jnp.dot(a.larray, b.larray, precision=_precision())
            result = DNDarray(
                res, (), types.promote_types(a.dtype, b.dtype), None, a.device, a.comm, True
            )
            if out is not None:
                out.larray = result.larray
                return out
            return result
        ret = matmul(a, b)
        if out is not None:
            out.larray = ret.larray
            return out
        return ret
    from .. import arithmetics

    return arithmetics.mul(a, b)


def matrix_norm(a: DNDarray, ord=None) -> DNDarray:
    """Frobenius norm of a matrix (numpy-parity helper over the reference's
    single ``norm``, basics.py:788-811)."""
    sanitize_in(a)
    res = jnp.linalg.norm(a.larray.astype(jnp.float32) if types.heat_type_is_exact(a.dtype) else a.larray, ord=ord)
    return DNDarray(res, (), types.canonical_heat_type(res.dtype), None, a.device, a.comm, True)


def norm(a: DNDarray) -> float:
    """Frobenius/2-norm of the whole array
    (reference basics.py:788-811: sqrt of distributed dot)."""
    sanitize_in(a)
    arr = a.larray
    if types.heat_type_is_exact(a.dtype):
        arr = arr.astype(jnp.float32)
    return float(jnp.sqrt(jnp.sum(arr * arr)))


def vector_norm(a: DNDarray, ord=2) -> DNDarray:
    """Vector p-norm (numpy-parity helper)."""
    sanitize_in(a)
    arr = a.larray
    if types.heat_type_is_exact(a.dtype):
        arr = arr.astype(jnp.float32)
    res = jnp.linalg.norm(arr.reshape(-1), ord=ord)
    return DNDarray(res, (), types.canonical_heat_type(res.dtype), None, a.device, a.comm, True)


def outer(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, split: Optional[int] = None) -> DNDarray:
    """Outer product of two vectors (reference basics.py:812-1050 — a ring
    exchange of the smaller operand; here one sharded jnp.outer, with the
    requested result split applied)."""
    sanitize_in(a)
    sanitize_in(b)
    promoted = types.promote_types(a.dtype, b.dtype)
    garr = jnp.outer(a.larray.astype(promoted.jax_type()), b.larray.astype(promoted.jax_type()))
    if split is None:
        split = 0 if (a.split is not None or b.split is not None) else None
    split = sanitize_axis(garr.shape, split)
    garr = a.comm.apply_sharding(garr, split)
    result = DNDarray(garr, tuple(garr.shape), promoted, split, a.device, a.comm, True)
    if out is not None:
        out.larray = result.larray
        return out
    return result


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of vector a onto vector b (reference basics.py:1051-1077)."""
    sanitize_in(a)
    sanitize_in(b)
    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"projection requires 1-D vectors, got {a.ndim}-d and {b.ndim}-d")
    from .. import arithmetics

    scale = dot(a, b).item() / dot(b, b).item()
    return arithmetics.mul(b, scale)


def transpose(a: DNDarray, axes: Optional[List[int]] = None) -> DNDarray:
    """Permute axes (reference basics.py:1078-1146: local permute + split
    remap)."""
    sanitize_in(a)
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    else:
        axes = tuple(int(ax) % a.ndim for ax in axes)
        if len(axes) != a.ndim or len(set(axes)) != a.ndim:
            raise ValueError("axes do not match array")
    garr = jnp.transpose(a.larray, axes)
    split = axes.index(a.split) if a.split is not None else None
    garr = a.comm.apply_sharding(garr, split)
    return DNDarray(garr, tuple(garr.shape), a.dtype, split, a.device, a.comm, a.balanced)


def __tri_op(m: DNDarray, k: int, op) -> DNDarray:
    """Shared tril/triu core (reference basics.py:1147-1221 — per-rank
    diagonal offsets; here one global masked op)."""
    sanitize_in(m)
    if m.ndim < 2:
        # numpy semantics: a 1-D input becomes a 2-D matrix replicating the vector
        garr = op(jnp.vstack([m.larray] * m.shape[0]), k=k)
        split = m.split
        garr = m.comm.apply_sharding(garr, split)
        return DNDarray(garr, tuple(garr.shape), m.dtype, split, m.device, m.comm, True)
    garr = op(m.larray, k=k)
    garr = m.comm.apply_sharding(garr, m.split)
    return DNDarray(garr, tuple(garr.shape), m.dtype, m.split, m.device, m.comm, m.balanced)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower-triangular part (reference basics.py:1222-1246)."""
    return __tri_op(m, k, jnp.tril)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper-triangular part (reference basics.py:1247-1269)."""
    return __tri_op(m, k, jnp.triu)
