"""Parallel IO: HDF5, NetCDF, CSV.

Reference: heat/core/io.py:19-923 — per-rank chunk reads (each MPI process
reads only its ``chunk()`` slice of the dataset, io.py:104-111), slab
writes with Isend/Recv ordering, and a byte-range CSV partitioner.

TPU-native formulation: reads go through
:func:`jax.make_array_from_callback`, which asks for exactly the index
ranges each device's shard covers — so a sharded load reads each slab once,
straight into its device buffer (the direct analog of the reference's
per-rank slab read, generalized to any mesh).  Writes gather per-shard
slices on the host and write slabs sequentially (single-controller: no
inter-process ordering protocol needed).  netCDF4 is optional exactly like
the reference's try-import gating (io.py:26-41).
"""

from __future__ import annotations

import os
import shutil
from typing import List, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..telemetry import _core as _tel
from . import devices as _devices
from . import factories, types
from .communication import comm_for_device, sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

try:
    import h5py
except ImportError:
    h5py = None

try:
    import netCDF4 as nc
except ImportError:
    nc = None

try:
    # fallback NetCDF backend: scipy's pure-python NetCDF-3 reader/writer
    # (classic format only — no groups, no 64-bit integer variables)
    from scipy.io import netcdf_file as _scipy_nc
except ImportError:
    _scipy_nc = None

__all__ = [
    "load",
    "load_csv",
    "load_hdf5",
    "load_netcdf",
    "save",
    "save_csv",
    "save_hdf5",
    "save_netcdf",
    "supports_hdf5",
    "supports_netcdf",
]

__HDF5_EXTENSIONS = frozenset([".h5", ".hdf5"])
#: public alias — estimator checkpointing shares the routing table
HDF5_EXTENSIONS = __HDF5_EXTENSIONS
__NETCDF_EXTENSIONS = frozenset([".nc", ".nc4", ".netcdf"])
__CSV_EXTENSIONS = frozenset([".csv", ".txt"])


def supports_hdf5() -> bool:
    """True when h5py is importable (reference io.py:26-33)."""
    return h5py is not None


def supports_netcdf() -> bool:
    """True when a NetCDF backend is importable: netCDF4 (full NetCDF-4),
    else scipy's classic NetCDF-3 reader/writer (reference io.py:34-41
    gates on netCDF4 alone)."""
    return nc is not None or _scipy_nc is not None


def _faults():
    """Lazy import of the fault-injection seams (the resilience package
    imports this module, so the dependency must stay one-way at import
    time)."""
    from ..resilience import faults

    return faults


def _retry_open(fn, site: str):
    """Run a file-open probe under the bounded, seeded io retry policy:
    a transient ``OSError`` (flaky NFS, a file mid-failover, an injected
    ``io_error`` fault) heals on retry with every attempt incident-logged
    and counted; only an exhausted policy propagates.  Lazy import for
    the same one-way-dependency reason as :func:`_faults`."""
    from ..resilience import retry as _r

    return _r.call(fn, policy=_r.IO_POLICY, site=site)


def _named_member(path: str, mapping, name: str, kind: str):
    """Look up ``name`` in a file's member ``mapping`` (h5py File, NetCDF
    ``.variables``), naming BOTH the file and the missing member on
    failure — a bare ``KeyError: 'x'`` from a 40-file ingest loop says
    nothing about which file lacked which dataset."""
    try:
        return mapping[name]
    except KeyError:
        try:
            available = ", ".join(sorted(map(str, mapping.keys()))) or "<none>"
        except Exception:  # noqa: BLE001 — the lookup error is the story
            available = "<unknown>"
        raise ValueError(
            f"{path}: no {kind} named {name!r} (available: {available})"
        ) from None


# --------------------------------------------------------------------- #
# atomic writes                                                          #
# --------------------------------------------------------------------- #
# Every writer path stages into a same-directory temp file and commits
# with os.replace only after a successful close: a crash (or injected
# preemption) anywhere mid-save leaves the previous file byte-identical.
# Append modes first copy the existing file into the temp so the commit
# is still all-or-nothing.
def _atomic_begin(path: str, mode: str = "w") -> str:
    """Start an atomic write of ``path``: returns the temp path to write
    to.  Same directory as the target so :func:`os.replace` stays a
    rename, never a copy."""
    tmp = f"{path}.tmp-{os.getpid()}"
    if mode not in ("w", "w-") and os.path.exists(path):
        shutil.copyfile(path, tmp)
    return tmp


def _atomic_commit(tmp: str, path: str) -> None:
    """Publish a finished atomic write (rename over the target)."""
    os.replace(tmp, path)


def _atomic_abort(tmp: Optional[str]) -> None:
    """Discard a failed atomic write; the target was never touched."""
    if tmp is not None:
        try:
            os.remove(tmp)
        except OSError:
            pass


def _sharded_from_reader(shape, np_dtype, split, device, comm, read_slices):
    """Build a sharded global jax.Array by reading only each shard's slab
    (the parallel-read core; reference io.py:104-111 per-rank slab read)."""
    device = _devices.sanitize_device(device)
    comm = comm_for_device(device.platform) if comm is None else sanitize_comm(comm)
    split = sanitize_axis(shape, split)
    hdtype = types.canonical_heat_type(np_dtype)
    # io:read brackets the slab reads, io:h2d the device commit, and both
    # credit account_bytes("io", ...) — the streaming/bench bandwidth
    # headlines reconcile against this ledger like every comm headline
    total_bytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(hdtype._np_type).itemsize
    if split is not None and shape[split] % comm.size == 0 and comm.size > 1:
        sharding = comm.sharding(len(shape), split)

        def _cb(index):
            if _tel.enabled:
                with _tel.span("io:read", sharded=True):
                    block = np.asarray(read_slices(index))
                _tel.account_bytes("io", "read", block.nbytes, block.nbytes)
                return block
            return read_slices(index)

        if _tel.enabled:
            with _tel.span("io:h2d", bytes=total_bytes):
                garr = jax.make_array_from_callback(tuple(shape), sharding, _cb)
            _tel.account_bytes("io", "h2d", total_bytes, total_bytes)
        else:
            garr = jax.make_array_from_callback(tuple(shape), sharding, _cb)
    else:
        if _tel.enabled:
            with _tel.span("io:read", sharded=False):
                block = np.asarray(read_slices(tuple(slice(None) for _ in shape)))
            _tel.account_bytes("io", "read", block.nbytes, block.nbytes)
            with _tel.span("io:h2d", bytes=total_bytes):
                garr = jnp.asarray(block)
            _tel.account_bytes("io", "h2d", total_bytes, total_bytes)
        else:
            garr = jnp.asarray(read_slices(tuple(slice(None) for _ in shape)))
        garr = comm.apply_sharding(garr, split)
    return DNDarray(garr, tuple(shape), hdtype, split, device, comm, True)


def load_hdf5(
    path: str,
    dataset: str,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load an HDF5 dataset with per-shard slab reads
    (reference io.py:43-128)."""
    if not supports_hdf5():
        raise RuntimeError("h5py is required for HDF5 support")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    if not isinstance(dataset, str):
        raise TypeError(f"dataset must be str, not {type(dataset)}")
    dtype = types.canonical_heat_type(dtype)

    def _probe():
        _faults().io_open(path)
        with h5py.File(path, "r") as handle:
            return tuple(_named_member(path, handle, dataset, "dataset").shape)

    gshape = _retry_open(_probe, "io.load_hdf5")

    np_dtype = np.dtype(dtype._np_type)

    def read_slices(index):
        with h5py.File(path, "r") as f:
            return np.asarray(f[dataset][index], dtype=np_dtype)

    return _sharded_from_reader(gshape, dtype, split, device, comm, read_slices)


def _emit_slabs(data: DNDarray, write):
    """Feed host slabs of ``data`` to ``write(slices, np_block)`` one shard
    at a time (bounding host memory by one shard).  ``write`` may be None —
    the process then still participates in slab fetches: on multihost
    (``jax.process_count() > 1``) fetching a slab is a cross-process
    allgather that EVERY process must join, while only process 0 writes
    the file (the analog of the reference's rank-ordered MPI-IO writes,
    reference io.py:129-234).

    A ``write`` failure is RETURNED, not raised: the fetch sequence is a
    collective program that must run to completion in lockstep on every
    process — aborting it mid-way on one process would hang the others in
    their next allgather.  Callers re-raise after the barrier."""
    multihost = jax.process_count() > 1
    err = None
    if data.split is None:
        # replicated arrays are addressable everywhere — direct fetch
        if write is not None:
            try:
                _faults().preempt_point("save-slab")
                write(tuple(slice(0, s) for s in data.shape), np.asarray(data.larray))
            except Exception as e:  # noqa: BLE001 — deferred to the caller
                err = e
        return err
    for r in range(data.comm.size):
        _, _, slices = data.comm.chunk(data.shape, data.split, rank=r)
        if any(s.stop <= s.start for s in slices):
            continue
        block = data.larray[slices]
        if multihost:
            from jax.experimental import multihost_utils

            block = multihost_utils.process_allgather(block, tiled=True)
        if write is not None and err is None:
            try:
                # the simulated-preemption seam sits INSIDE the deferred-
                # error block: a writer killed between two slab writes
                # still reaches the barrier, the staged temp file is
                # discarded, and the previous file survives untouched
                _faults().preempt_point("save-slab")
                write(slices, np.asarray(block))
            except Exception as e:  # noqa: BLE001 — deferred to the caller
                err = e
    return err


def _finish_save(err: Optional[BaseException]) -> None:
    """End a cross-process save: allgather a per-process
    failure flag so a writer-side error raises on EVERY process.  Without
    the flag only process 0 learns of a failed save — the other processes
    return success and march into the next collective (e.g. a load of the
    file that was never written) while the writer has died, hanging the
    cluster.  The flag allgather is itself a full rendezvous (no process
    passes it until every process has finished its slab collectives and
    the writer has closed the file), so it IS the end-of-save barrier —
    a separate sync_global_devices on top would just double the
    cross-process latency.  Every process must reach this call exactly
    once per save."""
    any_err = err is not None
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([1 if err is not None else 0], np.int32)
        )
        any_err = bool(np.asarray(flags).sum())
    if err is not None:
        raise err
    if any_err:
        raise RuntimeError(
            "save failed on the writer process (process 0); see its traceback"
        )


def _writer_save(data: DNDarray, prepare, path: str, mode: str = "w") -> None:
    """Writer-side half of a cross-process save.  ``prepare(target)``
    returns ``(write, close)`` for the staged temp file ``target``; any
    error — open, dataset creation, or a slab write — is DEFERRED until
    the slab fetches and the barrier have run, because those are
    collectives the other processes are already executing (an early raise
    on the writer would hang the cluster in the next allgather).  The
    temp is committed over ``path`` only after a clean close; on any
    error it is discarded and the previous file survives."""
    err, write, close, tmp = None, None, None, None
    try:
        _faults().io_open(path)
        tmp = _atomic_begin(path, mode)
        write, close = prepare(tmp)
    except Exception as e:  # noqa: BLE001 — deferred past the collectives
        err = e
    werr = _emit_slabs(data, write)
    err = err or werr
    if close is not None:
        try:
            close()
        except Exception as e:  # noqa: BLE001
            err = err or e
    if tmp is not None:
        if err is None:
            try:
                _atomic_commit(tmp, path)
            except Exception as e:  # noqa: BLE001
                err = e
        else:
            _atomic_abort(tmp)
    _finish_save(err)


def _save_hdf5_many(path: str, datasets, attrs=None, mode: str = "w") -> None:
    """Write several datasets plus file attributes in ONE file open and
    ONE cross-process failure barrier.  ``datasets`` is an ordered
    sequence of (key, DNDarray); every process must pass the same
    sequence (the slab fetches are collectives executed in order).  This
    is the multi-dataset generalization of :func:`_writer_save` — the
    deferred-error choreography lives here once, shared by
    :func:`save_hdf5` (via that helper) and estimator checkpointing."""
    datasets = list(datasets)
    if jax.process_index() == 0:
        err, f, tmp = None, None, None
        try:
            _faults().io_open(path)
            tmp = _atomic_begin(path, mode)
            f = h5py.File(tmp, mode)
        except Exception as e:  # noqa: BLE001 — deferred past the collectives
            err = e
        for key, arr in datasets:
            write = None
            if f is not None and err is None:
                try:
                    dset = f.create_dataset(
                        key, arr.shape, dtype=np.dtype(arr.dtype._np_type)
                    )
                    write = dset.__setitem__
                except Exception as e:  # noqa: BLE001
                    err = e
            werr = _emit_slabs(arr, write)
            err = err or werr
        if f is not None:
            if err is None and attrs:
                try:
                    for k, v in attrs.items():
                        f.attrs[k] = v
                except Exception as e:  # noqa: BLE001
                    err = e
            try:
                f.close()
            except Exception as e:  # noqa: BLE001
                err = err or e
        if tmp is not None:
            if err is None:
                try:
                    _atomic_commit(tmp, path)
                except Exception as e:  # noqa: BLE001
                    err = e
            else:
                _atomic_abort(tmp)
        _finish_save(err)
    else:
        for _, arr in datasets:
            _emit_slabs(arr, None)
        _finish_save(None)


def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
    """Save to HDF5 (reference io.py:129-234 — rank-0 metadata + ordered
    per-rank slab writes; here process 0 writes each shard slab)."""
    if not supports_hdf5():
        raise RuntimeError("h5py is required for HDF5 support")
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")

    def prepare(target):
        f = h5py.File(target, mode)
        try:
            dset = f.create_dataset(
                dataset, data.shape, dtype=np.dtype(data.dtype._np_type), **kwargs
            )
        except Exception:
            f.close()
            raise
        return dset.__setitem__, f.close

    if jax.process_index() == 0:
        _writer_save(data, prepare, path, mode)
    else:
        _emit_slabs(data, None)
        _finish_save(None)


def load_netcdf(
    path: str,
    variable: str,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a NetCDF variable (reference io.py:235-311)."""
    if not supports_netcdf():
        raise RuntimeError("a NetCDF backend (netCDF4 or scipy) is required")
    dtype = types.canonical_heat_type(dtype)
    np_dtype = np.dtype(dtype._np_type)

    if nc is not None:
        def _probe():
            _faults().io_open(path)
            with nc.Dataset(path, "r") as handle:
                return tuple(
                    _named_member(path, handle.variables, variable, "variable").shape
                )

        def read_slices(index):
            with nc.Dataset(path, "r") as f:
                return np.asarray(f.variables[variable][index], dtype=np_dtype)

    else:
        def _probe():
            _faults().io_open(path)
            with _scipy_nc(path, "r", mmap=False) as handle:
                return tuple(
                    _named_member(path, handle.variables, variable, "variable").shape
                )

        def read_slices(index):
            with _scipy_nc(path, "r", mmap=False) as f:
                return np.array(f.variables[variable][index], dtype=np_dtype)

    gshape = _retry_open(_probe, "io.load_netcdf")

    return _sharded_from_reader(gshape, dtype, split, device, comm, read_slices)


def save_netcdf(
    data: DNDarray, path: str, variable: str, mode: str = "w", dimension_names=None, **kwargs
) -> None:
    """Save to NetCDF (reference io.py:312-621 — rank-ordered slab writes;
    here the controller writes each shard slab, bounding host memory by one
    shard exactly like :func:`save_hdf5`)."""
    if not supports_netcdf():
        raise RuntimeError("a NetCDF backend (netCDF4 or scipy) is required")
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")
    if dimension_names is None:
        dimension_names = [f"dim_{i}" for i in range(data.ndim)]
    np_dtype = np.dtype(data.dtype._np_type)

    if nc is None:
        if kwargs:
            raise TypeError(
                f"NetCDF-3 (scipy backend) does not support createVariable "
                f"options {sorted(kwargs)}; install netCDF4 for them"
            )
        # classic NetCDF-3 typecodes: int8/int16/int32, float32/float64
        classic_ok = (np_dtype.kind == "i" and np_dtype.itemsize <= 4) or (
            np_dtype.kind == "f" and np_dtype.itemsize in (4, 8)
        )
        if not classic_ok:
            raise TypeError(
                f"NetCDF-3 (scipy backend) cannot store dtype {np_dtype}; "
                "cast to a signed int <= 32 bits or float32/float64, or "
                "install netCDF4"
            )

    def prepare(target):
        f = (
            nc.Dataset(target, mode)
            if nc is not None
            else _scipy_nc(target, "w" if mode == "w" else "a")
        )
        try:
            for name, length in zip(dimension_names, data.shape):
                if name not in f.dimensions:
                    f.createDimension(name, length)
            if nc is not None:
                var = f.createVariable(variable, np_dtype, tuple(dimension_names), **kwargs)
            else:
                var = f.createVariable(variable, np_dtype, tuple(dimension_names))
        except Exception:
            f.close()
            raise
        return var.__setitem__, f.close

    if jax.process_index() == 0:
        _writer_save(data, prepare, path, mode)
    else:
        _emit_slabs(data, None)
        _finish_save(None)


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a CSV file (reference io.py:665-885 — byte-range partitioning by
    rank with line-boundary fixup).  The partitioning runs in the native
    threaded scanner (:mod:`heat_tpu.native`, C++ over mmap'd byte ranges
    with the same line-ownership rule); the numpy parser is the fallback
    for exotic encodings, ragged rows, or toolchain-less hosts."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    if not isinstance(sep, str):
        raise TypeError(f"separator must be str, not {type(sep)}")
    if not isinstance(header_lines, int):
        raise TypeError(f"header_lines must be int, not {type(header_lines)}")
    dtype = types.canonical_heat_type(dtype)
    data = None
    if encoding in ("utf-8", "ascii", "utf8"):
        from .. import native

        data = native.fastcsv_parse(path, header_lines=header_lines, sep=sep)
        if data is not None:
            data = data.astype(np.dtype(dtype._np_type), copy=False)
    if data is None:
        data = np.genfromtxt(
            path,
            delimiter=sep,
            skip_header=header_lines,
            dtype=np.dtype(dtype._np_type),
            encoding=encoding,
        )
    return factories.array(data, dtype=dtype, split=split, device=device, comm=comm)


def save_csv(
    data: DNDarray,
    path: str,
    header_lines: Optional[str] = None,
    sep: str = ",",
    decimals: int = -1,
    encoding: str = "utf-8",
    **kwargs,
) -> None:
    """Save a 1-D/2-D DNDarray to CSV (reference io.py adds this in later
    versions; provided for round-trip completeness)."""
    if data.ndim > 2:
        raise ValueError("save_csv supports 1-D and 2-D arrays")
    # the allgather is a collective every process joins BEFORE the
    # writer-only (fallible) file write, so a write error cannot desync it
    if jax.process_count() > 1 and data.split is not None:
        from jax.experimental import multihost_utils

        arr = np.asarray(multihost_utils.process_allgather(data.larray, tiled=True))
    else:
        arr = np.asarray(data.larray)
    fmt = f"%.{decimals}f" if decimals >= 0 else "%s"
    err = None
    if jax.process_index() == 0:
        tmp = None
        try:
            _faults().io_open(path)
            tmp = _atomic_begin(path)
            _faults().preempt_point("save-slab")
            np.savetxt(
                tmp, arr, delimiter=sep, header=header_lines or "", fmt=fmt, encoding=encoding
            )
            _atomic_commit(tmp, path)
        except Exception as e:  # noqa: BLE001 — deferred past the collectives
            err = e
            _atomic_abort(tmp)
    _finish_save(err)


def load(path: str, *args, **kwargs) -> DNDarray:
    """Extension-dispatched load (reference io.py:622-664)."""
    if _tel.enabled:
        _tel.inc("io.loads")
        with _tel.span("io:load", path=str(path)):
            return _load_impl(path, *args, **kwargs)
    return _load_impl(path, *args, **kwargs)


def _load_impl(path: str, *args, **kwargs) -> DNDarray:
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    ext = os.path.splitext(path)[-1].strip().lower()
    if ext in __HDF5_EXTENSIONS:
        if not supports_hdf5():
            raise RuntimeError(f"hdf5 is required for file extension {ext}")
        return load_hdf5(path, *args, **kwargs)
    if ext in __NETCDF_EXTENSIONS:
        if not supports_netcdf():
            raise RuntimeError(f"netcdf is required for file extension {ext}")
        return load_netcdf(path, *args, **kwargs)
    if ext in __CSV_EXTENSIONS:
        return load_csv(path, *args, **kwargs)
    raise ValueError(f"Unsupported file extension {ext}")


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Extension-dispatched save (reference io.py:886-923).  Estimators
    dispatch to :func:`heat_tpu.save_estimator` (extension): one call
    saves data or a fitted model alike."""
    if _tel.enabled:
        _tel.inc("io.saves")
        with _tel.span("io:save", path=str(path)):
            return _save_impl(data, path, *args, **kwargs)
    return _save_impl(data, path, *args, **kwargs)


def _save_impl(data: DNDarray, path: str, *args, **kwargs) -> None:
    from .base import BaseEstimator

    if isinstance(data, BaseEstimator):
        if args or kwargs:
            raise TypeError(
                "estimator checkpoints take no dataset/option arguments: "
                "use ht.save(estimator, path)"
            )
        from .checkpoint import save_estimator

        # path/extension validation lives in save_estimator so est.save()
        # and ht.save() enforce the same contract
        return save_estimator(data, path)
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    ext = os.path.splitext(path)[-1].strip().lower()
    if ext in __HDF5_EXTENSIONS:
        if not supports_hdf5():
            raise RuntimeError(f"hdf5 is required for file extension {ext}")
        return save_hdf5(data, path, *args, **kwargs)
    if ext in __NETCDF_EXTENSIONS:
        if not supports_netcdf():
            raise RuntimeError(f"netcdf is required for file extension {ext}")
        return save_netcdf(data, path, *args, **kwargs)
    if ext in __CSV_EXTENSIONS:
        return save_csv(data, path, *args, **kwargs)
    raise ValueError(f"Unsupported file extension {ext}")
