"""Split-semantics declarations: the op layer's transfer-function registry.

Every public op declares how it transforms sharding metadata — its
*transfer function* over split specs — right next to its definition, via
:func:`declare_split_semantics` tables at the bottom of each op module or
the :func:`split_semantics` decorator on methods.  The declarations feed
two consumers:

1. **runtime** — :data:`REGISTRY` is importable and introspectable, and
   the splitflow oracle suite (tests/test_splitflow_oracle.py) executes
   each declared op and asserts the declared rule matches the observed
   ``DNDarray.split`` metadata, so a declaration can never silently
   drift from the code it sits next to;
2. **static analysis** — :mod:`heat_tpu.analysis.splitflow` re-reads the
   SAME declarations from this tree's source (AST-level, jax-free) and
   uses them as the transfer functions of its abstract interpreter.

This module is deliberately dependency-free (no jax, no numpy): the op
modules import it at definition time and the analyzer may import it on a
bare Python install.

Kinds (the transfer-function families; ``params`` refine them):

=================  =====================================================
``elementwise``    unary map — splits, shape, raggedness preserved
``binary``         broadcast binary — the ``__binary_op`` anchor rules:
                   result carries the non-None split (re-anchored from
                   the right under broadcasting); operands split along
                   DIFFERENT axes force an implicit resplit of the
                   second operand onto the first's layout
``reduction``      axis reduction — reducing across the split axis
                   yields split=None, otherwise the split index shifts
                   down past removed axes (``__reduce_op``)
``cumulative``     split and shape preserved (``__cum_op``)
``matmul``         ``_result_split_matmul``: split-0 @ anything → row
                   split, anything @ col-split → col split, contraction
                   over the split axis → replicated
``transpose``      split follows its axis through the permutation
``reshape``        split preserved when the axis index survives, else
                   re-split at 0 (``manipulations.reshape``)
``concat``         first non-None operand split, along any axis
``stack``          split shifts past the new axis
``expand_dims``    split shifts past the inserted axis
``squeeze``        split drops with its axis or shifts down
``flatten``        any split → 0, replicated stays replicated
``resplit``        explicit layout change to the ``axis`` argument —
                   the one declared COMM op (costed by the
                   redistribution plan model).  ``axis`` may also be a
                   splits TUPLE (the N-D mesh spelling): facts stay
                   tuple-valued and the 1-D int form promotes to its
                   one-hot tuple automatically
``factory``        new array, split from the ``split=`` keyword, or a
                   splits tuple from ``splits=`` — tuple entries name
                   MESH axes and validate against the target comm's
                   mesh rank (the default comm's mesh is 1-D)
``factory_like``   new array mirroring the input's layout
``entry_fit``      estimator entry point returning the estimator itself
``entry_split0``   library entry point whose result is row-split iff
                   the data argument is row-split (predict family and
                   its shared input gate ``sanitize_predict_in``,
                   cdist, the U factor of svd).  The gate is also the
                   transfer fact serve pipelines are priced on:
                   replicated and row-split inputs pass through with
                   ZERO layout traffic (no resplit event to cost);
                   only a feature-split input re-splits onto rows
``entry_svd``      ``SVD(U, S, V)`` namedtuple: U per ``entry_split0``,
                   S and V replicated; grid ``(0, 1)``/``(1, 0)``
                   operands pin U to ``(0, 1)`` with S and V replicated
                   (wide grid inputs transpose-and-swap, so V lands on
                   the grid instead of U)
``entry_qr``       ``QR(Q, R)`` namedtuple: grid ``(0, 1)`` operands
                   pin Q to ``(0, 1)`` and R to ``(None, 1)``; 1-D Q
                   follows the operand split, R is sharded only down
                   the split-1 chain (``split == 1`` keeps R on 1,
                   everything else replicates R)
=================  =====================================================
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = [
    "KINDS",
    "KIND_LAYOUT_FREEDOM",
    "REGISTRY",
    "Semantics",
    "declare_split_semantics",
    "declare_split_semantics_table",
    "layout_alternatives",
    "split_semantics",
]

KINDS = frozenset(
    {
        "elementwise",
        "binary",
        "reduction",
        "cumulative",
        "matmul",
        "transpose",
        "reshape",
        "concat",
        "stack",
        "expand_dims",
        "squeeze",
        "flatten",
        "resplit",
        "factory",
        "factory_like",
        "entry_fit",
        "entry_split0",
        "entry_svd",
        "entry_qr",
    }
)


@dataclass(frozen=True)
class Semantics:
    """One op's declared transfer function.

    ``name`` is the public leaf name call sites resolve to (module
    function or method — the DNDarray methods delegate to the module
    functions of the same name, so one declaration covers both
    spellings).  ``module`` records where the declaration lives, for
    drift diagnostics.  ``params`` is a frozen extras tuple.
    """

    name: str
    kind: str
    module: str
    params: Tuple[Tuple[str, object], ...] = ()

    def param(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default


#: leaf name -> declared semantics.  One namespace on purpose: the public
#: API is flat (``ht.*`` mirrors the reference) and method names shadow
#: their module functions.
REGISTRY: Dict[str, Semantics] = {}


#: Layout freedom of each kind's RESULT — the op layer's declaration of
#: which placements the auto-layout solver (``ht.autoshard``) may choose,
#: sitting next to the transfer facts exactly like the kinds table above:
#:
#: ``free``
#:     the result may legally rest at ANY split (``resplit``: the target
#:     layout is the op's entire purpose, so the solver owns it);
#: ``declared``
#:     the layout comes from an explicit keyword (``split=``/``splits=``)
#:     and any value is legal — the solver may re-place it, but v1 keeps
#:     user-declared factory layouts (they are inputs to the search, not
#:     seams in it);
#: ``follows``
#:     the result layout is a function of the operand layouts (the
#:     transfer function above); the solver influences it only through
#:     the operands;
#: ``fixed``
#:     the entry point pins its own contract (e.g. ``entry_svd``'s S and
#:     V are replicated by construction) — never a search dimension.
KIND_LAYOUT_FREEDOM: Dict[str, str] = {
    "elementwise": "follows",
    "binary": "follows",
    "reduction": "follows",
    "cumulative": "follows",
    "matmul": "follows",
    "transpose": "follows",
    "reshape": "follows",
    "concat": "follows",
    "stack": "follows",
    "expand_dims": "follows",
    "squeeze": "follows",
    "flatten": "follows",
    "resplit": "free",
    "factory": "declared",
    "factory_like": "follows",
    "entry_fit": "fixed",
    "entry_split0": "fixed",
    "entry_svd": "fixed",
    "entry_qr": "fixed",
}


def layout_alternatives(kind: str, ndim: int, mesh_ndim: int = 1) -> Tuple:
    """Legal layout placements for the result of an op of ``kind`` on an
    ``ndim``-dimensional value over a ``mesh_ndim``-axis mesh.

    The enumeration the auto-layout solver searches: on a 1-D mesh the
    compat int spelling (``None`` first, then each array axis); on an N-D
    mesh the splits-tuple spelling (every assignment of mesh axes to
    array dims, each mesh axis at most once, fully-replicated first).
    Deterministic canonical order — the solver's tie-break depends on it.
    Kinds whose layout is not a search dimension return ``()``.
    """
    if KIND_LAYOUT_FREEDOM.get(kind, "fixed") not in ("free", "declared"):
        return ()
    ndim = int(ndim)
    if mesh_ndim <= 1:
        return (None,) + tuple(range(ndim))
    out = []

    def _extend(prefix, used):
        if len(prefix) == ndim:
            out.append(tuple(prefix))
            return
        for g in (None,) + tuple(range(mesh_ndim)):
            if g is not None and g in used:
                continue
            _extend(prefix + [g], used | ({g} if g is not None else set()))

    _extend([], set())
    # replicated-first canonical order: rank None below every mesh axis
    out.sort(key=lambda t: tuple(-1 if g is None else g for g in t))
    return tuple(out)


def declare_split_semantics(name: str, kind: str, *, module: str = "", **params) -> Semantics:
    """Declare the transfer function of op ``name`` (table form — call at
    the bottom of the module defining the op)."""
    if kind not in KINDS:
        raise ValueError(f"unknown split-semantics kind {kind!r} for {name!r}")
    prev = REGISTRY.get(name)
    sem = Semantics(name, kind, module, tuple(sorted(params.items())))
    if prev is not None and (prev.kind, prev.params) != (sem.kind, sem.params):
        raise ValueError(
            f"conflicting split semantics for {name!r}: "
            f"{prev.kind} from {prev.module} vs {kind} from {module}"
        )
    REGISTRY[name] = sem
    return sem


def declare_split_semantics_table(module: str, table: Dict[str, Tuple[str, ...]]) -> None:
    """Bulk table form: ``{kind: (op names...)}``.  Keep the argument a
    LITERAL dict — the static analyzer re-reads these declarations from
    source, and only literal tables parse without execution."""
    for kind, names in table.items():
        for name in names:
            declare_split_semantics(name, kind, module=module)


def split_semantics(kind: str, name: Optional[str] = None, **params):
    """Decorator form of :func:`declare_split_semantics` — registers the
    function under its own name and returns it UNCHANGED (no wrapper, so
    tracing, pickling, and ``cache_stable`` identity are unaffected)."""

    def deco(fn):
        declare_split_semantics(
            name or fn.__name__, kind, module=getattr(fn, "__module__", ""), **params
        )
        fn.__split_semantics__ = REGISTRY[name or fn.__name__]
        return fn

    return deco
