"""Input/output validation helpers for ops.

Reference: heat/core/sanitation.py:24-180 (``sanitize_in``, ``sanitize_out``,
``sanitize_in_tensor``, ``sanitize_sequence``, ``scalar_to_1d``).  The
``out=`` semantics here rebind the output DNDarray's backing jax.Array
(arrays are immutable in XLA), preserving the reference's user-visible
contract: after ``ht.add(a, b, out=c)``, ``c`` holds the result with its own
split/device checked for compatibility.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Union

import numpy as np
import jax.numpy as jnp

from ._split_semantics import split_semantics as _split_semantics

__all__ = [
    "merge_keepdims",
    "sanitize_in",
    "sanitize_infinity",
    "sanitize_in_tensor",
    "sanitize_lshape",
    "sanitize_out",
    "sanitize_predict_in",
    "sanitize_sequence",
    "scalar_to_1d",
]


def merge_keepdims(keepdims, keepdim) -> bool:
    """Reconcile the numpy (``keepdims``) and reference/torch (``keepdim``)
    spellings with one rule everywhere: an explicit ``keepdims`` wins,
    otherwise ``keepdim`` applies, otherwise False."""
    if keepdims is None:
        keepdims = keepdim
    return bool(keepdims) if keepdims is not None else False


def sanitize_in(x: Any) -> None:
    """Verify ``x`` is a DNDarray (reference sanitation.py:24-40)."""
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")


@_split_semantics("entry_split0")
def sanitize_predict_in(x: Any, n_features: Any = None, op: str = "predict"):
    """The ONE input gate of every predict path (KNN, GaussianNB, the
    k-clusterers, Lasso — and through them the serve engine).

    Validates that ``x`` is a 2-D DNDarray (optionally with exactly
    ``n_features`` columns) and normalizes its layout for the fused
    predict programs.  The layout rule is the point: replicated
    (``split=None``) and row-split (``split=0``) inputs pass through
    UNTOUCHED — no resplit, no device transfer, no extra dispatch — so a
    replicated serving micro-batch replays the cached program directly.
    Only the one layout the predict programs cannot shard over, a
    feature-split input (``split=1``), is re-split onto rows.

    Returns the (possibly re-split) input, unlike :func:`sanitize_in`
    which only checks — predict paths must use the returned array.
    """
    sanitize_in(x)
    if x.ndim != 2:
        raise ValueError(f"{op} expects a 2-D (n_samples, n_features) input, got {x.ndim}-D")
    if n_features is not None and int(x.shape[1]) != int(n_features):
        raise ValueError(
            f"{op} expects {int(n_features)} features, got {int(x.shape[1])} "
            f"(input shape {tuple(x.shape)})"
        )
    if x.split in (None, 0):
        return x
    return x.resplit(0)


def sanitize_in_tensor(x: Any) -> "jnp.ndarray":
    """Coerce to a local jax array (reference sanitation.py helper)."""
    from .dndarray import DNDarray

    if isinstance(x, DNDarray):
        return x.larray
    return jnp.asarray(x)


def sanitize_infinity(x) -> Union[int, float]:
    """Largest representable value for ``x``'s dtype (used by norms/clip)."""
    from . import types

    dt = x.dtype if hasattr(x, "dtype") else types.heat_type_of(x)
    dt = types.canonical_heat_type(dt)
    if types.heat_type_is_exact(dt):
        return types.iinfo(dt).max
    return float("inf")


def sanitize_lshape(array, tensor) -> None:
    """Verify ``tensor`` is a legal replacement for ``array``'s local shard
    (reference sanitation.py:69-108): non-split axes must match the global
    shape; the split axis may differ (shards vary in size)."""
    tshape = tuple(tensor.shape)
    if tshape == tuple(array.lshape):
        return
    gshape = tuple(array.gshape)
    split = array.split
    if split is None:
        non_zero = [i for i in range(len(tshape)) if tshape[i] != 0]
        if all(tshape[i] == gshape[i] for i in non_zero):
            return
        raise ValueError(
            f"Shape of local tensor is inconsistent with global DNDarray: "
            f"tensor.shape is {tshape}, should be {gshape}"
        )
    if tshape[:split] + tshape[split + 1 :] == gshape[:split] + gshape[split + 1 :]:
        return
    raise ValueError(
        f"Shape of local tensor along non-split axes is inconsistent with global "
        f"DNDarray: tensor.shape is {tshape}, DNDarray is {gshape}"
    )


def sanitize_out(out: Any, output_shape, output_split, output_device, output_comm=None) -> None:
    """Validate an ``out=`` target against the result geometry
    (reference sanitation.py:110-170)."""
    from .dndarray import DNDarray

    if not isinstance(out, DNDarray):
        raise TypeError(f"expected out to be None or a DNDarray, but was {type(out)}")
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"Expecting output buffer of shape {tuple(output_shape)}, got {out.shape}")
    if output_device is not None and out.device != output_device:
        raise ValueError(f"Expecting output buffer on device {output_device}, got {out.device}")


def sanitize_sequence(seq: Union[Sequence, "np.ndarray"]) -> List:
    """Normalize a sequence-like to a python list (reference sanitation.py)."""
    from .dndarray import DNDarray

    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    if isinstance(seq, np.ndarray):
        return seq.tolist()
    if isinstance(seq, DNDarray):
        return np.asarray(seq.larray).tolist()
    raise TypeError(f"seq must be a list, tuple, numpy.ndarray or DNDarray, got {type(seq)}")


def scalar_to_1d(x):
    """Turn a scalar DNDarray into a 1-element 1-D DNDarray
    (reference sanitation.py:171-180)."""
    from .dndarray import DNDarray

    if x.ndim == 1:
        return x
    return DNDarray(
        x.larray.reshape(1), (1,), x.dtype, split=None, device=x.device, comm=x.comm, balanced=True
    )
