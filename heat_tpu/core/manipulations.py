"""Shape and layout manipulations.

Reference: heat/core/manipulations.py:141-3386.  The reference hand-rolls
redistribution for nearly every function here (``concatenate`` moves
boundary chunks, ``reshape`` routes through a global-index Alltoallv
(:1756-1776), ``sort`` is a full distributed sample-sort with pivot
exchange (:2040-2160), ``unique`` merges per-rank uniques via Allgatherv
(:2685+), ``topk`` needs a custom MPI reduction op (:3346-3386)).

On global arrays each of these is its jnp equivalent — XLA plans the data
movement — plus split bookkeeping.  The result-split rules follow the
reference; performance-sensitive resharding stays explicit via
``resplit``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import factories, types
from ._tracing import NO_OVERRIDE, consume_layout_override, layout_plan_active
from .dndarray import DNDarray
from .sanitation import sanitize_in
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "balance",
    "column_stack",
    "concatenate",
    "diag",
    "diagonal",
    "dsplit",
    "expand_dims",
    "flatten",
    "flip",
    "fliplr",
    "flipud",
    "hsplit",
    "hstack",
    "pad",
    "redistribute",
    "repeat",
    "reshape",
    "resplit",
    "rot90",
    "row_stack",
    "shape",
    "sort",
    "split",
    "squeeze",
    "stack",
    "topk",
    "unique",
    "vsplit",
    "vstack",
]


def _rewrap(x: DNDarray, garr, split, dtype=None) -> DNDarray:
    """Apply layout + wrap a result derived from ``x``."""
    if garr.ndim == 0:
        split = None
    garr = x.comm.apply_sharding(garr, split)
    return DNDarray(
        garr,
        tuple(garr.shape),
        dtype or types.canonical_heat_type(garr.dtype),
        split,
        x.device,
        x.comm,
        True,
    )


def balance(x: DNDarray, copy: bool = False) -> DNDarray:
    """Return a load-balanced copy (reference dndarray.balance_,
    dndarray.py:900 — a no-op under the canonical GSPMD layout)."""
    sanitize_in(x)
    from .memory import copy as _copy

    return _copy(x) if copy else x


def redistribute(x: DNDarray, lshape_map=None, target_map=None) -> DNDarray:
    """Out-of-place redistribute (reference dndarray.redistribute_,
    dndarray.py:2560).  Canonical layout is maintained; see
    ``DNDarray.redistribute_``."""
    sanitize_in(x)
    x.redistribute_(lshape_map, target_map)
    return x


def concatenate(arrays, axis: int = 0) -> DNDarray:
    """Join arrays along an existing axis
    (reference manipulations.py:141-470 — there, boundary chunks are
    re-chunked and exchanged; here a global jnp.concatenate)."""
    if not isinstance(arrays, (list, tuple)) or len(arrays) < 1:
        raise TypeError("arrays must be a non-empty sequence of DNDarrays")
    for a in arrays:
        sanitize_in(a)
    a0 = arrays[0]
    axis = sanitize_axis(a0.shape, axis)
    out_type = a0.dtype
    for a in arrays[1:]:
        if a.ndim != a0.ndim:
            raise ValueError("DNDarrays must have the same number of dimensions")
        if any(i != axis and s != t for i, (s, t) in enumerate(zip(a0.shape, a.shape))):
            raise ValueError(
                f"Arrays cannot be concatenated, shapes must be the same in "
                f"every axis except the selected axis: {a0.shape}, {a.shape}"
            )
        out_type = types.promote_types(out_type, a.dtype)
    garr = jnp.concatenate(
        [a.larray.astype(out_type.jax_type()) for a in arrays], axis=axis
    )
    split = a0.split if a0.split is not None else next(
        (a.split for a in arrays if a.split is not None), None
    )
    return _rewrap(a0, garr, split, out_type)


def diag(a: DNDarray, offset: int = 0) -> DNDarray:
    """Extract/construct a diagonal (reference manipulations.py:471-548)."""
    sanitize_in(a)
    if a.ndim == 1:
        garr = jnp.diag(a.larray, k=offset)
        return _rewrap(a, garr, a.split, a.dtype)
    return diagonal(a, offset=offset)


def diagonal(a: DNDarray, offset: int = 0, dim1: int = 0, dim2: int = 1) -> DNDarray:
    """Extract a diagonal from an n-D array (reference manipulations.py:549-706)."""
    sanitize_in(a)
    dim1 = sanitize_axis(a.shape, dim1)
    dim2 = sanitize_axis(a.shape, dim2)
    if dim1 == dim2:
        raise ValueError("dim1 and dim2 need to be different dimensions")
    garr = jnp.diagonal(a.larray, offset=offset, axis1=dim1, axis2=dim2)
    split = None if a.split in (dim1, dim2) else a.split
    if split is not None:
        split = split - sum(1 for d in (dim1, dim2) if d < split)
        split = min(max(split, 0), garr.ndim - 1)
    return _rewrap(a, garr, split, a.dtype)


def expand_dims(a: DNDarray, axis: int) -> DNDarray:
    """Insert a size-1 axis (reference manipulations.py:707-765)."""
    sanitize_in(a)
    if not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be an int, got {type(axis)}")
    if axis < -(a.ndim + 1) or axis > a.ndim:
        raise ValueError(f"axis {axis} out of bounds for expanding {a.ndim}-d array")
    axis = axis % (a.ndim + 1)
    garr = jnp.expand_dims(a.larray, axis)
    split = a.split if a.split is None or a.split < axis else a.split + 1
    return _rewrap(a, garr, split, a.dtype)


def flatten(a: DNDarray) -> DNDarray:
    """1-D view of the global array (reference manipulations.py:766-800 —
    there an Alltoallv-backed reshape; here XLA's)."""
    sanitize_in(a)
    garr = a.larray.reshape(-1)
    split = 0 if a.split is not None else None
    return _rewrap(a, garr, split, a.dtype)


def flip(a: DNDarray, axis=None) -> DNDarray:
    """Reverse element order along axes (reference manipulations.py:801-866 —
    there a rank-reversal Send/Recv; here jnp.flip + reshard)."""
    sanitize_in(a)
    axis = sanitize_axis(a.shape, axis)
    garr = jnp.flip(a.larray, axis=axis)
    return _rewrap(a, garr, a.split, a.dtype)


def fliplr(a: DNDarray) -> DNDarray:
    """(reference manipulations.py:867-893)"""
    if a.ndim < 2:
        raise IndexError("fliplr requires at least 2 dimensions")
    return flip(a, 1)


def flipud(a: DNDarray) -> DNDarray:
    """(reference manipulations.py:894-920)"""
    return flip(a, 0)


#: numpy-style pad modes jnp.pad lowers natively, plus the reference's
#: torch.nn.functional.pad spellings (manipulations.py:1049-1394 passes
#: mode straight through to F.pad: replicate == edge, circular == wrap)
_PAD_MODE_ALIASES = {"replicate": "edge", "circular": "wrap"}
_PAD_MODES = frozenset(
    {"constant", "edge", "linear_ramp", "maximum", "mean", "median",
     "minimum", "reflect", "symmetric", "wrap", "empty"}
)


def pad(array: DNDarray, pad_width, mode: str = "constant", constant_values=0) -> DNDarray:
    """Pad an array (reference manipulations.py:1049-1394 — mode is handed
    to torch F.pad there; here to jnp.pad, accepting both numpy and torch
    mode names)."""
    sanitize_in(array)
    if not isinstance(mode, str):
        raise TypeError(f"expected mode to be a string, but was {type(mode)}")
    # normalize pad_width to numpy form
    if isinstance(pad_width, (int, np.integer)):
        np_pad = pad_width
    else:
        np_pad = tuple(
            tuple(p) if isinstance(p, (list, tuple)) else p for p in pad_width
        )
    mode = _PAD_MODE_ALIASES.get(mode, mode)
    if mode not in _PAD_MODES:
        raise NotImplementedError(f"pad mode {mode!r} not implemented")
    kwargs = {"constant_values": constant_values} if mode == "constant" else {}
    garr = jnp.pad(array.larray, np_pad, mode=mode, **kwargs)
    return _rewrap(array, garr, array.split, array.dtype)


def repeat(a, repeats, axis: Optional[int] = None) -> DNDarray:
    """Repeat elements (reference manipulations.py:1395-1650)."""
    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if isinstance(repeats, DNDarray):
        repeats = np.asarray(repeats.larray)
    axis = sanitize_axis(a.shape, axis)
    garr = jnp.repeat(a.larray, repeats, axis=axis)
    split = a.split if axis is not None else (0 if a.split is not None else None)
    if garr.ndim == 1:
        split = 0 if a.split is not None else None
    return _rewrap(a, garr, split, a.dtype)


def reshape(a: DNDarray, shape, new_split: Optional[int] = None, **kwargs) -> DNDarray:
    """Reshape to a new global shape (reference manipulations.py:1651-1775 —
    there, a global-index chunk mask + Alltoallv exchange; here XLA's
    reshape partitioning)."""
    sanitize_in(a)
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    shape = tuple(int(s) for s in shape)
    # resolve a single -1
    if any(s == -1 for s in shape):
        known = int(np.prod([s for s in shape if s != -1]))
        missing = a.size // max(known, 1)
        shape = tuple(missing if s == -1 else s for s in shape)
    if int(np.prod(shape)) != a.size:
        raise ValueError(f"cannot reshape array of size {a.size} into shape {shape}")
    garr = a.larray.reshape(shape)
    if new_split is None:
        new_split = a.split if (a.split is not None and a.split < len(shape)) else (
            0 if a.split is not None and len(shape) > 0 else None
        )
    else:
        new_split = sanitize_axis(shape, new_split)
    return _rewrap(a, garr, new_split, a.dtype)


def resplit(arr: DNDarray, axis: Optional[int] = None) -> DNDarray:
    """Out-of-place reshard along ``axis``
    (reference manipulations.py:2969-3060: split→None = Allgatherv path
    :3023; here a single XLA reshard).

    ``axis`` also accepts a splits tuple — the native spelling on a grid
    comm (routed through the 2-D planner via ``commit_split``), the exact
    one-hot compat spelling on a 1-D mesh."""
    sanitize_in(arr)
    comm = arr.comm
    grid = getattr(comm, "mesh_ndim", 1) > 1
    if layout_plan_active() and not grid and not isinstance(axis, (tuple, list)):
        # ht.autoshard plan application: this resplit's signature (shape,
        # dtype, src, requested dst) may carry a solver override for the
        # placement to actually commit.  Resplits the plan never priced
        # (e.g. __binary_op's implicit reshard) get NO_OVERRIDE and run
        # as written; an override equal to arr.split elides via the
        # same-layout early-out below.
        requested = sanitize_axis(arr.shape, axis) if axis is not None else None
        override = consume_layout_override(
            arr.shape, getattr(arr.dtype, "__name__", str(arr.dtype)),
            arr.split, requested,
        )
        if override is not NO_OVERRIDE:
            axis = override
    if isinstance(axis, (tuple, list)) or grid:
        if not isinstance(axis, (tuple, list)):
            axis = sanitize_axis(arr.shape, axis)
        splits = comm.normalize_splits(arr.ndim, axis)
        if not grid:
            axis = comm.split_view(splits)  # exact on 1-D: legacy path below
        else:
            if splits == arr.splits:
                return DNDarray(
                    arr._buffer, arr.shape, arr.dtype, splits,
                    arr.device, comm, arr.balanced,
                )
            garr = comm.commit_split(arr.larray, splits)
            return DNDarray(
                garr, arr.shape, arr.dtype, splits, arr.device, comm, True
            )
    axis = sanitize_axis(arr.shape, axis)
    if axis == arr.split:
        # same layout: share the at-rest buffer (re-wrapping the true view
        # would unpad + re-pad a ragged split for nothing)
        return DNDarray(
            arr._buffer, arr.shape, arr.dtype, axis, arr.device, arr.comm, arr.balanced
        )
    garr = arr.comm.commit_split(arr.larray, axis)
    return DNDarray(garr, arr.shape, arr.dtype, axis, arr.device, arr.comm, True)


def rot90(m: DNDarray, k: int = 1, axes=(0, 1)) -> DNDarray:
    """Rotate in the plane of two axes (reference manipulations.py:1776-1892)."""
    sanitize_in(m)
    axes = tuple(sanitize_axis(m.shape, ax) for ax in axes)
    if len(set(axes)) != 2:
        raise ValueError("axes must be different")
    garr = jnp.rot90(m.larray, k=k, axes=axes)
    split = m.split
    if split in axes and k % 2 == 1:
        split = axes[0] if split == axes[1] else axes[1]
    return _rewrap(m, garr, split, m.dtype)


def sort(a: DNDarray, axis: int = -1, descending: bool = False, out=None):
    """Sort along an axis, returning (values, original indices)
    (reference manipulations.py:1893-2160 — a distributed sample-sort with
    pivot Gatherv/Bcast and Alltoallv of values+indices).

    When the sorted axis IS the split axis on a multi-device mesh, the
    explicit distributed sort runs
    (:func:`heat_tpu.parallel.sort_axis0`: the ppermute ring rank sort
    for 1-D/narrow arrays, a resplit + batched local argsort for n-D) —
    the re-design of the reference's sample-sort, which likewise
    dispatches exactly when ``axis == split``
    (reference manipulations.py:1893-2160).  Everywhere else the sorted
    axis is local to each shard (or the mesh is trivial) and ``jnp``
    argsort suffices."""
    sanitize_in(a)
    axis = sanitize_axis(a.shape, axis)
    if axis is None:
        axis = a.ndim - 1
    arr = a.larray
    from ..parallel import sort as _parallel_sort  # lazy: parallel imports core

    if a.split == axis and _parallel_sort.supports_axis(arr.dtype, a.shape, axis, a.comm):
        moved = jnp.moveaxis(arr, axis, 0) if axis != 0 else arr
        values, indices = _parallel_sort.sort_axis0(
            moved, a.shape[axis], comm=a.comm, descending=descending
        )
        if axis != 0:
            values = jnp.moveaxis(values, 0, axis)
            indices = jnp.moveaxis(indices, 0, axis)
        vals = _rewrap(a, values.astype(arr.dtype), a.split, a.dtype)
        idx = _rewrap(a, indices, a.split, types.int32)
    else:
        # the shared order-inverting key (ties still by ascending index;
        # see parallel.sort._descending_key for the overflow rationale)
        key = _parallel_sort._descending_key(arr) if descending else arr
        indices = jnp.argsort(key, axis=axis, stable=True)
        values = jnp.take_along_axis(arr, indices, axis=axis)
        vals = _rewrap(a, values, a.split, a.dtype)
        idx = _rewrap(a, indices.astype(jnp.int32), a.split, types.int32)
    if out is not None:
        out.larray = vals.larray
        return out, idx
    return vals, idx


def shape(a: DNDarray) -> tuple:
    """Global shape of ``a`` (reference manipulations.py:1874-1891)."""
    from .dndarray import DNDarray

    if not isinstance(a, DNDarray):
        raise TypeError(f"Expected a to be a DNDarray but was {type(a)}")
    return a.gshape


def split(ary: DNDarray, indices_or_sections, axis: int = 0) -> List[DNDarray]:
    """Split into sub-arrays (reference manipulations.py:2162-2318)."""
    sanitize_in(ary)
    axis = sanitize_axis(ary.shape, axis)
    if isinstance(indices_or_sections, (int, np.integer)):
        if ary.shape[axis] % int(indices_or_sections) != 0:
            raise ValueError("array split does not result in an equal division")
    if isinstance(indices_or_sections, DNDarray):
        indices_or_sections = np.asarray(indices_or_sections.larray)
    parts = jnp.split(ary.larray, indices_or_sections, axis=axis)
    return [_rewrap(ary, p, ary.split, ary.dtype) for p in parts]


def dsplit(ary: DNDarray, indices_or_sections) -> List[DNDarray]:
    """(reference manipulations.py:2319-2347)"""
    return split(ary, indices_or_sections, axis=2)


def hsplit(ary: DNDarray, indices_or_sections) -> List[DNDarray]:
    """(reference manipulations.py:2348-2380)"""
    if ary.ndim < 2:
        return split(ary, indices_or_sections, axis=0)
    return split(ary, indices_or_sections, axis=1)


def vsplit(ary: DNDarray, indices_or_sections) -> List[DNDarray]:
    """(reference manipulations.py:2381-2413)"""
    return split(ary, indices_or_sections, axis=0)


def squeeze(x: DNDarray, axis=None) -> DNDarray:
    """Remove size-1 axes (reference manipulations.py:2414-2519)."""
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is not None:
        axes = (axis,) if isinstance(axis, int) else axis
        for ax in axes:
            if x.shape[ax] != 1:
                raise ValueError(f"cannot select an axis to squeeze out which has size not equal to one, axis {ax}")
    else:
        axes = tuple(i for i, s in enumerate(x.shape) if s == 1)
    garr = jnp.squeeze(x.larray, axis=axes)
    split = x.split
    if split is not None:
        if split in axes:
            split = None
        else:
            split = split - sum(1 for ax in axes if ax < split)
    return _rewrap(x, garr, split, x.dtype)


def stack(arrays: Sequence[DNDarray], axis: int = 0, out=None) -> DNDarray:
    """Join along a new axis (reference manipulations.py:2520-2605)."""
    if len(arrays) < 2:
        raise ValueError("stack expects a sequence of at least 2 DNDarrays")
    for a in arrays:
        sanitize_in(a)
    a0 = arrays[0]
    for a in arrays[1:]:
        if a.shape != a0.shape:
            raise ValueError(f"all input arrays must have the same shape, {a.shape} != {a0.shape}")
    ndim_out = a0.ndim + 1
    if not -ndim_out <= axis < ndim_out:
        raise ValueError(
            f"axis {axis} is out of bounds for the {ndim_out}-dimensional result"
        )
    axis = axis % ndim_out
    out_type = a0.dtype
    for a in arrays[1:]:
        out_type = types.promote_types(out_type, a.dtype)
    garr = jnp.stack([a.larray.astype(out_type.jax_type()) for a in arrays], axis=axis)
    split = a0.split
    if split is not None and axis <= split:
        split += 1
    result = _rewrap(a0, garr, split, out_type)
    if out is not None:
        out.larray = result.larray
        return out
    return result


def column_stack(arrays) -> DNDarray:
    """Stack 1-D/2-D arrays as columns (reference manipulations.py:2606-2645)."""
    reshaped = []
    for a in arrays:
        sanitize_in(a)
        reshaped.append(a.expand_dims(1) if a.ndim == 1 else a)
    return concatenate(reshaped, axis=1)


def row_stack(arrays) -> DNDarray:
    """Stack arrays as rows (reference manipulations.py:2646-2684)."""
    reshaped = []
    for a in arrays:
        sanitize_in(a)
        reshaped.append(a.expand_dims(0) if a.ndim == 1 else a)
    return concatenate(reshaped, axis=0)


def hstack(tup) -> DNDarray:
    """(reference manipulations.py: hstack)"""
    arrays = list(tup)
    if all(a.ndim == 1 for a in arrays):
        return concatenate(arrays, axis=0)
    return concatenate(arrays, axis=1)


def vstack(tup) -> DNDarray:
    """(reference manipulations.py: vstack)"""
    return row_stack(list(tup))


def _unique_mask_1d(flat, comm=None):
    """Sorted order, first-occurrence mask, and group ids of a flat array —
    the static-shape half of unique (everything except the data-dependent
    output length).  NaNs collapse to one representative (numpy's
    ``equal_nan=True`` default).  On a multi-device mesh with an orderable
    dtype the sort itself is the distributed ring rank sort
    (:func:`heat_tpu.parallel.ring_rank_sort`)."""
    from ..parallel import sort as _parallel_sort  # lazy: parallel imports core

    if comm is not None and _parallel_sort.supports(flat.dtype, flat.shape[0], comm):
        s, order = _parallel_sort.ring_rank_sort(flat, flat.shape[0], comm=comm)
    else:
        order = jnp.argsort(flat, stable=True)
        s = flat[order]
    prev = jnp.roll(s, 1)
    neq = s != prev
    if jnp.issubdtype(s.dtype, jnp.floating):
        neq = neq & ~(jnp.isnan(s) & jnp.isnan(prev))
    mask = neq.at[0].set(True) if s.shape[0] else neq
    if comm is not None and comm.size > 1 and s.shape[0]:
        # cumsum along a sharded axis is a pathological GSPMD scan — use
        # the explicit two-level prefix sum (local cumsum + shard offsets)
        from ..parallel import prefix_sum

        groups = prefix_sum(mask.astype(jnp.int32), comm=comm) - 1
    else:
        groups = jnp.cumsum(mask) - 1
    return order, s, mask, groups


def _compact(values, mask, groups, n_unique: int):
    """Scatter the masked first occurrences into a dense (n_unique, ...)
    buffer.  ``n_unique`` is the ONE host-synced scalar unique() needs: the
    output length is data-dependent, so the allocation size must reach the
    host — but only the count crosses, never the data."""
    sink = jnp.where(mask, groups, n_unique)
    out_shape = (n_unique,) + values.shape[1:]
    return jnp.zeros(out_shape, values.dtype).at[sink].set(values, mode="drop")


def unique(a: DNDarray, sorted: bool = False, return_inverse: bool = False, axis=None):
    """Unique elements (reference manipulations.py:2685-2968 — per-rank
    torch.unique + Allgatherv + merge on the gathered union).

    TPU formulation: one device-resident global sort (XLA partitions sorts
    over sharded inputs) → first-occurrence mask → count → scatter-compact.
    Only the unique COUNT syncs to the host (the output allocation is
    data-dependent; JAX needs a static shape) — the data itself never
    leaves the device, so scale is bounded by HBM, not host memory.
    ``axis=k`` uniquifies rows via a lexicographic sort of the remaining
    dims.  Results come back in sorted order (the reference's
    ``sorted=False`` leaves order unspecified) with ONE exception: wide
    slices (> 64 flattened columns) sort by a 64-bit row hash —
    deterministic but not lexicographic — unless ``sorted=True``, which
    additionally orders the compacted uniques lexicographically
    (:func:`_unique_axis_hashed`)."""
    sanitize_in(a)
    if axis is not None:
        axis = sanitize_axis(a.shape, axis)
        return _unique_axis(a, axis, return_inverse, sorted)

    flat = jnp.ravel(a.larray)
    order, s, mask, groups = _unique_mask_1d(flat, comm=a.comm if a.split is not None else None)
    n_unique = int(jnp.sum(mask))  # the single scalar host sync
    uniques = _compact(s, mask, groups, n_unique)
    split = 0 if a.split is not None else None
    result = _rewrap(a, uniques, split, a.dtype)
    if return_inverse:
        inv = jnp.zeros(flat.shape, jnp.int64).at[order].set(groups)
        inv_wrapped = factories.array(
            inv.reshape(a.larray.shape), dtype=types.int64, device=a.device, comm=a.comm
        )
        return result, inv_wrapped
    return result


#: above this flattened-slice width, axis-unique switches from the exact
#: lexicographic sort to a hashed sort key: jnp.lexsort builds one
#: variadic-sort operand per column, so compile time and memory scale with
#: m — a (n, 10k) matrix would emit a 10k-operand sort
_UNIQUE_AXIS_MAX_LEXSORT_KEYS = 64


def _unique_axis(a: DNDarray, axis: int, return_inverse: bool, sort_result: bool = False):
    """Unique slices along ``axis``: a device sort of the flattened
    remaining dims, then the same mask/count/compact pipeline as the flat
    case.  Narrow slices (≤ _UNIQUE_AXIS_MAX_LEXSORT_KEYS columns) sort
    exactly — lexicographic, so the result is row-sorted; wider slices
    sort by a 64-bit row hash with exact collision detection
    (:func:`_unique_axis_hashed`) — still fully device-resident, with the
    result in deterministic hash order (the reference's own
    ``sorted=False`` contract, reference manipulations.py:2685-2968)."""
    moved = jnp.moveaxis(a.larray, axis, 0)
    n = moved.shape[0]
    rows = moved.reshape(n, -1)
    m = rows.shape[1]
    if m > _UNIQUE_AXIS_MAX_LEXSORT_KEYS:
        return _unique_axis_hashed(a, axis, return_inverse, moved, rows, sort_result)
    # lexsort: last key is primary → feed columns in reverse order
    order = jnp.lexsort(tuple(rows[:, j] for j in range(m - 1, -1, -1))) if m else jnp.arange(n)
    s = rows[order]
    prev = jnp.roll(s, 1, axis=0)
    neq_el = s != prev
    if jnp.issubdtype(s.dtype, jnp.floating):
        neq_el = neq_el & ~(jnp.isnan(s) & jnp.isnan(prev))
    neq = jnp.any(neq_el, axis=1) if m else jnp.zeros((n,), bool)
    mask = neq.at[0].set(True) if n else neq
    groups = jnp.cumsum(mask) - 1
    n_unique = int(jnp.sum(mask))  # the single scalar host sync
    uniq_rows = _compact(s, mask, groups, n_unique)
    garr = jnp.moveaxis(uniq_rows.reshape((n_unique,) + moved.shape[1:]), 0, axis)
    split = 0 if a.split is not None else None
    result = _rewrap(a, garr, split, a.dtype)
    if return_inverse:
        inv = jnp.zeros((n,), jnp.int64).at[order].set(groups)
        inv_wrapped = factories.array(inv, dtype=types.int64, device=a.device, comm=a.comm)
        return result, inv_wrapped
    return result


def _row_words(rows: jax.Array) -> jax.Array:
    """Canonical uint32 word matrix of ``rows``: row equality under the
    unique() rules (±0 collapsed, NaN equal to NaN) ⇔ word equality.
    Floats canonicalize -0.0 and NaN payloads before the bit view; 64-bit
    dtypes contribute two words per element, narrow dtypes widen."""
    dt = rows.dtype
    if dt == jnp.bool_:
        return rows.astype(jnp.uint32)
    if jnp.issubdtype(dt, jnp.floating):
        rows = jnp.where(rows == 0, jnp.zeros((), dt), rows)  # -0.0 → +0.0
        rows = jnp.where(jnp.isnan(rows), jnp.full((), jnp.nan, dt), rows)
    width = jnp.dtype(dt).itemsize * 8
    uint = {8: jnp.uint8, 16: jnp.uint16, 32: jnp.uint32, 64: jnp.uint64}[width]
    bits = rows.view(uint)
    if width == 64:
        n, m = bits.shape
        lo = (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (bits >> jnp.uint64(32)).astype(jnp.uint32)
        return jnp.stack([hi, lo], axis=-1).reshape(n, 2 * m)
    return bits.astype(jnp.uint32)


def _hash_rows(words: jax.Array, seed: int) -> Tuple[jax.Array, jax.Array]:
    """Two independent 32-bit polynomial row hashes of a uint32 word
    matrix (a 64-bit key overall).  Each word first passes through a
    seeded murmur-style mixer — so linear structure in the input cannot
    align with the polynomial — then folds with per-hash odd
    multipliers."""
    w = words.shape[1]
    x = words ^ jnp.uint32((0x9E3779B9 * (seed + 1)) & 0xFFFFFFFF)
    x = (x ^ (x >> 16)) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)

    def powers(mult: int) -> np.ndarray:
        out, acc = [], 1
        for _ in range(w):
            out.append(acc)
            acc = (acc * mult) & 0xFFFFFFFF
        return np.asarray(out[::-1], dtype=np.uint32)

    h1 = jnp.sum(x * jnp.asarray(powers(2654435761)), axis=1, dtype=jnp.uint32)
    h2 = jnp.sum(x * jnp.asarray(powers(0x01000193)), axis=1, dtype=jnp.uint32)
    return h1, h2


def _unique_axis_hashed(
    a: DNDarray, axis: int, return_inverse: bool, moved, rows, sort_result: bool = False
):
    """Device-resident axis-unique for wide slices (replaces the r2 host
    ``np.unique`` fallback, which silently capped scale at host memory):
    compress each row to a 64-bit hash, sort by the hash — the distributed
    ring rank sort when the mesh and x64 policy allow a uint64 key, a
    2-operand lexsort otherwise; never one sort operand per column — then
    run the usual exact-content mask/count/compact on the hash-sorted
    rows.  A hash collision between unequal rows could interleave a
    duplicate row's group, so collisions are DETECTED exactly (adjacent
    hash-equal pairs with unequal content) and the pipeline retries with a
    fresh seed: correctness never rests on the hash.

    The result's row order is the hash order — deterministic and device-
    resident, but not lexicographic (the exact sorted order would need
    the per-column variadic sort this path exists to avoid); pass
    ``sorted=True`` to additionally lexsort the COMPACTED uniques (a host
    pass over n_unique rows, not the input).

    Data movement: the 64-bit key rides the explicit ring sort, and on a
    mesh the payload permutation rides :func:`heat_tpu.parallel.ring_take`
    (blocks rotate; every device answers the queries landing in the
    visiting block) — bounded at O(rows/p) per-device memory, where the
    GSPMD gather it replaces replicated the whole row matrix on every
    device.  The inverse map returns through the dual
    :func:`heat_tpu.parallel.ring_put`."""
    from ..parallel import sort as _parallel_sort  # lazy: parallel imports core

    n = moved.shape[0]
    words = _row_words(rows)
    comm = a.comm if a.split is not None else None
    for seed in range(4):
        h1, h2 = _hash_rows(words, seed)
        if jax.config.jax_enable_x64:
            key = h1.astype(jnp.uint64) << jnp.uint64(32) | h2.astype(jnp.uint64)
            if comm is not None and _parallel_sort.supports(key.dtype, n, comm):
                _, order = _parallel_sort.ring_rank_sort(key, n, comm=comm)
                order = order.astype(jnp.int64)
            else:
                order = jnp.argsort(key, stable=True)
        elif comm is not None and _parallel_sort.supports(jnp.dtype(jnp.uint32), n, comm):
            # x64 disabled (HEAT_TPU_DISABLE_X64): no uint64 key exists,
            # but two successive STABLE ring sorts — minor key first,
            # then major — compose to the same (h1, h2) lexicographic
            # order without ever handing GSPMD a sharded variadic sort;
            # the index compositions ride ring_take for the same
            # bounded-memory reason as the row payload below
            from ..parallel import take as _take

            _, ord2 = _parallel_sort.ring_rank_sort(h2, n, comm=comm)
            h1p = _take.ring_take(h1, ord2, comm=comm)
            _, ord1 = _parallel_sort.ring_rank_sort(h1p, n, comm=comm)
            order = _take.ring_take(ord2, ord1, comm=comm)
        else:
            order = jnp.lexsort((h2, h1))
        if comm is not None and comm.size > 1:
            from ..parallel import take as _take  # noqa: F811 — lazy per branch

            s = _take.ring_take(rows, order.astype(jnp.int32), comm=comm)
            # the hashes are pure functions of the rows: rehashing the
            # permuted rows costs one elementwise pass and saves two more
            # full ring pipelines
            sh1, sh2 = _hash_rows(_row_words(s), seed)
        else:
            s = rows[order]
            sh1, sh2 = h1[order], h2[order]
        same_hash = (sh1 == jnp.roll(sh1, 1)) & (sh2 == jnp.roll(sh2, 1))
        prev = jnp.roll(s, 1, axis=0)
        neq_el = s != prev
        if jnp.issubdtype(s.dtype, jnp.floating):
            neq_el = neq_el & ~(jnp.isnan(s) & jnp.isnan(prev))
        neq = jnp.any(neq_el, axis=1)
        # exact collision check: unequal neighbours under one hash key
        if n and bool(jnp.any(same_hash & neq & (jnp.arange(n) > 0))):
            continue  # astronomically rare: re-seed and re-hash
        mask = neq.at[0].set(True) if n else neq
        break
    else:  # 4 colliding seeds means adversarial input — fail loudly
        raise RuntimeError(
            "unique(axis=...): persistent 64-bit hash collisions; cannot "
            "group rows device-resident"
        )
    if comm is not None and comm.size > 1 and n:
        from ..parallel import prefix_sum

        groups = prefix_sum(mask.astype(jnp.int32), comm=comm) - 1
    else:
        groups = jnp.cumsum(mask) - 1
    n_unique = int(jnp.sum(mask))  # the single scalar host sync
    uniq_rows = _compact(s, mask, groups, n_unique)
    remap = None
    if sort_result and n_unique:
        # honor unique()'s sorted contract: lexsort just the COMPACTED
        # uniques on the host (n_unique rows — the dedup already ran on
        # device; this never touches the full input)
        host = np.asarray(uniq_rows)
        perm = np.lexsort(tuple(host[:, j] for j in range(host.shape[1] - 1, -1, -1)))
        uniq_rows = uniq_rows[jnp.asarray(perm)]
        remap = jnp.asarray(np.argsort(perm))  # old group id -> sorted position
    garr = jnp.moveaxis(uniq_rows.reshape((n_unique,) + moved.shape[1:]), 0, axis)
    split = 0 if a.split is not None else None
    result = _rewrap(a, garr, split, a.dtype)
    if return_inverse:
        sorted_groups = remap[groups] if remap is not None else groups
        if comm is not None and comm.size > 1:
            from ..parallel import take as _take

            inv = _take.ring_put(
                n, order.astype(jnp.int32), sorted_groups.astype(jnp.int32), comm=comm
            ).astype(jnp.int64)
        else:
            inv = jnp.zeros((n,), jnp.int64).at[order].set(sorted_groups)
        inv_wrapped = factories.array(inv, dtype=types.int64, device=a.device, comm=a.comm)
        return result, inv_wrapped
    return result


def topk(a: DNDarray, k: int, dim: int = -1, largest: bool = True, sorted: bool = True, out=None):
    """k largest/smallest elements and their indices
    (reference manipulations.py:3201-3345 + the custom MPI_TOPK reduction op
    :3346-3386; here jax.lax.top_k — a native TPU sort network).

    ``sorted=False`` relaxes the ordering contract; ``lax.top_k`` always
    returns sorted results, which satisfies the relaxed contract too, so
    both values produce sorted output."""
    sanitize_in(a)
    dim = sanitize_axis(a.shape, dim)
    if dim is None:
        dim = a.ndim - 1
    arr = a.larray
    moved = jnp.moveaxis(arr, dim, -1)
    if largest:
        vals, idx = lax.top_k(moved, k)
    else:
        # order-inverting key: -x for floats, ~x for ints/bool (negation
        # wraps INT_MIN and garbles unsigned; ~x inverts exactly) — same
        # key as parallel/sort._descending_key
        from ..parallel.sort import _descending_key

        vals, idx = lax.top_k(_descending_key(moved), k)
        vals = _descending_key(vals)
    vals = jnp.moveaxis(vals, -1, dim)
    idx = jnp.moveaxis(idx, -1, dim)
    values = _rewrap(a, vals, a.split if a.split != dim else None, a.dtype)
    indices = _rewrap(a, idx.astype(jnp.int64), a.split if a.split != dim else None, types.int64)
    if out is not None:
        out[0].larray = values.larray
        out[1].larray = indices.larray
        return out
    return values, indices


# split semantics for heat_tpu.analysis.splitflow (see core/_split_semantics.py)
from ._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {
        "concat": ("concatenate", "hstack", "vstack", "row_stack", "column_stack"),
        "stack": ("stack",),
        "expand_dims": ("expand_dims",),
        "squeeze": ("squeeze",),
        "flatten": ("flatten", "ravel"),
        "reshape": ("reshape",),
        "resplit": ("resplit", "resplit_"),
        "elementwise": ("flip", "fliplr", "flipud"),
    },
)
