"""Cached-jit dispatch for the op engine.

Every user-level op (``ht.add``, ``ht.mean``, ``ht.sqrt`` …) runs a short
chain of jnp primitives.  Dispatching those eagerly costs one host↔device
round trip *per primitive* — on a tunneled/remote TPU that is ~50 ms each,
three orders of magnitude above the kernel time.  The reference never faces
this (torch eager ops run in-process, reference heat/core/_operations.py
drives local torch kernels directly); the TPU-native answer is to compile
each op chain once and replay the cached executable.

``jitted(key, make_fn)`` memoizes ``jax.jit(make_fn())`` under a hashable
key describing the op and its static parameters (axis, kwargs, cast dtype,
scalar operands).  Subsequent calls with the same key skip tracing and
lowering entirely — XLA replays the compiled program, fusing the whole op
chain into one device round trip.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax

__all__ = ["jitted", "clear_cache", "cache_size"]

_CACHE: Dict[Tuple, Any] = {}


def jitted(key: Tuple, make_fn: Callable[[], Callable]) -> Callable:
    """Return a cached ``jax.jit`` of ``make_fn()`` memoized under ``key``.

    ``make_fn`` is only invoked on a cache miss; it should return a function
    closing over all static parameters named in ``key``.
    """
    fn = _CACHE.get(key)
    if fn is None:
        fn = jax.jit(make_fn())
        _CACHE[key] = fn
    return fn


def clear_cache() -> None:
    """Drop all cached executables (mainly for tests)."""
    _CACHE.clear()


def cache_size() -> int:
    return len(_CACHE)
