"""Cached-jit dispatch for the op engine.

Every user-level op (``ht.add``, ``ht.mean``, ``ht.sqrt`` …) runs a short
chain of jnp primitives.  Dispatching those eagerly costs one host↔device
round trip *per primitive* — on a tunneled/remote TPU that is ~50 ms each,
three orders of magnitude above the kernel time.  The reference never faces
this (torch eager ops run in-process, reference heat/core/_operations.py
drives local torch kernels directly); the TPU-native answer is to compile
each op chain once and replay the cached executable.

``jitted(key, make_fn)`` memoizes ``jax.jit(make_fn())`` under a hashable
key describing the op and its static parameters (axis, kwargs, cast dtype,
scalar operands).  Subsequent calls with the same key skip tracing and
lowering entirely — XLA replays the compiled program, fusing the whole op
chain into one device round trip.
"""

from __future__ import annotations

import sys
import types as _types
from typing import Any, Callable, Dict, Tuple

import numpy as np

import jax

from ..telemetry import _core as _tel
from ._tracing import record_dispatch

__all__ = [
    "jitted",
    "cache_stable",
    "clear_cache",
    "cache_size",
    "register_key_context",
    "context_token",
]

_CACHE: Dict[Tuple, Any] = {}

#: Process-wide state whose value changes what a cached program MEANS
#: (e.g. the collective-compression policy) registers a token provider
#: here; its current token joins every ``jitted`` key, so flipping the
#: state keys fresh entries instead of replaying stale programs.
_KEY_CONTEXT: list = []


def register_key_context(provider: Callable[[], Tuple]) -> Callable[[], Tuple]:
    """Register a zero-arg provider whose tuple joins every cache key."""
    if provider not in _KEY_CONTEXT:
        _KEY_CONTEXT.append(provider)
    return provider


def context_token() -> Tuple:
    """Concatenated tokens of all registered key-context providers."""
    out: Tuple = ()
    for provider in _KEY_CONTEXT:
        out = out + tuple(provider())
    return out

try:  # jax >= 0.4: True only outside any active jax trace
    _trace_state_clean = jax.core.trace_state_clean
except AttributeError:  # pragma: no cover - older jax
    def _trace_state_clean() -> bool:
        return True


def cache_stable(fn: Any) -> bool:
    """True when ``fn``'s identity repeats across calls, so it is safe to
    embed in a ``jitted`` key.

    Import-time singletons qualify: plain module-level ``def``s, numpy
    ufuncs, and any other callable that IS the attribute of its module
    under its own name (``jnp.add`` is a ``ufunc`` instance, ``jnp.where``
    a ``PjitFunction`` — both created once at import).  Lambdas, closures
    (anything defined inside a function — ``"<locals>"`` in the qualname),
    bound methods, and per-call ``partial`` objects do not: keying on a
    per-call identity grows the cache by one dead entry per call without
    ever hitting.  Callers must route unstable functions to a transient
    ``jax.jit`` or the eager path instead (spmdlint rule SPMD401).
    """
    if getattr(fn, "__self__", None) is not None:
        return False  # bound method: per-instance identity
    if isinstance(fn, _types.FunctionType):
        return (
            fn.__closure__ is None
            and "<locals>" not in fn.__qualname__
            and fn.__name__ != "<lambda>"
        )
    if isinstance(fn, np.ufunc):
        return True  # ufuncs only exist as import-time singletons
    mod = sys.modules.get(getattr(fn, "__module__", None) or "")
    name = getattr(fn, "__name__", None)
    return mod is not None and name is not None and getattr(mod, name, None) is fn


def jitted(key: Tuple, make_fn: Callable[[], Callable], jit_kwargs=None) -> Callable:
    """Return a cached ``jax.jit`` of ``make_fn()`` memoized under ``key``.

    ``make_fn`` is only invoked on a cache miss; it should return a function
    closing over all static parameters named in ``key``.

    ``jit_kwargs`` (a dict, used only on a miss) passes straight through to
    :func:`jax.jit` — e.g. ``out_shardings`` where the exact committed spec
    form matters (the redistribution planner pins its output layout so it
    compares EQUAL to the monolithic reshard's).  The key must determine the
    kwargs, exactly as it determines the traced function.

    The cached entry is a thin wrapper that records one device dispatch per
    eager invocation (see :mod:`heat_tpu.core._tracing`); calls made while a
    trace is active — an enclosing ``ht.fuse`` program or any jax trace —
    inline into the surrounding program and are not counted.
    """
    if _KEY_CONTEXT:
        key = key + context_token()
    fn = _CACHE.get(key)
    if fn is None:
        if _tel.enabled:
            _tel.inc("compile.cache.misses")
        jfn = jax.jit(make_fn(), **(jit_kwargs or {}))
        site = key[0] if key and isinstance(key[0], str) else getattr(
            jfn, "__name__", "op"
        )
        staged = [False]  # first-call stage timing done (telemetry only)

        def fn(*args, _jfn=jfn, **kwargs):
            clean = _trace_state_clean()
            if clean:
                record_dispatch()
            if _tel.enabled and clean:
                if not staged[0]:
                    staged[0] = True
                    out = _timed_first_call(site, _jfn, args, kwargs)
                    if out is not _AOT_UNAVAILABLE:
                        return out
                with _tel.span(f"jitted:{site}"):
                    return _jfn(*args, **kwargs)
            return _jfn(*args, **kwargs)

        fn.lower = jfn.lower  # HLO inspection passthrough (tests)
        fn.jitted = jfn
        _CACHE[key] = fn
        if _tel.enabled:
            _tel.gauge("compile.cache.size", len(_CACHE))
    elif _tel.enabled:
        _tel.inc("compile.cache.hits")
    return fn


_AOT_UNAVAILABLE = object()


def _timed_first_call(site: str, jfn, args, kwargs):
    """Telemetry-enabled first invocation of a freshly built ``jitted``
    entry: stage the call through the AOT API so the compile-miss event
    records trace+lower time and XLA compile time separately, then run
    the compiled executable (one dispatch, already counted by the
    caller).  Falls back to the plain call — returning the
    ``_AOT_UNAVAILABLE`` sentinel — when the AOT path does not apply
    (kwargs, older jax)."""
    if kwargs:
        return _AOT_UNAVAILABLE
    t0 = _tel.clock()
    try:
        lowered = jfn.lower(*args)
        t1 = _tel.clock()
        compiled = lowered.compile()
        t2 = _tel.clock()
    except Exception:
        return _AOT_UNAVAILABLE
    _tel.record_event(
        "compile", site=site, trace_lower_s=t1 - t0, compile_s=t2 - t1
    )
    with _tel.span(f"jitted:{site}", phase="first_run"):
        return compiled(*args)


def clear_cache() -> None:
    """Drop all cached executables (mainly for tests)."""
    _CACHE.clear()


def cache_size() -> int:
    return len(_CACHE)
