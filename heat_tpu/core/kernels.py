"""Pallas TPU kernels for hot loops.

SURVEY.md §7 reserves Pallas for the fused KMeans inner loop; this module
implements the fused **distance + argmin** assignment: for each row block,
the |x|²+|c|²−2xc distance tile and its argmin are computed entirely in
VMEM — one HBM read of x per row, no (n, k) distance matrix ever
materialized in HBM.  The centroid update remains a plain matmul (XLA is
already optimal there).

The kernel is opt-in (``assign_labels_pallas``) with a jnp fallback; on
CPU it runs in interpret mode so the same code path is testable without a
TPU.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

try:  # pallas is part of jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["assign_labels_pallas", "assign_labels"]


def _assign_kernel(x_ref, c_ref, out_ref):
    """One row-block: d² tile in VMEM, argmin over centroids."""
    x = x_ref[:]  # (bm, f)
    c = c_ref[:]  # (k, f)
    x2 = jnp.sum(x * x, axis=1, keepdims=True)  # (bm, 1)
    c2 = jnp.sum(c * c, axis=1)[None, :]  # (1, k)
    d2 = x2 + c2 - 2.0 * jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    out_ref[:] = jnp.argmin(d2, axis=1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _assign_pallas(x, centers, block_rows: int = 1024, interpret: bool = False):
    n, f = x.shape
    k = centers.shape[0]
    grid = (n // block_rows,)
    return pl.pallas_call(
        _assign_kernel,
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, f), lambda i: (i, 0)),
            pl.BlockSpec((k, f), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows,), lambda i: (i,)),
        interpret=interpret,
    )(x, centers)


def assign_labels_pallas(x, centers, block_rows: int = 1024):
    """Fused nearest-centroid assignment via the Pallas kernel.

    Pads the row count up to the block size, launches the grid, and slices
    the padding back off.  Uses interpret mode automatically off-TPU.
    """
    if not _HAS_PALLAS:
        return assign_labels(x, centers)
    x = jnp.asarray(x, jnp.float32)
    centers = jnp.asarray(centers, jnp.float32)
    n = x.shape[0]
    block_rows = min(block_rows, max(n, 8))
    pad = (-n) % block_rows
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)])
    interpret = jax.devices()[0].platform not in ("tpu",)
    labels = _assign_pallas(x, centers, block_rows=block_rows, interpret=interpret)
    return labels[:n]


def assign_labels(x, centers):
    """jnp fallback: identical semantics, XLA-fused."""
    from ..spatial.distance import quadratic_d2

    return jnp.argmin(quadratic_d2(jnp.asarray(x), jnp.asarray(centers)), axis=1).astype(
        jnp.int32
    )
