"""Array construction: the ``ht.array``/``arange``/``zeros``/… factories.

Reference: heat/core/factories.py:12-1146.  There, every factory computes its
rank's chunk via ``comm.chunk`` and allocates only the local slab
(factories.py:382-386, 644-720); ``is_split`` triggers a neighbor-shape
handshake with Isend/Probe/Recv + Allreduce validation (:387-430).

Here a factory allocates the **global** array once (XLA materializes shards
lazily per device under jit; for eager construction the host buffer is
device_put straight into its NamedSharding, so each device only receives its
own shard over PCIe/ICI).  ``is_split`` — "every rank contributes its local
piece" — becomes :func:`array` with a sequence of per-position pieces
concatenated along the split axis; no handshake is needed because the single
controller sees all pieces.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import devices
from . import types
from .communication import sanitize_comm, comm_for_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape
from .memory import sanitize_memory_layout

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "full",
    "full_like",
    "linspace",
    "logspace",
    "ones",
    "ones_like",
    "zeros",
    "zeros_like",
]


def _setup(device, comm):
    """Resolve (device, comm) defaults: the comm spans the device's platform
    mesh (reference: sanitize_device + sanitize_comm in every factory)."""
    device = devices.sanitize_device(device)
    if comm is None:
        comm = comm_for_device(device.platform)
    else:
        comm = sanitize_comm(comm)
    return device, comm


def _wrap(garr: jax.Array, dtype, split, device, comm) -> DNDarray:
    """Lay out a freshly built global array and wrap it.  ``split`` may be
    the legacy int or a splits tuple over the comm's mesh."""
    split = split if garr.ndim else None
    gshape = tuple(garr.shape)
    splits = comm.normalize_splits(garr.ndim, split)
    if all(g is None or gshape[d] % comm._axis_size(g) == 0 for d, g in enumerate(splits)):
        garr = comm.apply_sharding(garr, split)
    # ragged split: skip the (replicated) boundary commit — the DNDarray
    # constructor pads the axes and commits them sharded in one step
    return DNDarray(garr, gshape, dtype, split, device, comm, True)


def _resolve_layout(shape, split, splits, comm):
    """One layout from the two spellings: ``splits`` (a mesh-axis tuple,
    validated against the comm's mesh rank) wins when given; the legacy
    ``split`` int passes through :func:`sanitize_axis` as before.  The two
    are mutually exclusive, like ``split``/``is_split``."""
    if splits is not None:
        if split is not None:
            raise ValueError("split and splits are mutually exclusive parameters")
        return comm.normalize_splits(len(tuple(shape)), splits)
    if isinstance(split, (tuple, list)):
        return comm.normalize_splits(len(tuple(shape)), split)
    return sanitize_axis(tuple(shape), split)


def array(
    obj,
    dtype=None,
    copy: bool = True,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device=None,
    comm=None,
    splits=None,
) -> DNDarray:
    """The master constructor (reference factories.py:138-443).

    Parameters follow the reference: ``split`` shards an existing global
    array along an axis; ``is_split`` declares that ``obj`` is a sequence of
    per-position local pieces to be concatenated along that axis (the
    single-controller reading of the reference's "each rank passes its local
    chunk", factories.py:387-430).  ``splits`` is the N-D mesh spelling —
    a tuple assigning a mesh axis of ``comm`` to each array dim (e.g.
    ``splits=(0, 1)`` on a :func:`heat_tpu.grid_comm` blocks both dims).
    """
    if split is not None and is_split is not None:
        raise ValueError("split and is_split are mutually exclusive parameters")
    if splits is not None and (split is not None or is_split is not None):
        raise ValueError("splits is mutually exclusive with split/is_split")
    device, comm = _setup(device, comm)
    sanitize_memory_layout(None, order)

    if is_split is not None:
        if isinstance(obj, (list, tuple)) and all(
            isinstance(p, (DNDarray, np.ndarray, jnp.ndarray)) for p in obj
        ):
            pieces = [p.larray if isinstance(p, DNDarray) else jnp.asarray(p) for p in obj]
            obj = jnp.concatenate(pieces, axis=is_split)
        split = is_split

    # unpack existing containers
    if isinstance(obj, DNDarray):
        garr = obj.larray
        if split is None and is_split is None and splits is None:
            # keep the source's full grid layout when it lives on this comm;
            # a foreign comm's mesh axes mean nothing here, so fall back to
            # the compat int (the pre-grid behavior)
            split = obj._layout if obj.comm == comm else obj.split
    elif isinstance(obj, (jnp.ndarray, jax.Array)):
        garr = obj
    else:
        # copy HOST-side before the one transfer: np.asarray aliases any
        # buffer-protocol input (ndarray, memoryview, array.array), and on
        # the CPU backend jnp.asarray can then zero-copy that alias — a
        # caller mutating their source would mutate the DNDarray
        # (observed as an alignment-dependent flake).  A fresh host copy
        # is owned by nobody else, so the later jnp aliasing is harmless,
        # and accelerator backends pay no second device-side copy.
        host = np.array(obj, copy=True if copy else None)
        garr = jnp.asarray(host)

    # dtype resolution: heat defaults promote python float data to float32
    # (reference factories.py:240-260)
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        garr = garr.astype(dtype.jax_type())
    else:
        npdt = np.dtype(garr.dtype)
        if not isinstance(obj, (DNDarray, jnp.ndarray, jax.Array, np.ndarray)):
            # python scalars/lists default to 32-bit (TPU-first; matches
            # the jax convention and the reference's float32 default) —
            # unless the VALUES need 64 bits or leaves carry an explicit
            # numpy dtype.  One rule shared with types.heat_type_of; the
            # probe runs on the HOST copy because an accelerator with
            # emulated f64 may already have clobbered wide values
            if npdt in (np.int64, np.float64):
                seq = obj if isinstance(obj, (list, tuple)) else [obj]
                inferred = types._infer_list_type(seq, np.atleast_1d(host))
                if inferred is not types.canonical_heat_type(npdt):
                    garr = garr.astype(inferred.jax_type())
        dtype = types.canonical_heat_type(garr.dtype)

    if copy and isinstance(obj, (jnp.ndarray, jax.Array, DNDarray)):
        garr = jnp.array(garr, copy=True)

    if not isinstance(ndmin, (int, np.integer)) or isinstance(ndmin, bool):
        raise TypeError(f"expected ndmin to be int, but was {type(ndmin)}")
    # pad to abs(ndmin) dims by PREPENDING singleton axes.  The reference
    # accepts negative ndmin and prepends for it (factories.py:361-365);
    # for positive ndmin its code appends while its own docstring example
    # (factories.py:204-205) shows numpy's prepend — we follow numpy and
    # the docstring (see docs/migration.md)
    ndmin_abs = abs(int(ndmin)) - garr.ndim
    if ndmin_abs > 0:
        garr = garr.reshape((1,) * ndmin_abs + tuple(garr.shape))

    layout = _resolve_layout(garr.shape, split, splits, comm)
    return _wrap(garr, dtype, layout, device, comm)


def asarray(obj, dtype=None, order="C", is_split=None, device=None) -> DNDarray:
    """No-copy ``array`` (reference factories.py:438-571)."""
    sanitize_memory_layout(None, order)
    if (
        isinstance(obj, DNDarray)
        and is_split is None
        and (dtype is None or obj.dtype is types.canonical_heat_type(dtype))
    ):
        return obj
    return array(obj, dtype=dtype, copy=False, is_split=is_split, device=device)


def arange(*args, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Evenly spaced values in [start, stop) (reference factories.py:30-137).
    Default dtype int32 for integer arguments, float32 otherwise."""
    num_args = len(args)
    if num_args == 1:
        start, stop, step = 0, args[0], 1
    elif num_args == 2:
        start, stop, step = args[0], args[1], 1
    elif num_args == 3:
        start, stop, step = args
    else:
        raise TypeError(f"function takes minimum one and at most 3 positional arguments ({num_args} given)")

    device, comm = _setup(device, comm)
    all_int = all(isinstance(a, (int, np.integer)) for a in (start, stop, step))
    if dtype is None:
        dtype = types.int32 if all_int else types.float32
    dtype = types.canonical_heat_type(dtype)
    garr = jnp.arange(start, stop, step, dtype=dtype.jax_type())
    split = sanitize_axis(garr.shape, split)
    return _wrap(garr, dtype, split, device, comm)


def __factory(shape, dtype, split, builder, device, comm, order="C", splits=None) -> DNDarray:
    """Shared constructor core (reference __factory, factories.py:644-684)."""
    shape = sanitize_shape(shape)
    dtype = types.canonical_heat_type(dtype)
    device, comm = _setup(device, comm)
    layout = _resolve_layout(shape, split, splits, comm)
    sanitize_memory_layout(None, order)
    garr = builder(shape, dtype.jax_type())
    return _wrap(garr, dtype, layout, device, comm)


def empty(shape, dtype=types.float32, split=None, device=None, comm=None, order="C", splits=None) -> DNDarray:
    """Uninitialized array (reference factories.py:444-507).  XLA has no
    uninitialized allocation; zeros are used (same observable contract)."""
    return __factory(shape, dtype, split, lambda s, d: jnp.zeros(s, d), device, comm, order, splits)


def zeros(shape, dtype=types.float32, split=None, device=None, comm=None, order="C", splits=None) -> DNDarray:
    """Array of zeros (reference factories.py:1060-1112)."""
    return __factory(shape, dtype, split, lambda s, d: jnp.zeros(s, d), device, comm, order, splits)


def ones(shape, dtype=types.float32, split=None, device=None, comm=None, order="C", splits=None) -> DNDarray:
    """Array of ones (reference factories.py:955-1007)."""
    return __factory(shape, dtype, split, lambda s, d: jnp.ones(s, d), device, comm, order, splits)


def full(shape, fill_value, dtype=types.float32, split=None, device=None, comm=None, order="C", splits=None) -> DNDarray:
    """Constant-filled array (reference factories.py:721-772)."""
    return __factory(
        shape, dtype, split, lambda s, d: jnp.full(s, fill_value, d), device, comm, order, splits
    )


def __factory_like(a, dtype, split, factory, device, comm, order="C", **kwargs) -> DNDarray:
    """Shared *_like core (reference __factory_like, factories.py:685-720)."""
    shape = a.shape if hasattr(a, "shape") else np.asarray(a).shape
    if dtype is None:
        dtype = a.dtype if isinstance(a, DNDarray) else types.heat_type_of(a)
    if split is None and isinstance(a, DNDarray):
        split = a.split
    if device is None and isinstance(a, DNDarray):
        device = a.device
    return factory(shape, dtype=dtype, split=split, device=device, comm=comm, order=order, **kwargs)


def empty_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """(reference factories.py:508-552)"""
    return __factory_like(a, dtype, split, empty, device, comm, order)


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """(reference factories.py:1113-1146)"""
    return __factory_like(a, dtype, split, zeros, device, comm, order)


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """(reference factories.py:1008-1059)"""
    return __factory_like(a, dtype, split, ones, device, comm, order)


def full_like(a, fill_value, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """(reference factories.py:773-823)"""
    return __factory_like(a, dtype, split, full, device, comm, order, fill_value=fill_value)


def eye(shape, dtype=types.float32, split=None, device=None, comm=None, order="C", splits=None) -> DNDarray:
    """Identity-like matrix (reference factories.py:572-643 — there each rank
    computes its diagonal offset; here one global jnp.eye)."""
    sanitize_memory_layout(None, order)
    if isinstance(shape, (int, np.integer)):
        gshape = (int(shape), int(shape))
    else:
        shape = sanitize_shape(shape)
        gshape = (shape[0], shape[1] if len(shape) > 1 else shape[0])
    dtype = types.canonical_heat_type(dtype)
    device, comm = _setup(device, comm)
    layout = _resolve_layout(gshape, split, splits, comm)
    garr = jnp.eye(gshape[0], gshape[1], dtype=dtype.jax_type())
    return _wrap(garr, dtype, layout, device, comm)


def linspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    retstep: bool = False,
    dtype=None,
    split=None,
    device=None,
    comm=None,
):
    """num evenly spaced samples over [start, stop] (reference
    factories.py:824-915)."""
    num = int(num)
    if num <= 0:
        raise ValueError(f"number of samples 'num' must be non-negative, but was {num}")
    device, comm = _setup(device, comm)
    start_f, stop_f = float(start), float(stop)
    step = (stop_f - start_f) / max((num - (1 if endpoint else 0)), 1)
    # build the grid in f64 and round ONCE into the target dtype: a grid
    # computed directly in f32 (start + i*step per element) carries
    # accumulated half-ulp errors that exceed rtol=1e-6 near zero
    # crossings (x64 is on at import, so f64 is available)
    garr = jnp.linspace(start_f, stop_f, num, endpoint=endpoint, dtype=jnp.float64)
    dtype = types.canonical_heat_type(dtype) if dtype is not None else types.float32
    garr = garr.astype(dtype.jax_type())
    split = sanitize_axis(garr.shape, split)
    ht = _wrap(garr, dtype, split, device, comm)
    if retstep:
        return ht, step
    return ht


def logspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    base: float = 10.0,
    dtype=None,
    split=None,
    device=None,
    comm=None,
) -> DNDarray:
    """num log-spaced samples (reference factories.py:916-954)."""
    y = linspace(start, stop, num=num, endpoint=endpoint, split=split, device=device, comm=comm)
    from . import arithmetics

    result = arithmetics.pow(float(base), y)
    if dtype is None:
        return result
    return result.astype(types.canonical_heat_type(dtype))


# split semantics for heat_tpu.analysis.splitflow (see core/_split_semantics.py)
from ._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {
        "factory": (
            "array", "arange", "empty", "zeros", "ones", "full", "eye",
            "linspace", "logspace",
        ),
        "factory_like": ("empty_like", "zeros_like", "ones_like", "full_like"),
    },
)
