"""TPU-native communication layer: device meshes + XLA collectives.

This module is the TPU-first re-design of the reference's MPI backend
(reference: heat/core/communication.py:23-1184, classes ``Communication`` /
``MPICommunication`` / ``MPIRequest``).  The reference launches N identical
MPI processes and hand-writes every collective over mpi4py buffers.  Here the
execution model is **single-controller SPMD**: one Python process drives a
1-D :class:`jax.sharding.Mesh` of devices, arrays are *global*
:class:`jax.Array` objects whose layout is described by a
:class:`~jax.sharding.NamedSharding`, and XLA lowers resharding requests to
``all-gather`` / ``all-to-all`` / ``collective-permute`` over ICI (within a
slice) or DCN (across slices).  There are no ranks and no message-passing in
user code — a "collective" at this level is a *sharding transformation* of a
global array, which is both the idiomatic XLA formulation and the reason this
backend needs no CUDA-awareness sniffing, no derived datatypes, and no
staging buffers (reference communication.py:10-20, 212-374).

Key correspondences with the reference:

=====================================  =========================================
reference (MPI)                        heat_tpu (XLA)
=====================================  =========================================
``MPI_WORLD`` / N ranks                one :class:`Communication` over all
                                       devices of a platform (the mesh)
``chunk()`` (communication.py:82)      :meth:`Communication.chunk` —
                                       ceil-division shard geometry (GSPMD's
                                       layout rule, *not* MPI's
                                       remainder-to-low-ranks rule)
``Allreduce`` (communication.py:516)   a reduction op on a global array — XLA
                                       emits the all-reduce; explicit form:
                                       :func:`jax.lax.psum` inside
                                       ``shard_map`` (see :meth:`allreduce`)
``Allgatherv`` (communication.py:646)  :meth:`allgather` = reshard to
                                       replicated
``Alltoallv`` (communication.py:843)   :meth:`alltoall` = reshard from one
                                       axis to another (the "Ulysses"
                                       head/sequence swap primitive)
``Send/Recv`` rings                    :func:`jax.lax.ppermute` inside
                                       ``shard_map`` (:meth:`ring_permute`)
``MPIRequest`` (async)                 XLA's async dispatch — every jax op is
                                       non-blocking until its value is read
=====================================  =========================================
"""

from __future__ import annotations

import math
import os
import sys
import warnings
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..telemetry import _core as _tel
from ._compile import jitted
from ._jax_compat import distributed_is_initialized, shard_map
from ._tracing import in_trace, record_dispatch

__all__ = [
    "Communication",
    "XlaCommunication",
    "MESH_AXIS",
    "get_comm",
    "use_comm",
    "sanitize_comm",
    "comm_for_device",
    "grid_comm",
    "init_multihost",
]

#: Name of the (single) mesh axis every DNDarray is sharded over.  The
#: reference's "rank along MPI_COMM_WORLD" becomes "position along this axis".
MESH_AXIS = "heat"

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _user_stacklevel() -> int:
    """``warnings.warn`` stacklevel attributing to the first frame OUTSIDE
    the heat_tpu package.

    A fixed ``stacklevel=2`` is right only for direct callers; when a
    comm method is reached through a wrapper (DNDarray method, fused
    program, another comm method) the warning points inside the library.
    Walking the stack from the warning site to the first external frame
    makes the attribution correct in both cases.
    """
    level = 2  # stacklevel=2 == the caller of the method that warns
    frame = sys._getframe(2)  # 0=this helper, 1=the warning method, 2=its caller
    while frame is not None and os.path.abspath(frame.f_code.co_filename).startswith(
        _PKG_DIR + os.sep
    ):
        frame = frame.f_back
        level += 1
    return level


#: warning sites already fired this process — keyed (warning kind,
#: (user filename, user lineno)), so a resplit loop warns ONCE per call
#: site instead of once per iteration.  Tests clear this set directly.
_WARNED_SITES: set = set()


def _user_site() -> Tuple[int, Tuple[str, int]]:
    """(stacklevel, (filename, lineno)) of the first frame OUTSIDE the
    heat_tpu package, counted for a ``warnings.warn`` issued one helper
    below the warning method (see :func:`_warn_once_per_site`)."""
    level = 2
    frame = sys._getframe(2)  # 0=this helper, 1=_warn_once_per_site, 2=the method
    while frame is not None and os.path.abspath(frame.f_code.co_filename).startswith(
        _PKG_DIR + os.sep
    ):
        frame = frame.f_back
        level += 1
    if frame is None:
        return level, ("<unknown>", 0)
    return level, (frame.f_code.co_filename, frame.f_lineno)


def _warn_once_per_site(message: str, kind: str) -> None:
    """Warn with :func:`_user_stacklevel`-style attribution, deduplicated
    per user call site: the first hit from a given (file, line) fires,
    repeats — a resplit inside a loop body — stay silent."""
    level, site = _user_site()
    key = (kind, site)
    if key in _WARNED_SITES:
        return
    _WARNED_SITES.add(key)
    warnings.warn(message, stacklevel=level)


def _nbytes_of(array) -> int:
    """Payload bytes from shape/dtype (tracers lack ``.nbytes``)."""
    elems = 1
    for s in tuple(getattr(array, "shape", ()) or ()):
        elems *= int(s)
    return elems * jnp.dtype(array.dtype).itemsize


class Communication:
    """Abstract communication seam (reference: heat/core/communication.py:23-51).

    Concrete backends implement shard geometry (:meth:`chunk`) and the
    sharding-transformation collectives.  This mirrors the reference's
    abstract ``Communication`` class, which is the documented extension point
    for alternative backends.
    """

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def chunk(self, shape, split, rank=None) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        raise NotImplementedError()


class XlaCommunication(Communication):
    """A communicator backed by a (1-D or N-D) JAX device mesh.

    Parameters
    ----------
    devices : sequence of jax.Device, optional
        Devices spanned by this communicator.  Defaults to every device of
        the default platform (the analog of ``MPI_WORLD``,
        reference communication.py:1123).
    axis_name : str
        Base mesh axis name used for collectives inside ``shard_map``.  A
        1-D mesh uses it verbatim (``"heat"``); an N-D mesh derives one
        name per mesh axis (``"heat0"``, ``"heat1"``, ...).
    mesh_shape : tuple of int, optional
        Logical mesh shape.  Defaults to ``(len(devices),)`` — the 1-D
        communicator every existing call site gets.  A 2-D shape ``(r, c)``
        arranges the same devices on an r×c grid; array layouts over it are
        *splits tuples* (``splits[d]`` = the mesh axis sharding array dim
        ``d``, or None), with the legacy single ``split`` int an exact view
        of the tuple layouts that shard only mesh axis 0.
    """

    def __init__(
        self,
        devices: Optional[Sequence] = None,
        axis_name: str = MESH_AXIS,
        mesh_shape: Optional[Tuple[int, ...]] = None,
    ):
        if devices is None:
            devices = jax.devices()
        self._devices = list(devices)
        if mesh_shape is None:
            mesh_shape = (len(self._devices),)
        mesh_shape = tuple(int(s) for s in mesh_shape)
        if any(s < 1 for s in mesh_shape) or math.prod(mesh_shape) != len(self._devices):
            raise ValueError(
                f"mesh_shape {mesh_shape} does not tile {len(self._devices)} device(s)"
            )
        self._mesh_shape = mesh_shape
        if len(mesh_shape) == 1:
            # the 1-D axis name stays exactly `axis_name` ("heat") so every
            # existing kernel, cache key, and committed sharding is unchanged
            self._axis_names: Tuple[str, ...] = (axis_name,)
        else:
            self._axis_names = tuple(f"{axis_name}{i}" for i in range(len(mesh_shape)))
        self.axis_name = self._axis_names[0]
        self._mesh = Mesh(
            np.asarray(self._devices).reshape(mesh_shape), self._axis_names
        )

    # ------------------------------------------------------------------ #
    # identity / geometry                                                #
    # ------------------------------------------------------------------ #
    @property
    def devices(self) -> List:
        """The devices in this communicator's mesh."""
        return list(self._devices)

    @property
    def mesh(self) -> Mesh:
        """The :class:`jax.sharding.Mesh` backing this communicator."""
        return self._mesh

    @property
    def mesh_shape(self) -> Tuple[int, ...]:
        """Logical mesh shape; ``(size,)`` for the default 1-D communicator."""
        return self._mesh_shape

    @property
    def mesh_ndim(self) -> int:
        """Number of mesh axes (1 for every legacy communicator)."""
        return len(self._mesh_shape)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        """Mesh axis names; ``("heat",)`` 1-D, ``("heat0", "heat1")`` 2-D."""
        return self._axis_names

    @property
    def size(self) -> int:
        """Number of devices (the reference's ``comm.size`` = MPI world size)."""
        return len(self._devices)

    @property
    def rank(self) -> int:
        """Index of the controlling process.

        Single-controller SPMD has no per-device rank in user code; for
        multi-host setups this is the JAX process index.  (Reference:
        ``comm.rank``, communication.py:76 — there, every Python process had
        a distinct rank; here one process drives all local devices.)
        """
        return jax.process_index()

    def is_distributed(self) -> bool:
        """True when the mesh spans more than one device."""
        return self.size > 1

    def local_position(self) -> int:
        """Mesh position of the calling process's first addressable device.

        Single-host this is 0 (every device is addressable); on multihost it
        is the position of the first device owned by THIS process — the
        honest analog of the reference's "calling rank" for per-shard
        metadata like ``DNDarray.lshape``.
        """
        pid = jax.process_index()
        for pos, d in enumerate(self._devices):
            if getattr(d, "process_index", 0) == pid:
                return pos
        return 0

    def __repr__(self) -> str:
        plat = self._devices[0].platform if self._devices else "?"
        grid = "x".join(str(s) for s in self._mesh_shape)
        return f"XlaCommunication({self.size} {plat} device(s), mesh={grid}, axis='{self.axis_name}')"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, XlaCommunication)
            and self._devices == other._devices
            and self.axis_name == other.axis_name
            and self._mesh_shape == other._mesh_shape
        )

    def __hash__(self) -> int:
        return hash((tuple(id(d) for d in self._devices), self.axis_name, self._mesh_shape))

    # ------------------------------------------------------------------ #
    # splits tuples (the N-D layout vocabulary)                           #
    # ------------------------------------------------------------------ #
    def normalize_splits(
        self, ndim: int, split: Union[None, int, Sequence[Optional[int]]]
    ) -> Tuple[Optional[int], ...]:
        """Canonicalize any layout spelling to a splits tuple.

        ``splits[d]`` names the mesh axis sharding array dimension ``d``
        (or None).  The three accepted spellings:

        * ``None`` — fully replicated, ``(None,) * ndim``;
        * an int ``s`` — the legacy 1-axis layout: dim ``s`` sharded over
          mesh axis 0 (negative ``s`` counts from the end, as before);
        * a sequence of length ``ndim`` of mesh-axis indices / Nones.

        A mesh axis may shard at most one array dimension (a
        :class:`~jax.sharding.PartitionSpec` invariant).
        """
        ndim = int(ndim)
        if split is None:
            return (None,) * ndim
        if isinstance(split, (tuple, list)):
            splits = tuple(None if g is None else int(g) for g in split)
            if len(splits) != ndim:
                raise ValueError(
                    f"splits {splits} has arity {len(splits)}, array has ndim {ndim}"
                )
            used = [g for g in splits if g is not None]
            for g in used:
                if not 0 <= g < self.mesh_ndim:
                    raise ValueError(
                        f"splits {splits}: mesh axis {g} out of range for a "
                        f"{self.mesh_ndim}-D mesh of shape {self._mesh_shape}"
                    )
            if len(set(used)) != len(used):
                raise ValueError(f"splits {splits} uses a mesh axis more than once")
            return splits
        entries: List[Optional[int]] = [None] * ndim
        entries[int(split)] = 0  # negative ints index from the end, as before
        return tuple(entries)

    @staticmethod
    def split_view(splits: Tuple[Optional[int], ...]) -> Optional[int]:
        """The legacy ``split`` int of a splits tuple: the array dimension
        sharded by mesh axis 0 (None when axis 0 shards nothing).  Exact
        and lossless on a 1-D mesh — the only mesh legacy layouts live on."""
        for d, g in enumerate(splits):
            if g == 0:
                return d
        return None

    def _axis_size(self, mesh_axis: Optional[int] = None) -> int:
        """Devices along one mesh axis; the whole mesh when ``None`` (the
        legacy 1-D reading, where axis 0 *is* the mesh)."""
        return self.size if mesh_axis is None else int(self._mesh_shape[mesh_axis])

    # ------------------------------------------------------------------ #
    # shard geometry (reference: chunk, communication.py:82-169)          #
    # ------------------------------------------------------------------ #
    def chunk(
        self, shape: Sequence[int], split: Optional[int], rank: Optional[int] = None
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Compute the shard of ``shape`` owned by mesh position ``rank``.

        The reference's partitioner (communication.py:82-137) hands
        ``size//w (+1 for low ranks)`` items to each rank.  XLA/GSPMD instead
        uses **ceil-division**: every shard is ``ceil(n/size)`` wide and the
        trailing shards absorb the shortfall (possibly empty).  We adopt the
        GSPMD rule so that ``chunk()`` always describes the *actual* on-device
        layout of a sharded ``jax.Array``.

        Returns
        -------
        offset : int
            Global start index along the split axis.
        lshape : tuple of int
            Shape of the local shard.
        slices : tuple of slice
            Global-coordinate slices selecting the shard.
        """
        if rank is None:
            rank = 0
        shape = tuple(int(s) for s in shape)
        if split is None:
            return 0, shape, tuple(slice(0, s) for s in shape)
        if isinstance(split, (tuple, list)):
            return self._chunk_grid(shape, tuple(split), rank)
        split = int(split) % max(len(shape), 1)
        n = shape[split]
        c = -(-n // self.size) if n else 0  # ceil division
        start = min(rank * c, n)
        stop = min((rank + 1) * c, n)
        lshape = shape[:split] + (stop - start,) + shape[split + 1 :]
        slices = tuple(
            slice(start, stop) if dim == split else slice(0, s) for dim, s in enumerate(shape)
        )
        return start, lshape, slices

    def _chunk_grid(
        self, shape: Tuple[int, ...], splits: Tuple[Optional[int], ...], rank: int
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Splits-tuple shard geometry: ``rank`` is a flat row-major mesh
        position; each sharded dim divides ceil-wise over its own mesh axis.
        The returned scalar offset is the one along the mesh-axis-0 dim (the
        ``split`` compat view's axis; 0 when axis 0 shards nothing)."""
        splits = self.normalize_splits(len(shape), splits)
        pos = np.unravel_index(int(rank) % max(self.size, 1), self._mesh_shape)
        lshape, slices, offset0 = [], [], 0
        for dim, (n, g) in enumerate(zip(shape, splits)):
            if g is None:
                lshape.append(n)
                slices.append(slice(0, n))
                continue
            c = self.shard_width(n, mesh_axis=g)
            start = min(int(pos[g]) * c, n)
            stop = min((int(pos[g]) + 1) * c, n)
            lshape.append(stop - start)
            slices.append(slice(start, stop))
            if g == 0:
                offset0 = start
        return offset0, tuple(lshape), tuple(slices)

    def counts_displs_shape(
        self, shape: Sequence[int], split: int
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...], Tuple[int, ...]]:
        """Per-position counts and displacements along ``split``.

        Mirrors reference communication.py:138-169 (used there to drive
        ``Allgatherv``/``Scatterv``); here used for shard bookkeeping and IO.
        """
        counts, displs = [], []
        for r in range(self.size):
            offset, lshape, _ = self.chunk(shape, split, rank=r)
            counts.append(lshape[split])
            displs.append(offset)
        _, lshape0, _ = self.chunk(shape, split, rank=self.rank)
        return tuple(counts), tuple(displs), tuple(lshape0)

    # ------------------------------------------------------------------ #
    # ragged-shard machinery (SURVEY §7 hard-part #1)                     #
    # ------------------------------------------------------------------ #
    # XLA shards are equal-sized; the reference instead allows ±1-remainder
    # and arbitrarily unbalanced shards (reference communication.py:138-169
    # Allgatherv/Scatterv counts, dndarray.py:900/2560 balance_/
    # redistribute_).  The bridge is *canonical padding*: an axis of length
    # n is zero-padded to size·ceil(n/size) so every shard is exactly
    # ``shard_width(n)`` wide, and ``valid_counts(n)`` records how many
    # leading rows of each shard are real data.  Every explicit shard_map
    # algorithm (permute/ring/halo/TSQR) consumes the padded layout and is
    # thereby defined for *any* axis length, including prime-mesh ragged
    # cases; results are sliced back with :meth:`unpad`.

    def shard_width(self, n: int, mesh_axis: Optional[int] = None) -> int:
        """Width of every (padded) shard of an axis of length ``n``:
        ``ceil(n / p)`` — the GSPMD layout rule.  ``p`` is the whole mesh
        (legacy 1-D reading) unless ``mesh_axis`` selects one grid axis."""
        n = int(n)
        return -(-n // self._axis_size(mesh_axis)) if n else 0

    def padded_size(self, n: int, mesh_axis: Optional[int] = None) -> int:
        """Padded axis length ``p * shard_width(n)`` (≥ n)."""
        return self._axis_size(mesh_axis) * self.shard_width(n, mesh_axis)

    def valid_counts(self, n: int, mesh_axis: Optional[int] = None) -> Tuple[int, ...]:
        """Per-position count of real (un-padded) rows along an axis of
        length ``n``: position r holds global rows
        ``[r*c, min((r+1)*c, n))`` of the padded layout.  The analog of the
        reference's Allgatherv/Scatterv counts vector
        (communication.py:138-169)."""
        c = self.shard_width(n, mesh_axis)
        n = int(n)
        return tuple(min(c, max(0, n - r * c)) for r in range(self._axis_size(mesh_axis)))

    def pad_to_shards(self, array: jax.Array, axis: int = 0, splits=None) -> jax.Array:
        """Zero-pad the sharded axes to their canonical padded lengths and
        commit the layout.

        Legacy form (``axis``): pad ``axis`` so ``shape[axis] % size == 0``.
        Splits form (``splits``): pad every dim a mesh axis shards to that
        *axis's* width — dim ``d`` with ``splits[d] = g`` pads to
        ``padded_size(n_d, mesh_axis=g)``.  On a 1-D mesh the two forms
        coincide exactly.  After this every explicit shard_map algorithm
        applies; the invalid tail rows of each shard are zeros.  No-op (bar
        the sharding) for already-divisible axes.
        """
        if splits is None:
            splits = self.normalize_splits(array.ndim, axis)
        else:
            splits = self.normalize_splits(array.ndim, splits)
        widths = []
        for d, g in enumerate(splits):
            if g is None:
                widths.append((0, 0))
                continue
            n = int(array.shape[d])
            widths.append((0, self.padded_size(n, mesh_axis=g) - n))
        if any(w for _, w in widths):

            def make():
                def _pad(x):
                    return jnp.pad(x, widths)

                return _pad

            array = jitted(("comm.pad", self, tuple(widths), array.ndim), make)(array)
        return self.apply_sharding(array, splits)

    def unpad(self, array: jax.Array, n: int, axis: int = 0) -> jax.Array:
        """Slice a padded axis back to its true length ``n``."""
        if int(array.shape[axis]) == int(n):
            return array
        sl = [slice(None)] * array.ndim
        sl[axis] = slice(0, int(n))
        return array[tuple(sl)]

    # ------------------------------------------------------------------ #
    # shardings                                                          #
    # ------------------------------------------------------------------ #
    def spec(self, ndim: int, split) -> PartitionSpec:
        """PartitionSpec for a layout — ``split`` in any of the spellings
        :meth:`normalize_splits` accepts (None / int / splits tuple)."""
        if split is None:
            return PartitionSpec()
        splits = self.normalize_splits(ndim, split)
        if all(g is None for g in splits):
            # canonical replicated spec: callers compare shardings for
            # their no-op early-outs, and PartitionSpec(None, None) !=
            # PartitionSpec() even though the layouts are identical
            return PartitionSpec()
        entries = [None if g is None else self._axis_names[g] for g in splits]
        return PartitionSpec(*entries)

    def sharding(self, ndim: int, split) -> NamedSharding:
        """NamedSharding for an ``ndim``-dimensional array laid out at
        ``split`` (int, None, or splits tuple)."""
        return NamedSharding(self._mesh, self.spec(ndim, split))

    def apply_sharding(self, array: jax.Array, split) -> jax.Array:
        """Lay out a global array according to ``split``.

        Exact :func:`jax.device_put` when the split axis is divisible by the
        mesh size; otherwise a compiled ``with_sharding_constraint`` lets
        GSPMD choose the closest valid layout (sharding is a performance
        hint, never a correctness constraint — the deliberate inversion of
        the reference, where layout errors corrupt results).

        Under an ``ht.fuse`` trace there is no committed layout to inspect
        or create — the request becomes a
        :func:`jax.lax.with_sharding_constraint` hint that GSPMD resolves
        when the whole program compiles.
        """
        if isinstance(array, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(array, self.sharding(array.ndim, split))
        if in_trace():
            # concrete array inside a fuse.trace() block: same constraint
            # semantics, via the compiled form (eager wsc commits a
            # single-device layout, losing the mesh)
            return _constrained_copy(array, self.sharding(array.ndim, split))
        if self.size == 1:
            # single device: every layout is trivially correct — skip the
            # device_put dispatch when the data already lives on our device
            if getattr(array, "devices", None) and array.devices() == {self._devices[0]}:
                return array
            split = None
        sh = self.sharding(array.ndim, split)
        splits = self.normalize_splits(array.ndim, split)
        divisible = all(
            g is None or array.shape[d] % self._axis_size(g) == 0
            for d, g in enumerate(splits)
        )
        if divisible:
            return _reshard(array, sh)
        if os.environ.get("HEAT_DEBUG_RAGGED_COMMIT") == "1":
            # the memory-hazard tripwire: THIS branch (and only this
            # branch) commits replicated — _constrained_copy is also the
            # multi-process reshard path for perfectly divisible arrays,
            # so the warning lives at the ragged call site
            warnings.warn(
                f"ragged-axis commit replicates: axis {split} of shape "
                f"{tuple(array.shape)} does not divide over {self.size} "
                "devices, so every device stores a full copy (use a "
                "divisible split axis, pre-pad with pad_to_shards, or keep "
                "the array inside one jit region)",
                stacklevel=3,
            )
        return _constrained_copy(array, sh)

    # ------------------------------------------------------------------ #
    # collectives as sharding transformations                            #
    # ------------------------------------------------------------------ #
    def allgather(self, array: jax.Array, axis: int = 0) -> jax.Array:
        """Replicate a split array: the reference's ``Allgatherv``
        (communication.py:646-711) expressed as a reshard-to-replicated; XLA
        emits a single all-gather over ICI.

        Consults the collective-precision policy
        (:func:`heat_tpu.comm.set_collective_precision`): a compressible
        payload on a canonically split axis rides the block-scaled
        quantized ring instead (:func:`heat_tpu.comm.allgather_q`);
        ``"f32"`` (the default), exact dtypes, ragged axes, and traced
        inputs keep the exact reshard.
        """
        del axis  # the global array already carries its own geometry
        if self.size > 1 and getattr(array, "ndim", 0):
            from ..comm import compressed as _cq

            src = self._split_axis_of(array)
            mode = _cq.reduce_mode(array.dtype, _nbytes_of(array))
            if mode is not None:
                if src is not None and int(array.shape[src]) % self.size == 0:
                    return _cq.allgather_q(array, axis=src, comm=self, precision=mode)
            # ledger + span only when traffic actually moves: an already
            # replicated input (src None — includes every tracer) makes
            # the reshard a no-op, and crediting (p-1)/p of its bytes
            # here overcounted every allgather of replicated data
            if _tel.enabled and src is not None:
                _cq._account_wire(
                    "allgather", None, int(np.prod(array.shape)) // self.size, self.size
                )
                with _tel.span("comm:allgather", mesh=self.size):
                    return _reshard(array, self.sharding(array.ndim, None))
        return _reshard(array, self.sharding(array.ndim, None))

    def alltoall(self, array: jax.Array, send_axis: int, recv_axis: int) -> jax.Array:
        """Swap the sharded axis: the reference's axis-permuted ``Alltoallv``
        (communication.py:764-881) and the Ulysses sequence↔head swap.

        Naming follows MPI: data split at ``recv_axis`` gets re-split at
        ``send_axis``.

        Contract: in the global-array model the input's current layout
        never affects VALUES, so ``recv_axis`` is a statement about the
        expected input layout, not a transformation step — resharding to
        it first would only add an inert collective.  The result is
        always the global array laid out at ``send_axis``; ``recv_axis``
        exists purely so layout bookkeeping bugs surface: a warning fires
        when the input's layout DEFINITIVELY contradicts it, meaning the
        committed sharding is this mesh's own canonical (divisible)
        layout on a different axis.  Ragged axes are exempt — there GSPMD
        may legitimately commit a different-looking layout than the
        logical split, and warning on it would be noise (the spurious
        fire VERDICT r2 #9 flagged).  XLA emits a single all-to-all over
        ICI when both axes are divisible.
        """
        src = self._split_axis_of(array)
        if recv_axis is not None and src is not None and src != recv_axis:
            # only a canonical divisible layout on our mesh is definitive
            definitive = (
                getattr(array.sharding, "mesh", None) == self._mesh
                and array.shape[src] % self.size == 0
            )
            if definitive:
                # once per user call site: a resplit loop hits this path
                # every iteration and per-iteration repeats are noise
                _warn_once_per_site(
                    f"alltoall: input is split at axis {src}, not recv_axis="
                    f"{recv_axis}; the global result is unaffected (layout is "
                    "a performance hint), but the caller's layout bookkeeping "
                    "may be stale",
                    kind="alltoall-stale-recv",
                )
        return self.resplit(array, send_axis)

    def resplit(self, array: jax.Array, split: Optional[int]) -> jax.Array:
        """Generic reshard (the engine under ``DNDarray.resplit_``,
        reference dndarray.py:2801-2921): split→None is an all-gather,
        None→split a local slice-discard, split→split an all-to-all.

        Consults the redistribution policy
        (:func:`heat_tpu.comm.set_redistribution`): eligible eager
        changes run the planner's compiled schedule
        (:mod:`heat_tpu.comm.redistribute`) — same values, bounded peak
        memory, one dispatch; everything else takes the monolithic GSPMD
        reshard."""
        split = self._collapse_layout(getattr(array, "ndim", 0), split)
        if self.mesh_ndim > 1:
            return self._grid_resplit(array, split, allow_pad=False)
        out = self._planned_resplit(array, split, allow_pad=False)
        if out is not None:
            return out
        return self.apply_sharding(array, split)

    def commit_split(self, array: jax.Array, split: Optional[int]) -> jax.Array:
        """Reshard a TRUE-shape global array to ``split`` in its at-rest
        form: a ragged target axis pads+shards in ONE step (apply_sharding
        on the ragged view would commit it replicated first); divisible or
        replicated targets take the plain reshard.  The single dispatch
        site shared by in-place and out-of-place resplit.  Routes through
        the redistribution planner like :meth:`resplit` (the planner's
        schedules pad ragged target axes themselves, preserving this
        method's padded at-rest contract)."""
        split = self._collapse_layout(getattr(array, "ndim", 0), split)
        if self.mesh_ndim > 1:
            return self._grid_resplit(array, split, allow_pad=True)
        out = self._planned_resplit(array, split, allow_pad=True)
        if out is not None:
            return out
        if split is not None and array.ndim and array.shape[split] % max(self.size, 1):
            return self.pad_to_shards(array, axis=split)
        return self.apply_sharding(array, split)

    def _collapse_layout(self, ndim: int, split):
        """On a 1-D mesh a splits tuple is exactly its ``split`` compat int
        — collapse it so the legacy planner/reshard paths apply verbatim.
        N-D meshes keep the tuple."""
        if self.mesh_ndim == 1 and isinstance(split, (tuple, list)):
            return self.split_view(self.normalize_splits(ndim, split))
        return split

    def _grid_resplit(self, array: jax.Array, split, allow_pad: bool) -> jax.Array:
        """Layout change on an N-D mesh: the 2-D redistribution planner
        when eligible (one compiled dispatch, bounded peak memory,
        per-mesh-axis factored schedule), else the monolithic GSPMD
        reshard — padding ragged target dims first when the caller's
        contract allows (``commit_split``)."""
        from ..comm import redistribute as _rd

        splits = self.normalize_splits(getattr(array, "ndim", 0) or 0, split)
        out = _rd.grid_redistribute_or_none(array, splits, comm=self, allow_pad=allow_pad)
        if out is not None:
            return out
        if allow_pad and getattr(array, "ndim", 0):
            ragged = any(
                g is not None and int(array.shape[d]) % self._axis_size(g)
                for d, g in enumerate(splits)
            )
            if ragged:
                return self.pad_to_shards(array, splits=splits)
        return self.apply_sharding(array, splits)

    def _planned_resplit(
        self, array: jax.Array, split: Optional[int], allow_pad: bool
    ) -> Optional[jax.Array]:
        """The redistribution-policy seam: the planned result, or None
        when this change stays on the monolithic path.

        Fallback (monolithic) whenever the planner cannot improve on or
        exactly reproduce the GSPMD reshard: policy "monolithic";
        tracers and fuse traces (layout is a constraint there, not a
        program); single-device or multi-process meshes; host values;
        inputs committed on a foreign mesh or non-canonically; ragged
        destinations when the caller's contract forbids padding
        (``resplit``/``alltoall`` preserve shape; ``commit_split`` pads).
        Policy "auto" additionally demands a split→split change of at
        least :func:`heat_tpu.comm.get_redistribution_threshold` bytes —
        the regime where the rotation schedule's p× wire saving beats
        the monolithic reshard's single-collective latency.
        """
        from ..comm import redistribute as _rd

        policy = _rd.get_redistribution()
        if policy == "monolithic" or self.size == 1:
            return None
        if isinstance(array, jax.core.Tracer) or in_trace():
            return None
        if not isinstance(array, jax.Array) or not getattr(array, "ndim", 0):
            return None
        if any(int(s) == 0 for s in array.shape) or jax.process_count() > 1:
            return None
        ndim = array.ndim
        dst = None if split is None else int(split) % ndim
        src = self._split_axis_of(array)
        if src is not None and (
            getattr(array.sharding, "mesh", None) != self._mesh
            or int(array.shape[src]) % self.size
        ):
            return None
        if src == dst:
            return None  # no-op: apply_sharding's early-outs are cheaper
        if dst is not None and not allow_pad and int(array.shape[dst]) % self.size:
            return None
        if policy == "auto" and (
            src is None
            or dst is None
            or _nbytes_of(array) < _rd.get_redistribution_threshold()
        ):
            return None
        return _rd.redistribute(array, dst, comm=self, src=src)

    def allreduce(self, array: jax.Array, op: str = "sum") -> jax.Array:
        """All-reduce a *per-position* quantity (reference ``Allreduce``,
        communication.py:516-523).

        ``array`` has shape ``(size, ...)`` — one block per mesh position.
        The blocks are sharded over the mesh and combined with a real XLA
        collective inside ``shard_map`` (``psum``/``pmax``/``pmin``; prod
        via all-gather + local product); the combined value, shape ``(...)``,
        comes back replicated.  On global arrays a plain reduction
        (``x.sum()``) already implies this collective — the explicit form
        exists for per-shard partials and shard_map kernels.
        """
        if op not in ("sum", "prod", "max", "min"):
            raise ValueError(f"unsupported allreduce op {op!r}")
        n = self.size
        if int(array.shape[0]) != n:
            raise ValueError(
                f"allreduce expects one block per mesh position: leading axis "
                f"{array.shape[0]} != mesh size {n}"
            )
        if n == 1:
            return jnp.squeeze(array, axis=0)
        if op == "sum":
            # collective-precision policy seam: compressible sum payloads
            # ride the block-scaled quantized ring (heat_tpu.comm) — the
            # default "f32" policy answers None and keeps this path
            # bit-identical
            from ..comm import compressed as _cq

            mode = _cq.reduce_mode(array.dtype, _nbytes_of(array) // n)
            if mode is not None:
                return _cq.allreduce_q(array, op=op, comm=self, precision=mode)
        mesh, name = self._mesh, self.axis_name

        def make():
            def kernel(block):
                blk = jnp.squeeze(block, axis=0)
                if op == "sum":
                    return jax.lax.psum(blk, name)
                if op == "max":
                    return jax.lax.pmax(blk, name)
                if op == "min":
                    return jax.lax.pmin(blk, name)
                # prod has no reduction primitive: psum a one-hot-slotted
                # stack (the all-gather), then multiply locally — the
                # result is replication-invariant by construction
                idx = jax.lax.axis_index(name)
                stack = jnp.zeros((n,) + blk.shape, blk.dtype)
                stack = jax.lax.dynamic_update_slice_in_dim(stack, blk[None], idx, axis=0)
                return jnp.prod(jax.lax.psum(stack, name), axis=0)

            def _f(x):
                return shard_map(
                    kernel,
                    mesh=mesh,
                    in_specs=PartitionSpec(self.axis_name),
                    out_specs=PartitionSpec(),
                )(x)

            return _f

        fn = jitted(("comm.allreduce", self, op), make)
        if _tel.enabled:
            from ..comm.compressed import _account_wire

            elems = int(np.prod(array.shape[1:])) if array.ndim > 1 else 1
            _account_wire("allreduce", None, elems, n)
            with _tel.span("comm:allreduce", op=op, mesh=n):
                return fn(array)
        return fn(array)

    def ring_permute(self, array: jax.Array, shift: int = 1) -> jax.Array:
        """Rotate shards around the mesh ring: the reference's paired
        ``Send``/``Recv`` ring iteration (e.g. spatial/distance.py:261-345)
        as a single :func:`jax.lax.ppermute` inside ``shard_map``.

        Any leading-axis length is accepted (non-divisible axes go through
        the canonical zero-padding — see :meth:`permute`).
        """
        n = self.size
        return self.permute(array, [(i, (i + shift) % n) for i in range(n)])

    def permute(self, array: jax.Array, perm: Sequence[Tuple[int, int]]) -> jax.Array:
        """Arbitrary point-to-point shard exchange: the reference's tagged
        ``Isend``/``Recv`` pair schedules (e.g. resplit tile shuffle,
        dndarray.py:2870-2921) as one :func:`jax.lax.ppermute` with an
        explicit (src, dst) list.  Positions that receive nothing get
        zeros, matching ppermute semantics.

        Any axis-0 length is accepted: a non-divisible axis is first
        zero-padded to the canonical layout (:meth:`pad_to_shards`), so the
        result has the *padded* length ``padded_size(n)``; each destination
        block then carries its source's shard with ``valid_counts(n)[src]``
        real leading rows.  Callers slice with those counts (this is the
        exact analog of the reference's per-rank recv counts).
        """
        n = self.size
        if n == 1:
            return array
        orig = int(array.shape[0])
        if orig % n != 0:
            array = self.pad_to_shards(array, axis=0)
        perm = tuple((int(s), int(d)) for s, d in perm)
        # runtime twin of spmdlint SPMD101: ppermute silently drops or
        # XOR-merges shards on duplicate endpoints — fail loudly instead
        srcs = [s for s, _ in perm]
        dsts = [d for _, d in perm]
        bad = [v for v in srcs + dsts if not 0 <= v < n]
        if bad:
            raise ValueError(f"permute: index {bad[0]} out of range for {n} shards")
        if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
            raise ValueError(
                f"permute: perm {perm} is not a partial bijection "
                "(duplicate source or destination)"
            )
        mesh = self._mesh
        axis = self.axis_name

        def make():
            def _p(x):
                return shard_map(
                    lambda s: jax.lax.ppermute(s, axis, perm),
                    mesh=mesh,
                    in_specs=PartitionSpec(axis),
                    out_specs=PartitionSpec(axis),
                )(x)

            return _p

        return jitted(("comm.permute", self, perm), make)(array)

    def _split_axis_of(self, array: jax.Array) -> Optional[int]:
        """The mesh-sharded axis of a global array, or None if replicated.

        Tracers never carry a committed sharding — under a fuse/jit trace
        this reports None and callers degrade to their replicated-input
        behavior (layout is a hint; GSPMD re-derives it at compile time).
        """
        if isinstance(array, jax.core.Tracer):
            return None
        sharding = getattr(array, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is None:
            return None
        for ax, entry in enumerate(spec):
            if entry is not None:
                return ax
        return None

    def _splits_of(self, array: jax.Array) -> Tuple[Optional[int], ...]:
        """Committed splits tuple of a global array — ``splits[d]`` is the
        index of this mesh's axis named in the array's PartitionSpec at dim
        ``d``.  All-None for replicated arrays, tracers (no committed
        sharding), and arrays committed on a foreign mesh's axis names."""
        ndim = int(getattr(array, "ndim", 0) or 0)
        blank = (None,) * ndim
        if isinstance(array, jax.core.Tracer):
            return blank
        spec = getattr(getattr(array, "sharding", None), "spec", None)
        if spec is None:
            return blank
        name_to_axis = {nm: i for i, nm in enumerate(self._axis_names)}
        splits = [None] * ndim
        for d, entry in enumerate(spec):
            if entry is None or d >= ndim:
                continue
            for nm in entry if isinstance(entry, tuple) else (entry,):
                if nm in name_to_axis:
                    splits[d] = name_to_axis[nm]
        return tuple(splits)

    def bcast(self, array: jax.Array, root: int = 0) -> jax.Array:
        """Replicate mesh position ``root``'s shard everywhere: the
        reference's ``Bcast`` (communication.py:463-475).  For an array
        split along some axis, returns the root's block along that axis
        (shape = root lshape) replicated on every device; a replicated
        input is already everywhere and is returned unchanged."""
        n = self.size
        if n == 1:
            return array
        split = self._split_axis_of(array)
        if split is None:
            return array
        _, _, slices = self.chunk(tuple(array.shape), split, rank=root)
        block = array[slices]
        return _reshard(block, self.sharding(block.ndim, None))

    def scatter(self, array: jax.Array, axis: int = 0) -> jax.Array:
        """Distribute a (replicated) array so each mesh position owns one
        block along ``axis``: the reference's ``Scatter(v)``
        (communication.py:955-1010) as a reshard-to-split."""
        return self.apply_sharding(array, axis)

    def gather(self, array: jax.Array, root: int = 0, axis: int = 0) -> jax.Array:
        """Collect all shards: the reference's ``Gather(v)``
        (communication.py:1011-1068).  Single-controller SPMD has no
        privileged root — every position ends up with the full array, so
        this is ``allgather``; ``root`` is accepted for API parity."""
        del root
        return self.allgather(array, axis=axis)

    def reduce(self, array: jax.Array, op: str = "sum", root: int = 0) -> jax.Array:
        """Reduce a per-shard quantity (reference ``Reduce``,
        communication.py:552-559).  Like :meth:`gather`, the result is
        available everywhere; ``root`` kept for parity."""
        del root
        return self.allreduce(array, op=op)

    def scan(self, array: jax.Array, op: str = "sum", exclusive: bool = False) -> jax.Array:
        """Prefix-combine across mesh positions along the split axis: the
        reference's ``Scan``/``Exscan`` (communication.py:524-567), the
        engine under distributed cumulative ops.  ``array`` is a stacked
        per-position partial of shape (size, ...); returns the (exclusive)
        running combine with the same shape.

        Implemented as a real collective: blocks are sharded over the mesh,
        each position all-gathers the partials inside ``shard_map``,
        cum-combines, and keeps its own prefix — the standard XLA
        formulation of MPI ``Scan`` (there is no prefix-scan collective
        primitive; all-gather + local combine is how GSPMD lowers one).
        """
        if op not in ("sum", "prod", "max", "min"):
            raise ValueError(f"unsupported scan op {op!r}")
        n = self.size
        if int(array.shape[0]) != n:
            raise ValueError(
                f"scan expects one block per mesh position: leading axis "
                f"{array.shape[0]} != mesh size {n}"
            )

        def _cum(stack):
            if op == "sum":
                out = jnp.cumsum(stack, axis=0)
                if exclusive:
                    out = jnp.concatenate([jnp.zeros_like(out[:1]), out[:-1]], axis=0)
                return out
            if op == "prod":
                out = jnp.cumprod(stack, axis=0)
                if exclusive:
                    out = jnp.concatenate([jnp.ones_like(out[:1]), out[:-1]], axis=0)
                return out
            fn = jax.lax.cummax if op == "max" else jax.lax.cummin
            out = fn(stack, axis=0)
            if exclusive:
                # position 0 gets the operation's identity, consistent with
                # the sum (0) / prod (1) branches
                if jnp.issubdtype(stack.dtype, jnp.inexact):
                    ident = jnp.finfo(stack.dtype).min if op == "max" else jnp.finfo(stack.dtype).max
                else:
                    ident = jnp.iinfo(stack.dtype).min if op == "max" else jnp.iinfo(stack.dtype).max
                out = jnp.concatenate([jnp.full_like(out[:1], ident), out[:-1]], axis=0)
            return out

        if n == 1:
            return _cum(array)
        mesh, name = self._mesh, self.axis_name

        def make():
            def kernel(block):
                stack = jax.lax.all_gather(jnp.squeeze(block, axis=0), name)
                own = jax.lax.axis_index(name)
                return jax.lax.dynamic_slice_in_dim(_cum(stack), own, 1, axis=0)

            def _f(x):
                return shard_map(
                    kernel,
                    mesh=mesh,
                    in_specs=PartitionSpec(name),
                    out_specs=PartitionSpec(name),
                )(x)

            return _f

        return jitted(("comm.scan", self, op, exclusive), make)(array)

    def exscan(self, array: jax.Array, op: str = "sum") -> jax.Array:
        """Exclusive scan (reference ``Exscan``, communication.py:524-551)."""
        return self.scan(array, op=op, exclusive=True)


def _constrained_copy(array: jax.Array, sh: NamedSharding) -> jax.Array:
    """Best-effort reshard for non-divisible shapes via a compiled
    with_sharding_constraint.

    Measured behavior (pinned by tests/test_hlo_ragged.py): JAX refuses
    uneven shardings at program boundaries outright (device_put and
    out_shardings both raise), so GSPMD resolves this constraint to
    REPLICATED — a ragged-axis array lives one full copy per device, and
    each program boundary costs an all-gather of the padded form.
    Compute inside a program still runs sharded (GSPMD pads the axis
    internally), so FLOPs parallelize; only storage-at-rest replicates.
    Pipelines built for scale must therefore pre-pad with
    :meth:`XlaCommunication.pad_to_shards` — the padded array is
    divisible and commits genuinely sharded (the ring sort, TSQR, and
    prefix scan all do).  ``HEAT_DEBUG_RAGGED_COMMIT=1`` warns at the
    ragged ``apply_sharding`` call site (not here: this helper is also
    the multi-process reshard path for divisible arrays)."""

    from ._compile import jitted

    def make():
        def _f(x):
            return jax.lax.with_sharding_constraint(x, sh)

        return _f

    # cached per target sharding: a fresh jax.jit object per call would
    # recompile on every boundary commit
    return jitted(("constrained_copy", sh), make)(array)


def _reshard(array, sh: NamedSharding):
    """Exact relayout to ``sh``: plain :func:`jax.device_put` single-host,
    but a compiled reshard for multi-process global arrays — device_put
    cannot relayout an array that spans non-addressable devices (jax
    raises in ``_different_device_order_reshard`` for computed GSPMD
    outputs), whereas a jitted sharding constraint lowers to the proper
    cross-host collective.  Host values (numpy / single-device arrays) keep
    the device_put path everywhere.  Tracers (fuse / jit) get the in-program
    form, a plain with_sharding_constraint."""
    if isinstance(array, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(array, sh)
    if in_trace():
        return _constrained_copy(array, sh)
    if getattr(array, "sharding", None) == sh:
        # already laid out: device_put would no-op anyway but costs ~50 us
        # of dispatch per call — this check is ~0.1 us and sits on the
        # eager per-op hot path (every wrapped result passes through here)
        return array
    if (
        jax.process_count() > 1
        and isinstance(array, jax.Array)
        and len(getattr(array.sharding, "device_set", ())) > 1
    ):
        return _constrained_copy(array, sh)
    record_dispatch()
    if _tel.enabled:
        _tel.inc("comm.reshards")
        with _tel.span("comm:reshard"):
            return jax.device_put(array, sh)
    return jax.device_put(array, sh)


# ---------------------------------------------------------------------- #
# process-global default communicator                                     #
# (reference: get_comm/use_comm/sanitize_comm, communication.py:1130-1181)#
# ---------------------------------------------------------------------- #
_default_comm: Optional[XlaCommunication] = None
_platform_comms: dict = {}


def get_comm() -> XlaCommunication:
    """Retrieve the globally set default communicator
    (reference communication.py:1130-1139)."""
    global _default_comm
    if _default_comm is None:
        _default_comm = XlaCommunication()
    return _default_comm


def use_comm(comm: Optional[Communication] = None) -> None:
    """Set the default communicator (reference communication.py:1142-1160)."""
    global _default_comm
    if comm is None:
        _default_comm = XlaCommunication()
        return
    if not isinstance(comm, XlaCommunication):
        raise TypeError(f"expected an XlaCommunication, got {type(comm)}")
    _default_comm = comm


def sanitize_comm(comm: Optional[Communication]) -> XlaCommunication:
    """Validate a communicator argument, substituting the default for None
    (reference communication.py:1163-1181)."""
    if comm is None:
        return get_comm()
    if not isinstance(comm, XlaCommunication):
        raise TypeError(f"expected an XlaCommunication or None, got {type(comm)}")
    return comm


_grid_comms: dict = {}


def grid_comm(mesh_shape: Sequence[int], devices: Optional[Sequence] = None) -> XlaCommunication:
    """Communicator arranging devices on an N-D grid (cached per shape).

    ``grid_comm((2, 4))`` reshapes the default platform's devices onto a
    2×4 mesh with axis names ``("heat0", "heat1")``; arrays created with
    ``splits`` tuples over it shard both dimensions at once.  The default
    1-D communicator is untouched — grid communicators are always explicit
    objects, so every legacy layout keeps its exact mesh and cache keys.
    """
    mesh_shape = tuple(int(s) for s in mesh_shape)
    if devices is not None:
        return XlaCommunication(devices, mesh_shape=mesh_shape)
    if mesh_shape not in _grid_comms:
        _grid_comms[mesh_shape] = XlaCommunication(
            jax.devices()[: math.prod(mesh_shape)], mesh_shape=mesh_shape
        )
    return _grid_comms[mesh_shape]


def comm_for_device(platform: str) -> XlaCommunication:
    """Communicator spanning all devices of ``platform`` (cached).

    The analog of binding ``MPI_WORLD`` to a device class: on a mixed
    CPU+TPU host, ``ht.array(..., device=ht.cpu)`` lands on the CPU mesh.
    """
    if platform not in _platform_comms:
        _platform_comms[platform] = XlaCommunication(jax.devices(platform))
    return _platform_comms[platform]


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> XlaCommunication:
    """Bootstrap multi-host execution and install a global communicator.

    The multi-host analog of the reference's ``mpirun``-launched
    ``MPI_WORLD`` (communication.py:1123): each host calls this once at
    startup (arguments may be omitted on TPU pods / managed clusters,
    where JAX discovers the coordinator from the environment); afterwards
    ``get_comm()`` spans every chip of every host, with collectives riding
    ICI within a slice and DCN across slices.

    Safe to call when the distributed runtime is already up — it then just
    (re)installs the all-devices communicator.
    """
    if not distributed_is_initialized():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids,
            )
        except RuntimeError as e:
            if "must be called before" in str(e):
                raise RuntimeError(
                    "init_multihost() must run before anything touches the "
                    "XLA backend. Call it immediately after `import heat_tpu` "
                    "and before creating arrays; if your environment "
                    "initializes a backend at import (e.g. the axon plugin's "
                    "x64 workaround), set HEAT_TPU_DISABLE_X64=1."
                ) from e
            raise
    comm = XlaCommunication(jax.devices())
    use_comm(comm)
    return comm
