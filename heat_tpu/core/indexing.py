"""Index discovery and conditional selection.

Reference: heat/core/indexing.py:12-156 (``nonzero`` with global-offset
correction on the split axis; ``where`` built on it).  On global arrays the
offset correction vanishes; ``nonzero`` is data-dependent and therefore runs
on host-visible shapes (eager, like the reference).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import factories, types
from .dndarray import DNDarray
from .sanitation import sanitize_in

__all__ = ["nonzero", "where"]


def nonzero(a: DNDarray) -> DNDarray:
    """Indices of nonzero elements as an (nnz, ndim) array
    (reference indexing.py:12-97: local nonzero + split-offset add; result
    split=0)."""
    sanitize_in(a)
    idx = np.stack(np.nonzero(np.asarray(a.larray)), axis=1)
    if a.ndim == 1:
        idx = idx.reshape(-1)
    split = 0 if a.split is not None else None
    return factories.array(idx, dtype=types.int64, split=split, device=a.device, comm=a.comm)


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """3-operand select / 1-operand nonzero (reference indexing.py:98-156)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    sanitize_in(cond)
    ax = x.larray if isinstance(x, DNDarray) else jnp.asarray(x)
    ay = y.larray if isinstance(y, DNDarray) else jnp.asarray(y)
    garr = jnp.where(cond.larray != 0, ax, ay)
    garr = cond.comm.apply_sharding(garr, cond.split if garr.ndim else None)
    return DNDarray(
        garr,
        tuple(garr.shape),
        types.canonical_heat_type(garr.dtype),
        cond.split if garr.ndim else None,
        cond.device,
        cond.comm,
        cond.balanced,
    )
