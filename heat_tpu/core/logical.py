"""Logical reductions and elementwise logical ops.

Reference: heat/core/logical.py:24-350 — ``all``/``any`` are reductions with
MPI.LAND/LOR; ``allclose``/``isclose`` and the elementwise logicals route
through the generic engines.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations, types
from .sanitation import merge_keepdims
from .dndarray import DNDarray

__all__ = [
    "all",
    "allclose",
    "any",
    "isclose",
    "isfinite",
    "isinf",
    "isnan",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
]


def all(x, axis=None, out=None, keepdims=None, keepdim=None):
    """True where all elements (along axis) are nonzero
    (reference logical.py:24-86; the MPI.LAND Allreduce is XLA's)."""
    keepdims = merge_keepdims(keepdims, keepdim)
    return _operations.__reduce_op(jnp.all, x, axis, out, neutral=1, keepdims=keepdims)


def any(x, axis=None, out=None, keepdims=None, keepdim=None):
    """True where any element (along axis) is nonzero
    (reference logical.py:133-180)."""
    keepdims = merge_keepdims(keepdims, keepdim)
    return _operations.__reduce_op(jnp.any, x, axis, out, neutral=0, keepdims=keepdims)


def allclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> bool:
    """Scalar closeness verdict (reference logical.py:87-132: local allclose
    + LAND Allreduce)."""
    ax = x.larray if isinstance(x, DNDarray) else jnp.asarray(x)
    ay = y.larray if isinstance(y, DNDarray) else jnp.asarray(y)
    return bool(jnp.allclose(ax, ay, rtol=rtol, atol=atol, equal_nan=equal_nan))


def isclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False):
    """Elementwise closeness (reference logical.py:181-230)."""

    def _isclose(a, b):
        return jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)

    return _operations.__binary_op(_isclose, x, y)


def isfinite(x, out=None):
    """Elementwise finiteness test (extension; numpy semantics).
    The reference (heat 0.5.1) has no isfinite/isinf/isnan family."""
    return _operations.__local_op(jnp.isfinite, x, out, no_cast=True)


def isinf(x, out=None):
    """Elementwise +/-inf test (extension; numpy semantics)."""
    return _operations.__local_op(jnp.isinf, x, out, no_cast=True)


def isnan(x, out=None):
    """Elementwise NaN test (extension; numpy semantics)."""
    return _operations.__local_op(jnp.isnan, x, out, no_cast=True)


def isneginf(x, out=None):
    """Elementwise -inf test (extension; numpy semantics)."""
    return _operations.__local_op(jnp.isneginf, x, out, no_cast=True)


def isposinf(x, out=None):
    """Elementwise +inf test (extension; numpy semantics)."""
    return _operations.__local_op(jnp.isposinf, x, out, no_cast=True)


def logical_and(t1, t2):
    """(reference logical.py:231-260)"""
    return _operations.__binary_op(jnp.logical_and, t1, t2)


def logical_or(t1, t2):
    """(reference logical.py:261-290)"""
    return _operations.__binary_op(jnp.logical_or, t1, t2)


def logical_xor(t1, t2):
    """(reference logical.py:291-320)"""
    return _operations.__binary_op(jnp.logical_xor, t1, t2)


def logical_not(t, out=None):
    """(reference logical.py:321-350)"""
    return _operations.__local_op(jnp.logical_not, t, out, no_cast=True)


# split semantics for heat_tpu.analysis.splitflow (see core/_split_semantics.py)
from ._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {
        "reduction": ("all", "any"),
        "binary": ("isclose", "logical_and", "logical_or", "logical_xor"),
        "elementwise": (
            "isfinite", "isinf", "isnan", "isneginf", "isposinf", "logical_not",
        ),
    },
)
