"""Memory layout helpers.

Reference: heat/core/memory.py:9-76 (``copy``, ``sanitize_memory_layout``).
XLA manages physical layout itself (tiling for the MXU/VPU makes C-vs-F
stride order meaningless on TPU), so ``sanitize_memory_layout`` validates
the argument for API parity and returns the array unchanged for both
orders — documented divergence: ``order='F'`` does not change the stride
pattern of the backing buffer.
"""

from __future__ import annotations

__all__ = ["copy", "sanitize_memory_layout"]


def copy(x):
    """Physical copy of a DNDarray (reference memory.py:9-27)."""
    from .dndarray import DNDarray
    import jax.numpy as jnp

    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
    return DNDarray(
        jnp.array(x.larray, copy=True),
        x.gshape,
        x.dtype,
        x.split,
        x.device,
        x.comm,
        x.balanced,
    )


def sanitize_memory_layout(x, order: str = "C"):
    """Validate a memory-order flag (reference memory.py:29-76).

    On TPU, XLA chooses physical tilings; the order flag is accepted for
    API compatibility but does not alter the buffer.
    """
    if order not in ("C", "F"):
        raise ValueError(f"invalid memory layout {order!r}, expected 'C' or 'F'")
    return x
