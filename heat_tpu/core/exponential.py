"""Exponential and logarithmic elementwise maps.

Reference: heat/core/exponential.py:8-222 — all ``__local_op`` maps; float
promotion of exact types happens in the engine.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations

__all__ = ["exp", "expm1", "exp2", "log", "log2", "log10", "log1p", "sqrt"]


def exp(x, out=None):
    """e**x (reference exponential.py:8-38)."""
    return _operations.__local_op(jnp.exp, x, out)


def expm1(x, out=None):
    """e**x - 1 (reference exponential.py:39-69)."""
    return _operations.__local_op(jnp.expm1, x, out)


def exp2(x, out=None):
    """2**x (reference exponential.py:70-100)."""
    return _operations.__local_op(jnp.exp2, x, out)


def log(x, out=None):
    """Natural logarithm (reference exponential.py:101-131)."""
    return _operations.__local_op(jnp.log, x, out)


def log2(x, out=None):
    """Base-2 logarithm (reference exponential.py:132-162)."""
    return _operations.__local_op(jnp.log2, x, out)


def log10(x, out=None):
    """Base-10 logarithm (reference exponential.py:163-192)."""
    return _operations.__local_op(jnp.log10, x, out)


def log1p(x, out=None):
    """log(1 + x) (reference exponential.py:193-207)."""
    return _operations.__local_op(jnp.log1p, x, out)


def sqrt(x, out=None):
    """Square root (reference exponential.py:208-222)."""
    return _operations.__local_op(jnp.sqrt, x, out)


# split semantics for heat_tpu.analysis.splitflow (see core/_split_semantics.py)
from ._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {"elementwise": ("exp", "expm1", "exp2", "log", "log2", "log10", "log1p", "sqrt")},
)
