"""The DNDarray: a global n-D array sharded over a TPU device mesh.

Reference: heat/core/dndarray.py:53-3962 — there, a ``DNDarray`` is an SPMD
illusion: every MPI process stores only its slab (``lshape``) of the global
array (``gshape``), split along at most one axis, and ~130 methods hand-roll
the communication to maintain the illusion.

Here the illusion is real: the backing store **is** a single global
:class:`jax.Array` whose shards live distributed across the mesh with a
:class:`~jax.sharding.NamedSharding`; ``split`` records which axis is
sharded.  Every operation is expressed on the global array and XLA/GSPMD
inserts the collectives — so the reference's per-method communication logic
(e.g. the 250-line distributed ``__getitem__``, dndarray.py:1476-1726)
collapses into plain ``jnp`` indexing plus split bookkeeping.  Sharding in
this model is a *performance annotation*: a mis-placed shard costs time,
never correctness — the exact inversion of the MPI design, where layout
errors corrupt results.

Design invariants:

* the at-rest backing store (``self._buffer``) is a global jax.Array whose
  split axis is **canonically padded**: an axis of true length ``n`` over a
  ``p``-device mesh is stored zero-padded to ``p * ceil(n/p)`` and committed
  SHARDED, so per-device memory is O(n/p) for *any* n — the TPU-first
  equivalent of the reference invariant that each rank's torch tensor
  matches its ``chunk()`` slice (reference communication.py:82-137,
  dndarray.py:93).  Divisible axes (and replicated arrays) store exactly
  ``gshape``;
* ``self.larray`` is the true-shape view: ``larray.shape == gshape``
  always.  For padded arrays it is a lazily-cached slice — cheap inside
  compiled programs, but committing it at a program boundary materializes
  a ragged (hence replicated) array, so scale paths consume ``_buffer``;
* pad rows hold *unspecified* values after ops (elementwise garbage-in/
  garbage-out is confined to the pad): every non-elementwise consumer
  must go through ``larray``/masking.  The op wrappers in
  ``_operations.py`` do this centrally;
* ``split ∈ {None, 0..ndim-1}``; ``None`` = replicated on all devices;
* shard layout is *canonical* (GSPMD ceil-division): arrays are always
  balanced, so ``balance_``/``redistribute_`` (reference dndarray.py:900,
  2560) are no-ops kept for API parity.
"""

from __future__ import annotations

import math
import warnings
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import types
from ._compile import jitted
from ._jax_compat import shard_map
from ._tracing import require_concrete
from .communication import Communication, sanitize_comm
from .devices import Device
from .stride_tricks import sanitize_axis

__all__ = ["DNDarray", "LocalIndex"]

#: Minimum element count of the operand before array-key indexing along the
#: split axis routes through the bounded-memory ring gather/scatter
#: (:mod:`heat_tpu.parallel.take`) instead of the GSPMD gather (which
#: REPLICATES the operand for data-dependent cross-shard indexing).  Small
#: operands keep the plain jnp path — the ring's p rounds only pay off once
#: per-device memory is at stake.  Override with HEAT_TPU_RING_INDEX_MIN.
import os as _os

_RING_INDEX_MIN = int(_os.environ.get("HEAT_TPU_RING_INDEX_MIN", str(1 << 22)))


def _fit_index_array(k, n: int):
    """Normalize an integer index array for axis length ``n`` so jax's
    documented clamp (gather) / drop (scatter) semantics hold WITHOUT the
    silent int32 truncation jax applies to wide keys (an int64 index of
    2**32+3 otherwise reads/writes row 3), and without the OverflowError
    narrow keys (int8 on an axis longer than their range) trigger.

    Values are mapped into int32-safe sentinels that jax post-processes to
    its own semantics: OOB-high → ``n`` (gather clamps to n-1, scatter
    drops), OOB-low → ``-(n+1)`` (one wrap later still ``-1`` < 0: gather
    clamps to 0, scatter drops).  Both sentinels fit int32 for every
    ``n < 2**31`` (``n`` ≤ int32 max, ``-(n+1)`` ≥ int32 min), i.e. for
    every axis jax itself can index with int32 — there is no unguarded
    large-``n`` regime (the r4 advisor found the previous ``2n``-based
    sentinel silently skipped normalization for n ≥ 2**30).  Host numpy
    arrays normalize for free;
    device arrays pay two elementwise ops only for risky dtypes.
    """
    if n <= 0 or n >= 2**31:
        return k  # n itself no longer fits int32; jax must gather in int64
    if isinstance(k, np.ndarray):
        if np.issubdtype(k.dtype, np.unsignedinteger):
            return np.minimum(k, np.asarray(n, np.uint64)).astype(np.int32)
        kk = k.astype(np.int64)
        return np.where(kk >= n, n, np.where(kk < -n, -(n + 1), kk)).astype(np.int32)
    dt = k.dtype
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        if np.dtype(dt).itemsize <= 2:
            return k  # uint8/16 fit int32; jax clamps/drops them natively
        return jnp.minimum(k, jnp.asarray(n, dt)).astype(jnp.int32)
    if np.dtype(dt).itemsize <= 2:
        return k.astype(jnp.int32)  # widen int8/16 past their own range
    if np.dtype(dt).itemsize == 4:
        return k  # int32 cannot out-range int32
    kk = jnp.where(k >= n, n, jnp.where(k < -n, -(n + 1), k))
    return kk.astype(jnp.int32)


class LocalIndex:
    """Indexing proxy over the raw backing array
    (reference dndarray.py:37-50, exposed as ``x.lloc``).

    In the single-controller model the "local" array is the global one; this
    proxy indexes it directly, without split bookkeeping, and supports
    assignment (functionally, via ``.at[].set``).
    """

    __slots__ = ("__obj",)

    def __init__(self, obj: "DNDarray"):
        self.__obj = obj

    def __getitem__(self, key):
        return self.__obj.larray[key]

    def __setitem__(self, key, value):
        arr = self.__obj.larray.at[key].set(jnp.asarray(value, self.__obj.larray.dtype))
        self.__obj.larray = arr


class DNDarray:
    """Distributed N-Dimensional array over a JAX device mesh.

    Parameters mirror the reference constructor (dndarray.py:79-93):

    array : jax.Array
        The **global** array (reference stores the local chunk instead).
        On a ragged split axis this may be either the true-length array
        (it will be padded to the at-rest form) or an already canonically
        padded buffer (``comm.padded_size`` long on the split axis, pad
        rows arbitrary) — anything else raises ``ValueError``.
    gshape : tuple of int
        TRUE global shape (``gshape[split]`` is the real length even when
        ``array`` arrives padded); equals ``array.shape`` otherwise.
    dtype : heat type
        Element type (:mod:`heat_tpu.core.types`).
    split : int or None
        Sharded axis; None = replicated.
    device : Device
        Platform the mesh lives on.
    comm : Communication
        The device-mesh communicator.
    balanced : bool
        Kept for API parity; canonical GSPMD layout is always balanced.
    """

    def __init__(
        self,
        array: jax.Array,
        gshape: Tuple[int, ...],
        dtype,
        split: Optional[int],
        device: Device,
        comm: Communication,
        balanced: bool = True,
    ):
        self.__gshape = tuple(int(s) for s in gshape)
        self.__dtype = dtype
        self.__device = device
        self.__comm = comm
        ndim = len(self.__gshape)
        if isinstance(split, (tuple, list)):
            # splits-tuple spelling: splits[d] = mesh axis sharding dim d.
            # The legacy `split` int becomes the exact compat view (the dim
            # mesh axis 0 shards — lossless on a 1-D mesh).
            splits = comm.normalize_splits(ndim, split)
            split = comm.split_view(splits)
        else:
            if split is not None and self.__gshape:
                if not -ndim <= split < ndim:
                    raise ValueError(
                        f"split axis {split} out of range for {ndim}-dimensional "
                        f"shape {self.__gshape}"
                    )
                split = int(split) % ndim  # normalize negatives only
            splits = (
                comm.normalize_splits(ndim, split)
                if (self.__gshape or split is None)
                else (None,) * ndim
            )
        self.__split = split
        self.__splits = splits
        self.__balanced = True if balanced is None else bool(balanced)
        self.__true_view = None
        self.__halo_prev = None
        self.__halo_next = None
        self.__halo_size = 0
        self.__array = self.__commit(array)

    def __commit(self, array) -> jax.Array:
        """Normalize ``array`` to the at-rest invariant: every ragged
        sharded dim (gshape[d] not divisible by its mesh axis) is
        zero-padded to the canonical length and committed sharded.  Accepts
        either the true-shape array or an already-padded buffer, per dim;
        divisible/replicated arrays pass through untouched (sharding them
        stays the caller's job, as before)."""
        splits = self.__splits
        if not self.__gshape or all(g is None for g in splits):
            return array
        comm = self.__comm
        needs_pad = False
        for d, g in enumerate(splits):
            if g is None:
                continue
            n = self.__gshape[d]
            pn = comm.padded_size(n, mesh_axis=g)
            if pn == n:
                continue
            have = int(array.shape[d])
            if have == pn:
                continue  # this dim is already at rest
            if have != n:
                raise ValueError(
                    f"backing array axis {d} has length {have}; expected the "
                    f"true length {n} or the padded length {pn} for gshape "
                    f"{self.__gshape} over mesh {comm.mesh_shape}"
                )
            needs_pad = True
        if not needs_pad:
            return array
        return comm.pad_to_shards(array, splits=splits)

    # ------------------------------------------------------------------ #
    # metadata properties (reference dndarray.py:95-360)                  #
    # ------------------------------------------------------------------ #
    @property
    def balanced(self) -> bool:
        """Always True under the canonical GSPMD layout
        (reference dndarray.py:95-106 tracks this lazily)."""
        return self.__balanced

    @property
    def comm(self) -> Communication:
        return self.__comm

    @comm.setter
    def comm(self, comm):
        self.__comm = sanitize_comm(comm)

    @property
    def device(self) -> Device:
        return self.__device

    @property
    def dtype(self):
        return self.__dtype

    @property
    def gshape(self) -> Tuple[int, ...]:
        """Global shape (reference dndarray.py:186)."""
        return self.__gshape

    @property
    def shape(self) -> Tuple[int, ...]:
        """Global shape — numpy-compatible alias (reference dndarray.py:286)."""
        return self.__gshape

    @property
    def larray(self) -> jax.Array:
        """The global array at its TRUE shape (``larray.shape == gshape``).

        Semantic shift from the reference (dndarray.py:123-135): there this
        is the rank-local torch tensor; here it is the *global* device array
        whose shards are distributed — the natural "local" object of
        single-controller SPMD.  When the at-rest buffer is padded (ragged
        split axis), this is a cached slice of the buffer; committing that
        slice at a program boundary materializes a ragged array (GSPMD
        replicates those), so scale pipelines consume :attr:`_buffer`.
        """
        arr = self.__array
        splits = self.__splits
        if not self.__gshape or all(g is None for g in splits):
            return arr
        padded_dims = tuple(
            d
            for d, g in enumerate(splits)
            if g is not None and int(arr.shape[d]) != self.__gshape[d]
        )
        if not padded_dims:
            return arr
        if self.__true_view is None:
            view = arr
            for d in padded_dims:
                view = self.__comm.unpad(view, self.__gshape[d], d)
            self.__true_view = view
        return self.__true_view

    @larray.setter
    def larray(self, array: jax.Array):
        """Rebind the backing data.  ``array`` is interpreted at its TRUE
        shape (adopted as the new gshape); a ragged split axis is re-padded
        to the at-rest invariant."""
        if tuple(array.shape) != self.__gshape:
            self.__gshape = tuple(int(s) for s in array.shape)
        self.__array = self.__commit(array)
        self._invalidate_halos()

    @property
    def _buffer(self) -> jax.Array:
        """The at-rest backing buffer: the split axis canonically padded to
        ``comm.padded_size(gshape[split])`` (== gshape for divisible axes).
        Pad-row values are unspecified; mask or :meth:`larray` before any
        non-elementwise use."""
        return self.__array

    @property
    def padshape(self) -> Tuple[int, ...]:
        """Shape of the at-rest buffer (gshape with the split axis padded)."""
        return tuple(int(s) for s in self.__array.shape)

    def _zeroed_buffer(self) -> jax.Array:
        """The at-rest buffer with pad rows forced to zero — still padded
        and sharded (no boundary crossing).  For consumers that assume the
        canonical zero fill (halo exchange, SUMMA's contraction-axis
        operands).  Zeroes every padded sharded dim, so grid layouts with
        two ragged dims come back fully masked."""
        arr = self.__array
        splits = self.__splits
        if not self.__gshape or all(g is None for g in splits):
            return arr
        dims = tuple(
            (d, self.__gshape[d])
            for d, g in enumerate(splits)
            if g is not None and int(arr.shape[d]) != self.__gshape[d]
        )
        if not dims:
            return arr
        comm = self.__comm

        def make():
            def _z(x):
                mask = None
                for d, n in dims:
                    m = jax.lax.broadcasted_iota(jnp.int32, x.shape, d) < n
                    mask = m if mask is None else mask & m
                return jnp.where(mask, x, jnp.zeros((), x.dtype))

            return _z

        key = ("dnd.zeropad", comm, splits, dims, tuple(int(s) for s in arr.shape))
        return jitted(key, make)(arr)

    @property
    def lloc(self) -> LocalIndex:
        """Raw (split-unaware) indexer (reference dndarray.py:259)."""
        return LocalIndex(self)

    @property
    def lshape(self) -> Tuple[int, ...]:
        """Shape of the calling process's first shard (reference
        dndarray.py:205: the calling rank's chunk).  Single-host this is
        mesh position 0; on multihost (init_multihost) it is the first
        position owned by THIS process."""
        _, lshape, _ = self.__comm.chunk(
            self.__gshape, self._layout, rank=self.__comm.local_position()
        )
        return lshape

    @property
    def lshape_map(self) -> np.ndarray:
        """(size, ndim) table of every mesh position's shard shape
        (reference ``create_lshape_map``, dndarray.py:1117 — built there via
        Allreduce; here computed from the canonical layout)."""
        return self.create_lshape_map()

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def size(self) -> int:
        """Total number of elements (reference ``gnumel``)."""
        return int(np.prod(self.__gshape)) if self.__gshape else 1

    @property
    def gnumel(self) -> int:
        return self.size

    @property
    def lnumel(self) -> int:
        """Elements in the calling process's first shard (reference
        dndarray.py:231)."""
        return int(np.prod(self.lshape)) if self.lshape else 1

    @property
    def nbytes(self) -> int:
        """Global memory footprint in bytes (reference ``gnbytes``)."""
        return self.size * np.dtype(self.__dtype._np_type).itemsize

    @property
    def gnbytes(self) -> int:
        return self.nbytes

    @property
    def lnbytes(self) -> int:
        return self.lnumel * np.dtype(self.__dtype._np_type).itemsize

    @property
    def itemsize(self) -> int:
        return np.dtype(self.__dtype._np_type).itemsize

    @property
    def split(self) -> Optional[int]:
        """The sharded axis, or None when replicated (reference dndarray.py:321).

        On an N-D grid comm this is the exact *compat view* of
        :attr:`splits`: the array dim mesh axis 0 shards.  Every layout a
        1-D mesh can express round-trips through it losslessly."""
        return self.__split

    @property
    def splits(self) -> Tuple[Optional[int], ...]:
        """Mesh-axis layout tuple: ``splits[d]`` is the mesh axis sharding
        array dim ``d`` (None = unsharded).  ``(0, 1)`` on a 2-D grid comm
        is the SUMMA block layout — dim 0 over mesh rows, dim 1 over mesh
        columns.  On the default 1-D mesh this is the one-hot spelling of
        :attr:`split`."""
        return self.__splits

    @property
    def _layout(self):
        """The layout in the spelling comm methods historically expect:
        the legacy int on a 1-D mesh (exact), the splits tuple on a grid."""
        if getattr(self.__comm, "mesh_ndim", 1) > 1:
            return self.__splits
        return self.__split

    @property
    def stride(self) -> Tuple[int, ...]:
        """C-order element strides (reference dndarray.py:333 — torch-style)."""
        strides = []
        acc = 1
        for s in reversed(self.__gshape):
            strides.append(acc)
            acc *= s
        return tuple(reversed(strides))

    @property
    def strides(self) -> Tuple[int, ...]:
        """C-order byte strides (reference dndarray.py:345 — numpy-style)."""
        return tuple(s * self.itemsize for s in self.stride)

    @property
    def T(self) -> "DNDarray":
        from .linalg import basics

        return basics.transpose(self, None)

    @property
    def real(self) -> "DNDarray":
        return self

    @property
    def imag(self) -> "DNDarray":
        from . import factories

        return factories.zeros_like(self)

    @property
    def sharding(self):
        """The semantic NamedSharding of this array over its comm's mesh
        (TPU-native introspection; no reference analog).

        Derived from (comm, split) rather than read off the backing array:
        on a single-device comm the backing array may carry a plain
        SingleDeviceSharding (the apply_sharding fast path skips the
        device_put), but the NamedSharding contract — ``.spec`` access,
        mesh introspection — holds either way."""
        return self.__comm.sharding(self.ndim, self._layout)

    # ------------------------------------------------------------------ #
    # conversion / export                                                #
    # ------------------------------------------------------------------ #
    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        """Cast to ``dtype`` (reference dndarray.py:540-575)."""
        dtype = types.canonical_heat_type(dtype)
        casted = self.__array.astype(dtype.jax_type())
        if copy:
            return DNDarray(
                casted, self.shape, dtype, self._layout, self.device, self.comm, self.balanced
            )
        self.__array = casted
        self.__dtype = dtype
        self._invalidate_halos()
        return self

    def numpy(self) -> np.ndarray:
        """Gather to a host numpy array (reference dndarray.py: ``numpy`` —
        there an implicit resplit(None) + .numpy())."""
        require_concrete(".numpy()")
        return np.asarray(self.larray)

    def copy(self) -> "DNDarray":
        """An independent copy of this array (reference dndarray.py: ``copy``
        → memory.copy)."""
        from . import memory

        return memory.copy(self)

    def is_distributed(self) -> bool:
        """True when data lives split across more than one mesh position
        (reference dndarray.py:1771-1779)."""
        return self.__split is not None and self.__comm.is_distributed()

    @property
    def numdims(self) -> int:
        """Deprecated alias of :attr:`ndim` (reference dndarray.py:245)."""
        warnings.warn("numdims is deprecated, use ndim instead", DeprecationWarning, stacklevel=2)
        return self.ndim

    def save(self, path: str, *args, **kwargs) -> None:
        """Save to HDF5/NetCDF/CSV by file extension (reference
        dndarray.py:3104)."""
        require_concrete(".save()")
        from . import io

        io.save(self, path, *args, **kwargs)

    def save_hdf5(self, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
        """Save to an HDF5 dataset (reference dndarray.py:3132)."""
        require_concrete(".save_hdf5()")
        from . import io

        io.save_hdf5(self, path, dataset, mode, **kwargs)

    def save_netcdf(self, path: str, variable: str, mode: str = "w", **kwargs) -> None:
        """Save to a NetCDF variable (reference dndarray.py:3162)."""
        require_concrete(".save_netcdf()")
        from . import io

        io.save_netcdf(self, path, variable, mode, **kwargs)

    def __array__(self, dtype=None):
        require_concrete("np.asarray()")
        arr = np.asarray(self.larray)
        return arr.astype(dtype) if dtype is not None else arr

    def tolist(self, keepsplit: bool = False) -> list:
        """Nested python lists of the global data (reference dndarray.py:3718)."""
        require_concrete(".tolist()")
        return np.asarray(self.larray).tolist()

    def item(self):
        """The single element of a size-1 array as a python scalar
        (reference dndarray.py:1754)."""
        require_concrete(".item()")
        if self.size != 1:
            raise ValueError("only one-element DNDarrays can be converted to Python scalars")
        return self.larray.reshape(()).item()

    def __bool__(self) -> bool:
        require_concrete("bool()")
        return bool(self.item())

    def __int__(self) -> int:
        require_concrete("int()")
        return int(self.item())

    def __float__(self) -> float:
        require_concrete("float()")
        return float(self.item())

    def __complex__(self) -> complex:
        require_concrete("complex()")
        return complex(self.item())

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.__gshape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------------ #
    # device / layout movement                                           #
    # ------------------------------------------------------------------ #
    def cpu(self) -> "DNDarray":
        """Move to the CPU mesh (reference dndarray.py:1006)."""
        return self.to_device("cpu")

    def to_device(self, device) -> "DNDarray":
        """Move the array to another platform's mesh (no reference analog as
        a general method; subsumes the reference's ``cpu()``/gpu pattern)."""
        from .devices import sanitize_device
        from .communication import comm_for_device

        device = sanitize_device(device)
        if device == self.__device:
            return self
        comm = comm_for_device(device.platform)
        arr = jax.device_put(np.asarray(self.larray), comm.sharding(self.ndim, None))
        arr = comm.apply_sharding(arr, self.__split)
        return DNDarray(arr, self.shape, self.dtype, self.split, device, comm, True)

    def create_lshape_map(self, force_check: bool = False) -> np.ndarray:
        """Table of all shard shapes (reference dndarray.py:1117-1160)."""
        size = self.__comm.size
        ndim = max(self.ndim, 1)
        out = np.zeros((size, ndim), dtype=np.int64)
        for r in range(size):
            _, lshape, _ = self.__comm.chunk(self.__gshape, self._layout, rank=r)
            out[r, : len(lshape)] = lshape
        return out

    def is_balanced(self, force_check: bool = False) -> bool:
        """Canonical layout ⇒ always balanced (reference dndarray.py:1781-1806
        needs an Allreduce to find out)."""
        return True

    def balance_(self) -> None:
        """No-op: the canonical GSPMD layout is always balanced
        (reference dndarray.py:900-1004 moves data with Send/Recv chains)."""
        self.__balanced = True

    def redistribute_(self, lshape_map=None, target_map=None) -> None:
        """Arbitrary per-rank shard sizes are not representable in XLA's
        sharding model; the canonical equal layout is maintained by the
        compiler (reference dndarray.py:2560-2746 implements a pairwise
        Isend/Recv shuffle).

        A ``target_map`` equal to the canonical layout is accepted as the
        no-op it is; any *other* map asks for a layout this framework
        cannot represent, and raises rather than silently returning the
        wrong distribution (see docs/migration.md)."""
        if target_map is None:
            return
        target = np.asarray(target_map)
        canonical = self.create_lshape_map()
        if target.size != canonical.size:
            raise ValueError(
                f"target_map must have shape {canonical.shape} "
                f"(one lshape row per shard), got {target.shape}"
            )
        # a flat (size,) map for a 1-D array is the natural spelling of
        # the same (size, 1) canonical table — normalize before comparing
        target = target.reshape(canonical.shape)
        if np.array_equal(target, canonical):
            return  # already the layout we maintain
        raise NotImplementedError(
            "redistribute_: non-canonical per-rank shard sizes are not "
            "representable in XLA's GSPMD sharding model; heat_tpu always "
            "maintains the canonical equal-chunk layout "
            f"({canonical.tolist()}). Requested {target.tolist()}. "
            "See docs/migration.md for the layout contract."
        )

    def resplit_(self, axis: Optional[int] = None) -> "DNDarray":
        """In-place re-shard along ``axis`` (reference dndarray.py:2801-2921:
        split→None = Allgatherv, None→split = local slicing, split→split =
        tile shuffle; here one XLA reshard covers all three).

        ``axis`` also accepts a splits tuple: on a grid comm this is the
        native spelling (e.g. ``(0, 1)`` = block layout), routed through
        the 2-D redistribution planner; on a 1-D mesh it collapses to its
        exact ``split`` compat int first."""
        comm = self.__comm
        grid = getattr(comm, "mesh_ndim", 1) > 1
        if isinstance(axis, (tuple, list)) or grid:
            if not isinstance(axis, (tuple, list)):
                axis = sanitize_axis(self.shape, axis)
            splits = comm.normalize_splits(self.ndim, axis)
            if not grid:
                axis = comm.split_view(splits)  # exact on 1-D: legacy path below
            else:
                if splits == self.__splits:
                    return self
                true = self.larray
                self.__splits = splits
                self.__split = comm.split_view(splits)
                self.__array = comm.commit_split(true, splits)
                self.__balanced = True
                self._invalidate_halos()
                return self
        axis = sanitize_axis(self.shape, axis)
        if axis == self.__split:
            return self
        true = self.larray
        self.__split = axis
        self.__splits = comm.normalize_splits(self.ndim, axis)
        # commit_split pads+shards ragged target axes in one step
        self.__array = self.__comm.commit_split(true, axis)
        self.__balanced = True
        self._invalidate_halos()
        return self

    def resplit(self, axis: Optional[int] = None) -> "DNDarray":
        """Out-of-place resplit (reference manipulations.py:2969)."""
        from . import manipulations

        return manipulations.resplit(self, axis)

    # ------------------------------------------------------------------ #
    # halo exchange (reference dndarray.py:390-483)                       #
    # ------------------------------------------------------------------ #
    def get_halo(self, halo_size: int) -> None:
        """Fetch every shard's neighbor boundary strips via one ppermute
        pair (:func:`heat_tpu.parallel.halo_exchange`).

        The reference posts Isend/Irecv pairs with prev/next ranks and
        stores the received strips per rank (dndarray.py:390-463).  Here
        :attr:`halo_prev` / :attr:`halo_next` become *global sharded*
        arrays whose split axis has length ``size * halo_size``: position
        p's block holds the strip it received from its predecessor /
        successor.  Strips reaching past the global edges are zero-filled
        (the reference leaves them absent — a per-rank None; equal-shard
        layouts need a uniform shape, and zeros are the natural stencil
        boundary).
        """
        if not isinstance(halo_size, int):
            raise TypeError(f"halo_size needs to be an integer, but was {type(halo_size)}")
        if halo_size < 0:
            raise ValueError(f"halo_size needs to be a non-negative integer, but was {halo_size}")
        if self.__split is None or halo_size == 0:
            self._invalidate_halos()
            return
        from ..parallel.primitives import halo_exchange

        arr = self._zeroed_buffer()
        if self.__split != 0:
            arr = jnp.moveaxis(arr, self.__split, 0)
        # halo_exchange validates halo_size <= shard_width (raising before
        # any state here changes)
        prev, nxt = halo_exchange(arr, halo_size, comm=self.__comm)
        if self.__split != 0:
            prev = jnp.moveaxis(prev, 0, self.__split)
            nxt = jnp.moveaxis(nxt, 0, self.__split)
        self.__halo_prev = prev
        self.__halo_next = nxt
        self.__halo_size = halo_size

    def _invalidate_halos(self) -> None:
        """Drop cached derived views (halo strips, the true-shape slice);
        called whenever the backing array or layout changes."""
        self.__true_view = None
        self.__halo_prev = None
        self.__halo_next = None
        self.__halo_size = 0

    @property
    def halo_prev(self):
        return self.__halo_prev

    @property
    def halo_next(self):
        return self.__halo_next

    @property
    def array_with_halos(self) -> jax.Array:
        """Every shard extended by its neighbor strips
        (reference dndarray.py:363-365, 465-483).

        A global sharded array whose split axis has length
        ``size * (shard_width + 2 * halo_size)``: position p's block is
        ``[prev strip | shard p (zero-padded to shard_width) | next
        strip]``.  Stencil consumers map over the blocks and keep rows
        ``[halo_size, halo_size + shard_width)``, then unpad with
        ``comm.valid_counts`` — see tests/test_extended_dndarray.py for
        the pattern.  Without halos (or replicated) this is the plain
        backing array.
        """
        h = self.__halo_size
        if self.__split is None or not h:
            return self.larray  # no halos: the plain (true-shape) array
        comm = self.__comm
        split = self.__split
        arr = self._zeroed_buffer()
        prev, nxt = self.__halo_prev, self.__halo_next
        if split != 0:
            arr = jnp.moveaxis(arr, split, 0)
            prev = jnp.moveaxis(prev, split, 0)
            nxt = jnp.moveaxis(nxt, split, 0)
        arr = comm.pad_to_shards(arr, axis=0)
        from jax.sharding import PartitionSpec

        from ._compile import jitted

        def make():
            spec = PartitionSpec(comm.axis_name)

            def kernel(p, b, nx):
                return jnp.concatenate([p, b, nx], axis=0)

            def _f(p, b, nx):
                return shard_map(
                    kernel,
                    mesh=comm.mesh,
                    in_specs=(spec, spec, spec),
                    out_specs=spec,
                )(p, b, nx)

            return _f

        out = jitted(("dnd.halo_concat", comm), make)(prev, arr, nxt)
        if split != 0:
            out = jnp.moveaxis(out, 0, split)
        return out

    # ------------------------------------------------------------------ #
    # indexing (reference dndarray.py:1476-1726, 3190-3339)               #
    # ------------------------------------------------------------------ #
    def __process_key(self, key):
        """Convert DNDarray (and numpy-style list) keys to jax arrays, pass
        everything else through.  Lists are advanced-index arrays in
        numpy/reference semantics (dndarray.py:1476) but rejected raw by
        jax, so they are wrapped here.

        Plain integer keys are bounds-checked on the host: jnp's ``.at``
        semantics silently CLIP out-of-range indices, so without the check
        ``x[99] = 1`` on a 5-row array would no-op instead of raising the
        numpy/reference ``IndexError``."""

        def pre(k):
            # one-time normalization (lists/DNDarrays convert exactly once,
            # before both the dim counting and the per-dim pass)
            if isinstance(k, DNDarray):
                return k.larray
            if isinstance(k, list):
                return np.asarray(k)
            return k

        def one(k, dim):
            if isinstance(k, np.ndarray) and k.ndim == 0 and np.issubdtype(k.dtype, np.integer):
                # numpy semantics: a host 0-d integer array key behaves like
                # the scalar int — route it through the same bounds check
                # (jnp's .at clips silently otherwise).  Device (jnp) 0-d
                # keys pass through: converting them would force a blocking
                # device→host sync per index.
                k = int(k)
            if isinstance(k, (int, np.integer)) and not isinstance(k, (bool, np.bool_)):
                if dim is not None and dim < self.ndim:
                    n = self.__gshape[dim]
                    if not -n <= k < n:
                        raise IndexError(
                            f"index {k} is out of bounds for axis {dim} with size {n}"
                        )
                return k
            if isinstance(k, np.ndarray):
                if k.size == 0:  # numpy: a[[]] selects nothing, not float64
                    k = k.astype(np.int32)
                if (
                    np.issubdtype(k.dtype, np.integer)
                    and dim is not None
                    and dim < self.ndim
                ):
                    k = _fit_index_array(k, self.__gshape[dim])
                return jnp.asarray(k)
            if (
                isinstance(k, (jnp.ndarray, jax.Array))
                and jnp.issubdtype(k.dtype, jnp.integer)
                and dim is not None
                and dim < self.ndim
            ):
                return _fit_index_array(k, self.__gshape[dim])
            return k

        def consumed(k):
            # how many array dims key element k consumes (keys are
            # pre-normalized: no lists or DNDarrays reach here)
            if k is None or isinstance(k, (bool, np.bool_)):
                return 0  # newaxis / scalar-bool mask: adds an axis instead
            if isinstance(k, (np.ndarray, jnp.ndarray)) and k.dtype == bool:
                return k.ndim
            return 1

        if isinstance(key, tuple):
            key = tuple(pre(k) for k in key)
            dims: List[Optional[int]] = []
            # `Ellipsis in key` would run elementwise == on array keys
            if any(k is Ellipsis for k in key):
                e = next(i for i, k in enumerate(key) if k is Ellipsis)
                dim = 0
                for k in key[:e]:
                    dims.append(dim if consumed(k) == 1 else None)
                    dim += consumed(k)
                dims.append(None)  # the ellipsis itself
                tail = key[e + 1 :]
                dim = self.ndim - sum(consumed(k) for k in tail)
                for k in tail:
                    dims.append(dim if consumed(k) == 1 else None)
                    dim += consumed(k)
            else:
                dim = 0
                for k in key:
                    dims.append(dim if consumed(k) == 1 else None)
                    dim += consumed(k)
            return tuple(one(k, d) for k, d in zip(key, dims))
        return one(pre(key), 0)

    def __result_split(self, key, result_ndim: int) -> Optional[int]:
        """Split bookkeeping for indexing results.

        For BASIC keys (ints, slices, None, Ellipsis, scalar bools) the
        output axis of the split is computed exactly: slices preserve it,
        ints drop axes before it, None/bool insert axes, and an Ellipsis
        expands to the full slices it stands for.  Advanced (array) keys
        keep the nearest-shardable-axis heuristic — a performance hint
        only, since layout never affects values (pinned by
        tests/test_setitem_matrix.py)."""
        if self.__split is None or result_ndim == 0:
            return None
        split = self.__split
        keyt = key if isinstance(key, tuple) else (key,)

        def is_basic(k):
            return (
                k is Ellipsis
                or k is None
                or isinstance(k, (bool, np.bool_, slice))
                or (isinstance(k, (int, np.integer)) and not isinstance(k, (bool, np.bool_)))
            )

        if all(is_basic(k) for k in keyt):
            consumed = sum(
                1
                for k in keyt
                if isinstance(k, (int, np.integer, slice))
                and not isinstance(k, (bool, np.bool_))
            )
            expanded: List = []
            for k in keyt:
                if k is Ellipsis:
                    expanded.extend([slice(None)] * (self.ndim - consumed))
                else:
                    expanded.append(k)
            dim = 0  # input axis cursor
            out = 0  # output axis cursor
            for k in expanded:
                if k is None or isinstance(k, (bool, np.bool_)):
                    out += 1  # newaxis / scalar-bool mask inserts an axis
                    continue
                if isinstance(k, slice):
                    if dim == split:
                        return min(out, result_ndim - 1)
                    dim += 1
                    out += 1
                else:  # integer: drops this input axis
                    if dim == split:
                        # split axis consumed: nearest shardable axis
                        return min(out, result_ndim - 1)
                    dim += 1
            # key exhausted before the split axis: the rest map one-to-one
            return min(out + (split - dim), result_ndim - 1)

        # advanced keys: nearest-shardable heuristic (as before)
        dim = 0
        dropped_before = 0
        split_key = slice(None)
        for k in keyt:
            if k is Ellipsis:
                return min(split, result_ndim - 1)
            if k is None:
                continue
            if dim == split:
                split_key = k
                break
            if isinstance(k, (int, np.integer)):
                dropped_before += 1
            dim += 1
        if isinstance(split_key, (int, np.integer)):
            return min(max(split - dropped_before, 0), result_ndim - 1)
        return min(split - dropped_before, result_ndim - 1)

    def __ring_index_plan(self, jkey):
        """Detect the scale-sensitive fancy-indexing pattern: ONE 1-D
        integer-array key on the split axis, every other axis untouched,
        on a distributed operand big enough that GSPMD's replicate-the-
        operand gather would hurt (≥ ``_RING_INDEX_MIN`` elements).
        Returns the index array, or None for the plain jnp path."""
        s = self.__split
        if s is None or not self.__comm.is_distributed():
            return None
        if self.size < _RING_INDEX_MIN:
            return None

        def is_idx(k):
            return (
                isinstance(k, (jnp.ndarray, jax.Array))
                and k.ndim == 1
                and k.shape[0] > 0
                and jnp.issubdtype(k.dtype, jnp.integer)
            )

        if isinstance(jkey, tuple):
            if len(jkey) > self.ndim:
                return None
            idx = None
            for d, k in enumerate(jkey):
                if isinstance(k, slice):
                    if k != slice(None):
                        return None
                elif is_idx(k):
                    if d != s or idx is not None:
                        return None
                    idx = k
                else:
                    return None
            return idx
        return jkey if s == 0 and is_idx(jkey) else None

    def __ring_getitem(self, idx) -> "DNDarray":
        """Fancy gather along the split axis via the bounded-memory ring
        (reference dndarray.py:1476-1726 exchanges per-rank key
        intersections; GSPMD would replicate the operand instead —
        parallel/take.py).  The operand's at-rest buffer feeds the ring
        directly; the result commits padded+sharded at rest."""
        from ..parallel.take import ring_take

        s, comm = self.__split, self.__comm
        n = self.__gshape[s]
        m = int(idx.shape[0])
        buf = self.__array
        if s != 0:
            buf = jnp.moveaxis(buf, s, 0)
        # oob='clip': jnp gather clamp semantics (wrap negatives, clip to
        # range).  The key arrives already sentinel-mapped by
        # _fit_index_array (__process_key); ring_take's own _sanitize_index
        # composes with those sentinels (n stays a drop, -(n+1) wraps to -1
        # and still clamps/drops) — two cheap passes on the index vector,
        # each safe alone
        out = ring_take(buf, idx, comm=comm, n=n, padded_out=True, oob="clip")
        if s != 0:
            out = jnp.moveaxis(out, 0, s)
        gshape = self.__gshape[:s] + (m,) + self.__gshape[s + 1 :]
        return DNDarray(out, gshape, self.__dtype, s, self.__device, comm, True)

    def __ring_setitem(self, idx, value) -> None:
        """Fancy scatter along the split axis via the ring dual
        (reference dndarray.py:3190-3339).  Out-of-range indices drop and
        duplicate destinations resolve in unspecified order — the same
        contract as jnp's ``.at[].set`` scatter.  The new buffer replaces
        the at-rest store without any boundary materialization."""
        from ..parallel.take import ring_put

        s, comm = self.__split, self.__comm
        n = self.__gshape[s]
        m = int(idx.shape[0])
        vshape = self.__gshape[:s] + (m,) + self.__gshape[s + 1 :]
        if (
            isinstance(value, DNDarray)
            and value.split == s
            and value.gshape == vshape
            and value._buffer.dtype == self.__array.dtype
        ):
            # aligned at-rest operand (e.g. the gather round-trip): its
            # padded buffer feeds the ring directly — pad rows align with
            # the masked pad queries and are never written.  Going through
            # .larray here would materialize the ragged view REPLICATED at
            # the boundary, the exact spike this path exists to avoid.
            value = value._buffer
        else:
            if isinstance(value, DNDarray):
                value = value.larray
            value = jnp.asarray(value, dtype=self.__array.dtype)
            # numpy setitem layout: the advanced axis stays in place (axis s)
            value = jnp.broadcast_to(value, vshape)
        buf = self.__array
        if s != 0:
            value = jnp.moveaxis(value, s, 0)
            buf = jnp.moveaxis(buf, s, 0)
        out = ring_put(n, idx, value, comm=comm, base=buf, padded_out=True)
        if s != 0:
            out = jnp.moveaxis(out, 0, s)
        self.__array = out
        self._invalidate_halos()

    def __getitem__(self, key) -> "DNDarray":
        """Global-semantics indexing (reference dndarray.py:1476-1726 — there
        each rank intersects the key with its chunk; here plain jnp indexing
        on the global array, with big split-axis array keys routed through
        the bounded-memory ring gather)."""
        jkey = self.__process_key(key)
        ridx = self.__ring_index_plan(jkey)
        if ridx is not None:
            return self.__ring_getitem(ridx)
        result = self.larray[jkey]
        if result.ndim == 0:
            return DNDarray(
                result, (), self.__dtype, None, self.__device, self.__comm, True
            )
        split = self.__result_split(jkey, result.ndim)
        result = self.__comm.apply_sharding(result, split)
        return DNDarray(
            result, tuple(result.shape), self.__dtype, split, self.__device, self.__comm, True
        )

    def __setitem__(self, key, value):
        """Global-semantics assignment (reference dndarray.py:3190-3339),
        expressed functionally via ``.at[key].set`` and a rebind."""
        jkey = self.__process_key(key)
        ridx = self.__ring_index_plan(jkey)
        if ridx is not None:
            self.__ring_setitem(ridx, value)
            return
        if isinstance(value, DNDarray):
            value = value.larray
        value = jnp.asarray(value, dtype=self.__array.dtype)
        updated = self.larray.at[jkey].set(value)
        if updated.shape == self.__array.shape:
            updated = self.__comm.apply_sharding(updated, self.__split)
        self.__array = self.__commit(updated)
        self._invalidate_halos()

    def fill_diagonal(self, value) -> "DNDarray":
        """Fill the main diagonal in place (reference dndarray.py:1161)."""
        if self.ndim != 2:
            raise ValueError("fill_diagonal requires a 2-D DNDarray")
        n = min(self.shape)
        idx = jnp.arange(n)
        self.__array = self.__comm.apply_sharding(
            self.__array.at[idx, idx].set(jnp.asarray(value, self.__array.dtype)), self.__split
        )
        self._invalidate_halos()
        return self

    # ------------------------------------------------------------------ #
    # string representations                                             #
    # ------------------------------------------------------------------ #
    def __repr__(self) -> str:
        require_concrete("repr()")
        from . import printing

        return printing.__str__(self)

    def __str__(self) -> str:
        require_concrete("print()/str()")
        from . import printing

        return printing.__str__(self)

    # ------------------------------------------------------------------ #
    # operator / method delegation (reference dndarray.py — ~130 methods) #
    # All following methods delegate to the ops modules, mirroring the    #
    # reference's delegation pattern.                                     #
    # ------------------------------------------------------------------ #
    # -- arithmetics ---------------------------------------------------- #
    def __add__(self, other):
        from . import arithmetics

        return arithmetics.add(self, other)

    __radd__ = __add__

    def __iadd__(self, other):
        from . import arithmetics

        res = arithmetics.add(self, other)
        if tuple(res.shape) != self.__gshape:
            # numpy semantics: in-place ops may not grow the array
            raise ValueError(
                f"non-broadcastable output operand with shape {self.__gshape} "
                f"doesn't match the broadcast shape {tuple(res.shape)}"
            )
        self.__array, self.__dtype, self.__split = res._buffer, res.dtype, res.split
        self.__splits = res.splits
        self._invalidate_halos()
        return self

    def __sub__(self, other):
        from . import arithmetics

        return arithmetics.sub(self, other)

    def __rsub__(self, other):
        from . import arithmetics

        return arithmetics.sub(other, self)

    def __mul__(self, other):
        from . import arithmetics

        return arithmetics.mul(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        from . import arithmetics

        return arithmetics.div(self, other)

    def __rtruediv__(self, other):
        from . import arithmetics

        return arithmetics.div(other, self)

    def __floordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(self, other)

    def __rfloordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(other, self)

    def __mod__(self, other):
        from . import arithmetics

        return arithmetics.mod(self, other)

    def __rmod__(self, other):
        from . import arithmetics

        return arithmetics.mod(other, self)

    def __pow__(self, other):
        from . import arithmetics

        return arithmetics.pow(self, other)

    def __rpow__(self, other):
        from . import arithmetics

        return arithmetics.pow(other, self)

    def __matmul__(self, other):
        from .linalg import basics

        return basics.matmul(self, other)

    def __neg__(self):
        from . import arithmetics

        return arithmetics.mul(self, -1)

    def __pos__(self):
        return self

    def __abs__(self):
        from . import rounding

        return rounding.abs(self)

    def __invert__(self):
        from . import arithmetics

        return arithmetics.invert(self)

    def __lshift__(self, other):
        from . import arithmetics

        return arithmetics.left_shift(self, other)

    def __rshift__(self, other):
        from . import arithmetics

        return arithmetics.right_shift(self, other)

    def __and__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_and(self, other)

    def __or__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_or(self, other)

    def __xor__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_xor(self, other)

    # -- relational ----------------------------------------------------- #
    def __eq__(self, other):
        from . import relational

        return relational.eq(self, other)

    def __ne__(self, other):
        from . import relational

        return relational.ne(self, other)

    def __lt__(self, other):
        from . import relational

        return relational.lt(self, other)

    def __le__(self, other):
        from . import relational

        return relational.le(self, other)

    def __gt__(self, other):
        from . import relational

        return relational.gt(self, other)

    def __ge__(self, other):
        from . import relational

        return relational.ge(self, other)

    __hash__ = None  # mutable container, like the reference

    # -- named arithmetics methods -------------------------------------- #
    def add(self, other):
        from . import arithmetics

        return arithmetics.add(self, other)

    def sub(self, other):
        from . import arithmetics

        return arithmetics.sub(self, other)

    def mul(self, other):
        from . import arithmetics

        return arithmetics.mul(self, other)

    def div(self, other):
        from . import arithmetics

        return arithmetics.div(self, other)

    def fmod(self, other):
        from . import arithmetics

        return arithmetics.fmod(self, other)

    def pow(self, other):
        from . import arithmetics

        return arithmetics.pow(self, other)

    def prod(self, axis=None, out=None, keepdims=None, keepdim=None):
        from . import arithmetics

        return arithmetics.prod(self, axis, out, keepdims, keepdim)

    def sum(self, axis=None, out=None, keepdims=None, keepdim=None):
        from . import arithmetics

        return arithmetics.sum(self, axis, out, keepdims, keepdim)

    def cumsum(self, axis=0):
        from . import arithmetics

        return arithmetics.cumsum(self, axis)

    def cumprod(self, axis=0):
        from . import arithmetics

        return arithmetics.cumprod(self, axis)

    # -- exponential / trig / rounding ---------------------------------- #
    def exp(self, out=None):
        from . import exponential

        return exponential.exp(self, out)

    def expm1(self, out=None):
        from . import exponential

        return exponential.expm1(self, out)

    def exp2(self, out=None):
        from . import exponential

        return exponential.exp2(self, out)

    def log(self, out=None):
        from . import exponential

        return exponential.log(self, out)

    def log2(self, out=None):
        from . import exponential

        return exponential.log2(self, out)

    def log10(self, out=None):
        from . import exponential

        return exponential.log10(self, out)

    def log1p(self, out=None):
        from . import exponential

        return exponential.log1p(self, out)

    def sqrt(self, out=None):
        from . import exponential

        return exponential.sqrt(self, out)

    def sin(self, out=None):
        from . import trigonometrics

        return trigonometrics.sin(self, out)

    def cos(self, out=None):
        from . import trigonometrics

        return trigonometrics.cos(self, out)

    def tan(self, out=None):
        from . import trigonometrics

        return trigonometrics.tan(self, out)

    def sinh(self, out=None):
        from . import trigonometrics

        return trigonometrics.sinh(self, out)

    def cosh(self, out=None):
        from . import trigonometrics

        return trigonometrics.cosh(self, out)

    def tanh(self, out=None):
        from . import trigonometrics

        return trigonometrics.tanh(self, out)

    def arcsin(self, out=None):
        from . import trigonometrics

        return trigonometrics.arcsin(self, out)

    def arccos(self, out=None):
        from . import trigonometrics

        return trigonometrics.arccos(self, out)

    def arctan(self, out=None):
        from . import trigonometrics

        return trigonometrics.arctan(self, out)

    def abs(self, out=None, dtype=None):
        from . import rounding

        return rounding.abs(self, out, dtype)

    def absolute(self, out=None, dtype=None):
        """Alias of :meth:`abs` (reference heat/core/dndarray.py:506)."""
        return self.abs(out, dtype)

    def fabs(self, out=None):
        from . import rounding

        return rounding.fabs(self, out)

    def ceil(self, out=None):
        from . import rounding

        return rounding.ceil(self, out)

    def floor(self, out=None):
        from . import rounding

        return rounding.floor(self, out)

    def clip(self, a_min, a_max, out=None):
        from . import rounding

        return rounding.clip(self, a_min, a_max, out)

    def modf(self, out=None):
        from . import rounding

        return rounding.modf(self, out)

    def round(self, decimals=0, out=None, dtype=None):
        from . import rounding

        return rounding.round(self, decimals, out, dtype)

    def trunc(self, out=None):
        from . import rounding

        return rounding.trunc(self, out)

    # -- logical -------------------------------------------------------- #
    def all(self, axis=None, out=None, keepdims=None, keepdim=None):
        from . import logical

        return logical.all(self, axis, out, keepdims, keepdim)

    def any(self, axis=None, out=None, keepdims=None, keepdim=None):
        from . import logical

        return logical.any(self, axis, out, keepdims, keepdim)

    def allclose(self, other, rtol=1e-05, atol=1e-08, equal_nan=False):
        from . import logical

        return logical.allclose(self, other, rtol, atol, equal_nan)

    def isclose(self, other, rtol=1e-05, atol=1e-08, equal_nan=False):
        from . import logical

        return logical.isclose(self, other, rtol, atol, equal_nan)

    # -- statistics ----------------------------------------------------- #
    def argmax(self, axis=None, out=None, **kwargs):
        from . import statistics

        return statistics.argmax(self, axis, out, **kwargs)

    def argmin(self, axis=None, out=None, **kwargs):
        from . import statistics

        return statistics.argmin(self, axis, out, **kwargs)

    def max(self, axis=None, out=None, keepdims=None, keepdim=None):
        from . import statistics

        return statistics.max(self, axis, out, keepdims, keepdim)

    def min(self, axis=None, out=None, keepdims=None, keepdim=None):
        from . import statistics

        return statistics.min(self, axis, out, keepdims, keepdim)

    def mean(self, axis=None, keepdims=None, keepdim=None):
        from . import statistics

        return statistics.mean(self, axis, keepdims=keepdims, keepdim=keepdim)

    def median(self, axis=None, keepdim=None, keepdims=None):
        from . import statistics

        return statistics.median(self, axis, keepdim, keepdims=keepdims)

    def var(self, axis=None, ddof=0, **kwargs):
        from . import statistics

        return statistics.var(self, axis, ddof=ddof, **kwargs)

    def std(self, axis=None, ddof=0, **kwargs):
        from . import statistics

        return statistics.std(self, axis, ddof=ddof, **kwargs)

    def skew(self, axis=None, unbiased=True):
        from . import statistics

        return statistics.skew(self, axis, unbiased)

    def kurtosis(self, axis=None, unbiased=True, Fischer=True):
        from . import statistics

        return statistics.kurtosis(self, axis, unbiased, Fischer)

    def average(self, axis=None, weights=None, returned=False):
        from . import statistics

        return statistics.average(self, axis=axis, weights=weights, returned=returned)

    def percentile(self, q, axis=None, out=None, interpolation="linear", keepdims=False):
        from . import statistics

        return statistics.percentile(self, q, axis, out, interpolation, keepdims)

    # -- manipulations -------------------------------------------------- #
    def expand_dims(self, axis):
        from . import manipulations

        return manipulations.expand_dims(self, axis)

    def flatten(self):
        from . import manipulations

        return manipulations.flatten(self)

    def ravel(self):
        from . import manipulations

        return manipulations.flatten(self)

    def reshape(self, *shape, **kwargs):
        from . import manipulations

        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return manipulations.reshape(self, shape, **kwargs)

    def squeeze(self, axis=None):
        from . import manipulations

        return manipulations.squeeze(self, axis)

    def unique(self, sorted=False, return_inverse=False, axis=None):
        from . import manipulations

        return manipulations.unique(self, sorted, return_inverse, axis)

    def flip(self, axis=None):
        from . import manipulations

        return manipulations.flip(self, axis)

    def sort(self, axis=-1, descending=False, out=None):
        from . import manipulations

        return manipulations.sort(self, axis, descending, out)

    def repeat(self, repeats, axis=None):
        from . import manipulations

        return manipulations.repeat(self, repeats, axis)

    def nonzero(self):
        from . import indexing

        return indexing.nonzero(self)

    # -- linalg --------------------------------------------------------- #
    def transpose(self, axes=None):
        from .linalg import basics

        return basics.transpose(self, axes)

    def tril(self, k=0):
        from .linalg import basics

        return basics.tril(self, k)

    def triu(self, k=0):
        from .linalg import basics

        return basics.triu(self, k)

    def dot(self, other, out=None):
        from .linalg import basics

        return basics.dot(self, other, out=out)

    def matmul(self, other, out=None, precision=None):
        from .linalg import basics

        return basics.matmul(self, other, out=out, precision=precision)

    def qr(self, tiles_per_proc=1, calc_q=True, overwrite_a=False):
        from .linalg.qr import qr as _qr

        return _qr(self, tiles_per_proc, calc_q, overwrite_a)

    def norm(self):
        from .linalg import basics

        return basics.norm(self)
