"""heat_tpu core: the distributed tensor layer
(reference: heat/core/__init__.py)."""

from .communication import *
from .devices import *
from . import types
from .types import *
from .constants import *
from .stride_tricks import *
from .memory import *
from . import sanitation
from .sanitation import *
from .dndarray import *
from . import fuse as _fuse_module
from .fuse import *
from . import autoshard as _autoshard_module
from .autoshard import *
from . import factories
from .factories import *
from . import arithmetics
from .arithmetics import *
from . import relational
from .relational import *
from . import logical
from .logical import *
from . import exponential
from .exponential import *
from . import trigonometrics
from .trigonometrics import *
from . import rounding
from .rounding import *
from . import statistics
from .statistics import *
from . import manipulations
from .manipulations import *
from . import indexing
from .indexing import *
from . import printing
from .printing import get_printoptions, set_printoptions
from . import random
from . import io
from .io import *
from . import checkpoint
from .checkpoint import *
from . import tiling
from .tiling import *
from .base import *
from . import linalg
from .linalg import *
from ..version import __version__
