"""Elementwise arithmetic, bit ops, and sum/prod/cum reductions.

Reference: heat/core/arithmetics.py:42-924.  Every function routes through
the generic engine in :mod:`_operations` exactly as the reference does; the
``diff`` neighbor exchange (reference :286-370, manual Send/Recv of boundary
slices along the split axis) is a single global ``jnp.diff`` here, with XLA
providing the shard-boundary halo.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from . import _operations
from . import types
from .dndarray import DNDarray
from .sanitation import merge_keepdims
from .stride_tricks import sanitize_axis

__all__ = [
    "add",
    "bitwise_and",
    "bitwise_not",
    "bitwise_or",
    "bitwise_xor",
    "cumprod",
    "cumproduct",
    "cumsum",
    "diff",
    "div",
    "divide",
    "floordiv",
    "floor_divide",
    "fmod",
    "invert",
    "left_shift",
    "mod",
    "remainder",
    "mul",
    "multiply",
    "pow",
    "power",
    "prod",
    "right_shift",
    "sub",
    "subtract",
    "sum",
]


def add(t1, t2, out=None):
    """Elementwise addition (reference arithmetics.py:42-87)."""
    return _operations.__binary_op(jnp.add, t1, t2, out)


def sub(t1, t2, out=None):
    """Elementwise subtraction (reference arithmetics.py:766-811)."""
    return _operations.__binary_op(jnp.subtract, t1, t2, out)


subtract = sub


def mul(t1, t2, out=None):
    """Elementwise multiplication (reference arithmetics.py:572-616)."""
    return _operations.__binary_op(jnp.multiply, t1, t2, out)


multiply = mul


def div(t1, t2, out=None):
    """Elementwise true division (reference arithmetics.py:345-390).
    Promotes to floating like the reference."""

    def _truediv(a, b):
        return jnp.true_divide(a, b)

    return _operations.__binary_op(_truediv, t1, t2, out)


divide = div


def floordiv(t1, t2, out=None):
    """Elementwise floor division (reference arithmetics.py:432-477)."""
    return _operations.__binary_op(jnp.floor_divide, t1, t2, out)


floor_divide = floordiv


def fmod(t1, t2, out=None):
    """Elementwise C-semantics remainder (reference arithmetics.py:478-523)."""
    return _operations.__binary_op(jnp.fmod, t1, t2, out)


def remainder(t1, t2, out=None):
    """Element-wise ``t1 % t2`` with Python sign semantics
    (reference arithmetics.py:719-760; ``mod`` is its alias there)."""
    return _operations.__binary_op(jnp.mod, t1, t2, out)


def mod(t1, t2, out=None):
    """Elementwise python-semantics modulo (reference arithmetics.py:524-571)."""
    return _operations.__binary_op(jnp.mod, t1, t2, out)


def pow(t1, t2, out=None):
    """Elementwise power (reference arithmetics.py:617-662)."""
    return _operations.__binary_op(jnp.power, t1, t2, out)


power = pow


def bitwise_and(t1, t2, out=None):
    """Elementwise AND for integers/booleans (reference arithmetics.py:88-140)."""
    _check_int(t1, t2, "bitwise_and")
    return _operations.__binary_op(jnp.bitwise_and, t1, t2, out)


def bitwise_or(t1, t2, out=None):
    """(reference arithmetics.py:141-193)"""
    _check_int(t1, t2, "bitwise_or")
    return _operations.__binary_op(jnp.bitwise_or, t1, t2, out)


def bitwise_xor(t1, t2, out=None):
    """(reference arithmetics.py:194-246)"""
    _check_int(t1, t2, "bitwise_xor")
    return _operations.__binary_op(jnp.bitwise_xor, t1, t2, out)


def invert(t, out=None):
    """Elementwise bitwise NOT (reference arithmetics.py:247-285)."""
    if isinstance(t, DNDarray) and types.heat_type_is_inexact(t.dtype):
        raise TypeError(f"Operation is not supported for float types, got {t.dtype.__name__}")
    return _operations.__local_op(jnp.invert, t, out, no_cast=True)


bitwise_not = invert


def left_shift(t1, t2, out=None):
    """Elementwise left shift (reference arithmetics.py:663-714)."""
    _check_int_shift(t1, "left_shift")
    return _operations.__binary_op(jnp.left_shift, t1, t2, out)


def right_shift(t1, t2, out=None):
    """Elementwise right shift (reference arithmetics.py:715-765)."""
    _check_int_shift(t1, "right_shift")
    return _operations.__binary_op(jnp.right_shift, t1, t2, out)


def _check_int(t1, t2, name):
    for t in (t1, t2):
        if isinstance(t, DNDarray) and types.heat_type_is_inexact(t.dtype):
            raise TypeError(f"Operation {name} not supported for float types, got {t.dtype.__name__}")
        if isinstance(t, float):
            raise TypeError(f"Operation {name} not supported for float scalars")


def _check_int_shift(t1, name):
    if isinstance(t1, DNDarray) and types.heat_type_is_inexact(t1.dtype):
        raise TypeError(f"Operation {name} not supported for float types, got {t1.dtype.__name__}")


def cumsum(a, axis, dtype=None, out=None):
    """Cumulative sum along ``axis`` (reference arithmetics.py:cumsum via
    __cum_op, _operations.py:173; the cross-shard Exscan is XLA's scan)."""
    return _operations.__cum_op(jnp.cumsum, a, axis, out, dtype)


def cumprod(a, axis, dtype=None, out=None):
    """Cumulative product (reference arithmetics.py:cumprod)."""
    return _operations.__cum_op(jnp.cumprod, a, axis, out, dtype)


cumproduct = cumprod


def diff(a, n: int = 1, axis: int = -1, prepend=None, append=None):
    """n-th discrete difference along ``axis``
    (reference arithmetics.py:286-344 — hand-written neighbor Send/Recv;
    here one global jnp.diff)."""
    if n == 0:
        return a
    if n < 0:
        raise ValueError(f"diff requires that n be a positive number, got {n}")
    from .sanitation import sanitize_in

    sanitize_in(a)
    axis = sanitize_axis(a.shape, axis)

    def _edge(v):
        if v is None:
            return None
        arr = v.larray if isinstance(v, DNDarray) else jnp.asarray(v)
        if arr.ndim == 0:
            eshape = list(a.shape)
            eshape[axis] = 1
            arr = jnp.broadcast_to(arr, eshape)
        return arr

    edges = {"prepend": _edge(prepend), "append": _edge(append)}
    edges = {k: v for k, v in edges.items() if v is not None}
    # numpy semantics: result dtype promotes across the input and both edges
    rtype = jnp.result_type(a.larray, *edges.values())
    kw = {k: v.astype(rtype) for k, v in edges.items()}
    result = jnp.diff(a.larray.astype(rtype), n=n, axis=axis, **kw)
    result = a.comm.apply_sharding(result, a.split)
    return DNDarray(
        result,
        tuple(result.shape),
        types.canonical_heat_type(result.dtype),
        a.split,
        a.device,
        a.comm,
        a.balanced,
    )


def sum(x, axis=None, out=None, keepdims=None, keepdim=None):
    """Sum reduction (reference arithmetics.py:878-924; the cross-split
    Allreduce of _operations.py:425-429 is compiler-inserted here).
    ``keepdim`` is the reference spelling; ``keepdims`` the numpy one."""
    keepdims = merge_keepdims(keepdims, keepdim)
    return _operations.__reduce_op(jnp.sum, x, axis, out, neutral=0, keepdims=keepdims)


def prod(x, axis=None, out=None, keepdims=None, keepdim=None):
    """Product reduction (reference arithmetics.py:787-833)."""
    keepdims = merge_keepdims(keepdims, keepdim)
    return _operations.__reduce_op(jnp.prod, x, axis, out, neutral=1, keepdims=keepdims)


# ----------------------------------------------------------------------- #
# split semantics (transfer functions for heat_tpu.analysis.splitflow —    #
# declared here so the registry cannot drift from the ops it describes)    #
# ----------------------------------------------------------------------- #
from ._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {
        "binary": (
            "add", "sub", "mul", "div", "floordiv", "fmod", "remainder",
            "mod", "pow", "left_shift", "right_shift", "bitwise_and",
            "bitwise_or", "bitwise_xor",
        ),
        "elementwise": ("invert",),
        "reduction": ("sum", "prod"),
        "cumulative": ("cumsum", "cumprod"),
    },
)
