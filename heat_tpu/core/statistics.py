"""Statistical reductions and order statistics.

Reference: heat/core/statistics.py:41-1705.  The reference's hardest
machinery — custom MPI reduction ops over packed (value‖index) buffers for
``argmax``/``argmin`` (:1124-1168) and Bennett-style pairwise moment merging
for ``mean``/``var``/``skew``/``kurtosis`` (:870-945) — is exactly what XLA's
reduction lowering performs natively (variadic reduce with value/index
pairs; tree reductions over shards), so every function here is its jnp
formulation plus the reference's split/keepdims/ddof semantics.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import _operations, factories, types
from ._compile import jitted
from .dndarray import DNDarray
from .fuse import fuse
from .sanitation import merge_keepdims, sanitize_in
from .stride_tricks import sanitize_axis

__all__ = [
    "argmax",
    "argmin",
    "average",
    "bincount",
    "cov",
    "histc",
    "histogram",
    "kurtosis",
    "max",
    "maximum",
    "mean",
    "median",
    "min",
    "minimum",
    "percentile",
    "skew",
    "std",
    "var",
]


def _argmax_op(a, axis=None, keepdims=False):
    return jnp.argmax(a, axis=axis, keepdims=keepdims)


def _argmin_op(a, axis=None, keepdims=False):
    return jnp.argmin(a, axis=axis, keepdims=keepdims)


def argmax(x, axis=None, out=None, keepdims=None, keepdim=None, **kwargs):
    """Index of the global maximum (reference statistics.py:41-112; the
    MPI_ARGMAX packed-buffer reduction :1124-1168 is XLA's variadic
    reduce)."""
    keepdims = merge_keepdims(keepdims, keepdim)
    return _operations.__reduce_op(
        _argmax_op, x, axis, out, keepdims=keepdims, dtype=types.int64
    )


def argmin(x, axis=None, out=None, keepdims=None, keepdim=None, **kwargs):
    """Index of the global minimum (reference statistics.py:113-185)."""
    keepdims = merge_keepdims(keepdims, keepdim)
    return _operations.__reduce_op(
        _argmin_op, x, axis, out, keepdims=keepdims, dtype=types.int64
    )


def average(x: DNDarray, axis=None, weights: Optional[DNDarray] = None, returned: bool = False):
    """Weighted average (reference statistics.py:186-319)."""
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if weights is None:
        result = mean(x, axis)
        if returned:
            n = x.size if axis is None else np.prod([x.shape[a] for a in (axis if isinstance(axis, tuple) else (axis,))])
            wsum = factories.full_like(result, float(n))
            return result, wsum
        return result
    w = weights.larray if isinstance(weights, DNDarray) else jnp.asarray(weights)
    arr = x.larray
    if w.ndim == 1 and axis is not None and not isinstance(axis, tuple) and w.shape[0] == arr.shape[axis]:
        bshape = [1] * arr.ndim
        bshape[axis] = -1
        wb = w.reshape(bshape)
    elif w.shape == arr.shape:
        wb = w
    else:
        raise ValueError("weights differ in shape from a and do not match the axis length")
    wsum = jnp.sum(wb * jnp.ones_like(arr), axis=axis)
    if bool(jnp.any(wsum == 0)):
        raise ZeroDivisionError("Weights sum to zero, can't be normalized")
    res = jnp.sum(arr * wb, axis=axis) / wsum
    result = _wrap_reduced(x, res, axis)
    if returned:
        wret = _wrap_reduced(x, jnp.broadcast_to(wsum, res.shape), axis)
        return result, wret
    return result


def _wrap_reduced(x: DNDarray, garr, axis, keepdims: bool = False) -> DNDarray:
    split = x.split
    if split is not None:
        axes = (
            tuple(range(x.ndim))
            if axis is None
            else ((axis,) if isinstance(axis, int) else tuple(axis))
        )
        if split in axes:
            split = None
        elif not keepdims:
            split = split - sum(1 for a in axes if a < split)
    if garr.ndim == 0:
        split = None
    garr = x.comm.apply_sharding(garr, split)
    return DNDarray(
        garr,
        tuple(garr.shape),
        types.canonical_heat_type(garr.dtype),
        split,
        x.device,
        x.comm,
        True,
    )


def _compressed_moment(x: DNDarray, axis, keepdims: bool, kind: str, ddof: int = 0):
    """Collective-precision policy seam for mean/var/std whose axes cover
    the split: local partials + the block-scaled quantized ring in one
    program (:mod:`heat_tpu.comm.compressed`), instead of GSPMD's exact
    all-reduce.  Returns the replicated result, or None when the policy
    (or the geometry) keeps the exact path.  var/std combine the first
    moment exactly and compress only the centered second moment (see
    :func:`heat_tpu.comm.compressed.moments_q`)."""
    if x.split is None or x.comm.size <= 1 or types.heat_type_is_exact(x.dtype):
        return None
    axes = (
        tuple(range(x.ndim))
        if axis is None
        else ((axis,) if isinstance(axis, int) else tuple(axis))
    )
    if x.split not in axes:
        return None
    from ..comm import compressed as _cq

    buf = x._buffer
    out_elems = 1
    for d, s in enumerate(x.gshape):
        if d not in axes:
            out_elems *= int(s)
    payload = out_elems * 4
    mode = _cq.reduce_mode(buf.dtype, payload)
    if mode is None:
        return None
    true_n = 1
    for a in axes:
        true_n *= int(x.gshape[a])
    if kind == "mean":
        return _cq.reduce_q(
            buf, comm=x.comm, split=x.split, axes=axes, keepdims=keepdims,
            mode=mode, mean_n=true_n, out_dtype=buf.dtype,
        )
    return _cq.moments_q(
        buf, comm=x.comm, split=x.split, axes=axes, keepdims=keepdims,
        mode=mode, true_n=true_n, split_valid=int(x.gshape[x.split]),
        ddof=ddof, finalize=kind, out_dtype=buf.dtype,
    )


def bincount(x: DNDarray, weights=None, minlength: int = 0) -> DNDarray:
    """Occurrence counts of non-negative ints (reference statistics.py:320-385).

    Data-dependent output size ⇒ computed with a fixed global length
    (max+1), the XLA-friendly formulation of a distributed histogram."""
    sanitize_in(x)
    arr = x.larray
    if arr.ndim != 1:
        raise ValueError("bincount expects a 1-d array")
    length = int(builtins_max(int(jnp.max(arr)) + 1 if arr.size else 0, minlength))
    w = weights.larray if isinstance(weights, DNDarray) else weights
    res = jnp.bincount(arr, weights=w, length=length)
    dtype = types.int64 if w is None else types.canonical_heat_type(res.dtype)
    return factories.array(res, dtype=dtype, split=None, device=x.device, comm=x.comm)


import builtins as _builtins

builtins_max = _builtins.max
builtins_min = _builtins.min


def cov(m: DNDarray, y: Optional[DNDarray] = None, rowvar: bool = True, bias: bool = False, ddof=None) -> DNDarray:
    """Covariance matrix estimate (reference statistics.py:386-459)."""
    sanitize_in(m)
    if ddof is not None and not isinstance(ddof, int):
        raise TypeError("ddof must be integer")
    arr = m.larray
    if arr.ndim > 2:
        raise ValueError("m has more than 2 dimensions")
    if arr.ndim == 1:
        arr = arr.reshape(1, -1)
    if not rowvar and arr.shape[0] != 1:
        arr = arr.T
    if y is not None:
        sanitize_in(y)
        ya = y.larray
        if ya.ndim > 2:
            raise ValueError("y has more than 2 dimensions")
        if ya.ndim == 1:
            ya = ya.reshape(1, -1)
        if not rowvar and ya.shape[0] != 1:
            ya = ya.T
        arr = jnp.concatenate([arr, ya], axis=0)
    if ddof is None:
        ddof = 0 if bias else 1
    n = arr.shape[1]
    avg = jnp.mean(arr, axis=1, keepdims=True)
    fact = n - ddof
    xc = arr - avg
    res = (xc @ xc.T) / fact
    return factories.array(res, split=m.split if m.split in (0, 1) else None, device=m.device, comm=m.comm)


def histc(input: DNDarray, bins: int = 100, min: float = 0.0, max: float = 0.0, out=None) -> DNDarray:
    """torch-style histogram (reference statistics.py:460-520)."""
    sanitize_in(input)
    arr = input.larray
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        lo, hi = float(jnp.min(arr)), float(jnp.max(arr))
    hist, _ = jnp.histogram(arr, bins=bins, range=(lo, hi))
    result = factories.array(
        hist.astype(input.dtype.jax_type()), dtype=input.dtype, device=input.device, comm=input.comm
    )
    if out is not None:
        out.larray = result.larray
        return out
    return result


def histogram(a: DNDarray, bins: int = 10, range=None, normed=None, weights=None, density=None):
    """numpy-style histogram (reference statistics.py:521-565)."""
    sanitize_in(a)
    hist, edges = jnp.histogram(
        a.larray,
        bins=bins,
        range=range,
        weights=weights.larray if isinstance(weights, DNDarray) else weights,
        density=density,
    )
    return (
        factories.array(hist, device=a.device, comm=a.comm),
        factories.array(edges, device=a.device, comm=a.comm),
    )


def _kurtosis_program(x: DNDarray, axis, unbiased: bool, Fischer: bool) -> DNDarray:
    arr = x.larray.astype(jnp.float64 if x.dtype is types.float64 else jnp.float32)
    mu = jnp.mean(arr, axis=axis, keepdims=True)
    diff = arr - mu
    m2 = jnp.mean(diff**2, axis=axis)
    m4 = jnp.mean(diff**4, axis=axis)
    n = arr.size if axis is None else arr.shape[axis]
    g2 = m4 / jnp.where(m2 == 0, 1, m2**2)
    if unbiased:
        g2 = ((n - 1) / ((n - 2) * (n - 3))) * ((n + 1) * g2 - 3 * (n - 1)) + 3
    res = g2 - 3 if Fischer else g2
    return _wrap_reduced(x, res, axis)


_fused_kurtosis = fuse(_kurtosis_program)


def kurtosis(x: DNDarray, axis=None, unbiased: bool = True, Fischer: bool = True):
    """Fourth standardized moment (reference statistics.py:566-615; pairwise
    moment merging :870-945 happens inside XLA's tree reduction).  The whole
    moment chain compiles into one program via :func:`heat_tpu.fuse`."""
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    return _fused_kurtosis(x, axis, unbiased, Fischer)


def _skew_program(x: DNDarray, axis, unbiased: bool) -> DNDarray:
    arr = x.larray.astype(jnp.float64 if x.dtype is types.float64 else jnp.float32)
    mu = jnp.mean(arr, axis=axis, keepdims=True)
    diff = arr - mu
    m2 = jnp.mean(diff**2, axis=axis)
    m3 = jnp.mean(diff**3, axis=axis)
    n = arr.size if axis is None else arr.shape[axis]
    g1 = m3 / jnp.where(m2 == 0, 1, m2**1.5)
    if unbiased and n > 2:
        g1 = g1 * jnp.sqrt(n * (n - 1.0)) / (n - 2.0)
    return _wrap_reduced(x, g1, axis)


_fused_skew = fuse(_skew_program)


def skew(x: DNDarray, axis=None, unbiased: bool = True):
    """Third standardized moment (reference statistics.py:1423-1465), one
    fused program per (shape, axis, flags) signature."""
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    return _fused_skew(x, axis, unbiased)


def _nan_propagating(redfn):
    """NaN-propagating min/max reduction: XLA's cross-shard all-reduce
    min/max follows IEEE minNum/maxNum (NaN silently loses to any
    number), so a SHARDED array with a NaN reduced like numpy's min/max
    would drop it — jnp.min on a single device propagates, the
    partitioned collective does not.  One extra fused isnan any-reduce
    restores numpy/reference semantics."""

    def f(a, axis=None, keepdims=False):
        r = redfn(a, axis=axis, keepdims=keepdims)
        if jnp.issubdtype(a.dtype, jnp.floating):
            bad = jnp.any(jnp.isnan(a), axis=axis, keepdims=keepdims)
            r = jnp.where(bad, jnp.nan, r)
        return r

    return f


_nanprop_min = _nan_propagating(jnp.min)
_nanprop_max = _nan_propagating(jnp.max)


def max(x, axis=None, out=None, keepdims=None, keepdim=None):
    """Maximum along axes (reference statistics.py:616-727)."""
    keepdims = merge_keepdims(keepdims, keepdim)
    return _operations.__reduce_op(_nanprop_max, x, axis, out, keepdims=keepdims)


def maximum(x1, x2, out=None):
    """Elementwise maximum of two arrays (reference statistics.py:958-1057)."""
    return _operations.__binary_op(jnp.maximum, x1, x2, out)


def mean(x, axis=None, keepdims=None, keepdim=None):
    """Arithmetic mean (reference statistics.py:728-869; cross-shard moment
    combination is XLA's).  ``axis`` may be an int or a tuple of ints;
    ``keepdims``/``keepdim`` follow numpy/torch spelling like every other
    reduction here (the reference's mean lacks it — kept for oracle
    conformance)."""
    keepdims = merge_keepdims(keepdims, keepdim)
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    cast = jnp.float32 if types.heat_type_is_exact(x.dtype) else None
    res = _compressed_moment(x, axis, keepdims, kind="mean")
    if res is None:
        fn = jitted(
            ("stat.mean", axis, cast, keepdims),
            lambda: lambda a: jnp.mean(
                a.astype(cast) if cast else a, axis=axis, keepdims=keepdims
            ),
        )
        res = fn(x.larray)
    return _wrap_reduced(x, res, axis, keepdims=keepdims)


def median(x: DNDarray, axis=None, keepdim=None, out=None, keepdims=None):
    """Median = 50th percentile (reference statistics.py:845-877 —
    signature there is ``median(x, axis, keepdim)``, so ``keepdim`` keeps
    the third positional slot)."""
    if isinstance(keepdim, DNDarray):
        # a numpy-style positional caller passing an output buffer third
        # would silently get keepdim truthiness — fail loudly instead
        raise TypeError(
            "median()'s third positional parameter is keepdim (reference "
            "signature); pass the output buffer as out=..."
        )
    keepdims = merge_keepdims(keepdims, keepdim)
    return percentile(x, 50.0, axis=axis, out=out, keepdims=keepdims)


def min(x, axis=None, out=None, keepdims=None, keepdim=None):
    """Minimum along axes (reference statistics.py:1058-1123)."""
    keepdims = merge_keepdims(keepdims, keepdim)
    return _operations.__reduce_op(_nanprop_min, x, axis, out, keepdims=keepdims)


def minimum(x1, x2, out=None):
    """Elementwise minimum (reference statistics.py:1253-1351)."""
    return _operations.__binary_op(jnp.minimum, x1, x2, out)


def percentile(x: DNDarray, q, axis=None, out=None, interpolation: str = "linear", keepdims=None, keepdim=None):
    """q-th percentile(s) along an axis (reference statistics.py:1171-1422 —
    distributed via resplit + partition gather; here XLA's global sort)."""
    keepdims = merge_keepdims(keepdims, keepdim)
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    method = {"linear": "linear", "lower": "lower", "higher": "higher", "midpoint": "midpoint", "nearest": "nearest"}[interpolation]
    # interpolation dtype follows the x64 state: requesting float64 with
    # x64 off silently downcasts to f32 AND trips jax's dtype warning —
    # ask for what the backend can actually represent
    wide = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    qa = jnp.asarray(q, dtype=wide)
    reduced_empty = (
        x.size == 0 if axis is None else any(x.shape[a] == 0 for a in (
            (axis,) if isinstance(axis, int) else axis
        ))
    )
    # interpolation dtype only — materializing the (possibly ragged) true
    # view or an f64 copy up front would defeat the padded fast paths below
    idt = wide if types.heat_type_is_exact(x.dtype) else x._buffer.dtype

    def _cast_view():
        arr = x.larray
        return arr.astype(wide) if types.heat_type_is_exact(x.dtype) else arr

    from ..parallel import sort as _parallel_sort  # lazy: parallel imports core

    if reduced_empty:
        # numpy: percentile of an empty region is nan (np.median([]) is
        # nan; numpy 2.x percentile IndexErrors — we take the nan
        # contract), never a backend gather error.  res flows into the
        # common wrap/out tail like every other branch
        if axis is None:
            tail = (1,) * x.ndim if keepdims else ()
        else:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            tail = tuple(
                (1 if d in axes else s) if keepdims or d not in axes else None
                for d, s in enumerate(x.shape)
            )
            tail = tuple(s for s in tail if s is not None)
        res = jnp.full(tuple(qa.shape) + tail, jnp.nan, dtype=idt)
    elif (
        axis is None
        and x.split is not None
        and _parallel_sort.supports(x._buffer.dtype, x.size, x.comm)
    ):
        # global percentile of a sharded array: jnp.percentile's internal
        # sort is the pathological GSPMD global sort — rank-sort over the
        # ring instead, then interpolate locally on the sorted output
        # 1-D padded arrays feed their at-rest buffer straight in (the ring
        # sort masks rows past the true length); n-D must ravel the true
        # view — pad rows would interleave the flattened order
        flat = x._buffer if x.ndim == 1 else jnp.ravel(x.larray)
        svals, _ = _parallel_sort.ring_rank_sort(
            flat, x.size, comm=x.comm, want_indices=False
        )
        res = _interp_sorted(svals.astype(idt), qa, method)
        if keepdims:
            res = jnp.reshape(res, qa.shape + (1,) * x.ndim)
    elif (
        isinstance(axis, int)
        and axis == x.split
        and _parallel_sort.supports_axis(x._buffer.dtype, x.shape, axis, x.comm)
    ):
        # axis-quantile ALONG the split axis: the reference resolves this
        # with a distributed partition gather (statistics.py:1171-1422);
        # here the explicit distributed sort orders every fiber along the
        # split axis, then interpolation is a local gather.
        # sort in the original (sortable) dtype, interpolate in the cast
        moved = jnp.moveaxis(x.larray, axis, 0) if axis != 0 else x.larray
        svals, _ = _parallel_sort.sort_axis0(
            moved, x.shape[axis], comm=x.comm, want_indices=False
        )
        res = _interp_sorted(svals.astype(idt), qa, method)
        # res: qa.shape + (dims of x without `axis`, original order) —
        # exactly jnp.percentile's layout; keepdims re-inserts the axis
        if keepdims:
            res = jnp.expand_dims(res, axis=qa.ndim + axis)
    elif qa.ndim > 1:
        # jnp.percentile only takes rank-<=1 q; numpy allows any shape —
        # flatten, compute, and fold the q axes back in front
        flat = jnp.percentile(
            _cast_view(), qa.reshape(-1), axis=axis, method=method, keepdims=keepdims
        )
        res = flat.reshape(qa.shape + flat.shape[1:])
    else:
        res = jnp.percentile(_cast_view(), qa, axis=axis, method=method, keepdims=keepdims)
    if np.isscalar(q) or qa.ndim == 0:
        result = _wrap_reduced(x, res, axis, keepdims=keepdims)
    else:
        # array q prepends a q-axis: replicate rather than mis-shift split
        garr = x.comm.apply_sharding(res, None)
        result = DNDarray(
            garr, tuple(garr.shape), types.canonical_heat_type(garr.dtype),
            None, x.device, x.comm, True,
        )
    if out is not None:
        out.larray = result.larray
        return out
    return result


def _interp_sorted(svals, qa, method: str):
    """numpy-method percentile lookup on an array already sorted along
    axis 0 (NaNs sorted last); trailing dims are independent fibers, so
    the result has shape ``qa.shape + svals.shape[1:]``.  Propagates NaN
    like jnp.percentile: any NaN in a fiber — visible as a NaN tail after
    the sort — poisons that fiber's every quantile."""
    n = svals.shape[0]
    batch = svals.ndim - 1
    # the virtual position q/100*(n-1) is pure host data (q and n are
    # both host-known) — compute it in float64 regardless of the x64
    # policy: in float32, 30% of 1001 lands at 299.99997 and floors to
    # the WRONG element for the exact-index methods
    pos = np.asarray(qa, dtype=np.float64) / 100.0 * (n - 1)
    lo = np.clip(np.floor(pos).astype(np.int32), 0, n - 1)
    hi = np.clip(np.ceil(pos).astype(np.int32), 0, n - 1)
    vlo, vhi = svals[lo], svals[hi]  # qa.shape + batch dims
    if method == "lower":
        res = vlo
    elif method == "higher":
        res = vhi
    elif method == "nearest":
        # numpy rounds half to even — np.round matches; a plain 0.5
        # threshold picks a different element at exact half positions
        idx = np.clip(np.round(pos).astype(np.int32), 0, n - 1)
        res = svals[idx]
    elif method == "midpoint":
        res = (vlo + vhi) / 2.0
    else:  # linear
        frac = jnp.asarray((pos - lo).reshape(pos.shape + (1,) * batch), svals.dtype)
        res = vlo * (1 - frac) + vhi * frac
    if jnp.issubdtype(svals.dtype, jnp.floating):
        res = jnp.where(jnp.isnan(svals[-1]), jnp.nan, res)
    return res


def _moment2(x, axis, ddof, kwargs, name, finalize):
    """Shared var/std engine: ddof/bessel semantics + one fused executable
    (``finalize`` is identity for var, sqrt for std)."""
    sanitize_in(x)
    if "bessel" in kwargs:
        ddof = 1 if kwargs.pop("bessel") else 0
    if ddof not in (0, 1):
        raise ValueError(f"ddof must be 0 or 1, got {ddof}")
    axis = sanitize_axis(x.shape, axis)
    keepdims = merge_keepdims(kwargs.pop("keepdims", None), kwargs.pop("keepdim", None))
    if kwargs:
        raise TypeError(f"unexpected keyword arguments: {sorted(kwargs)}")
    cast = jnp.float32 if types.heat_type_is_exact(x.dtype) else None
    res = _compressed_moment(
        x, axis, keepdims, kind=("std" if name == "stat.std" else "var"), ddof=ddof
    )
    if res is None:
        fn = jitted(
            ("stat.moment2", name, axis, ddof, cast, keepdims),
            lambda: lambda a: finalize(
                jnp.var(a.astype(cast) if cast else a, axis=axis, ddof=ddof, keepdims=keepdims)
            ),
        )
        res = fn(x.larray)
    return _wrap_reduced(x, res, axis, keepdims=keepdims)


def std(x, axis=None, ddof: int = 0, **kwargs):
    """Standard deviation (reference statistics.py:1466-1558) — one fused
    sqrt(var) executable rather than two dispatches.  Accepts numpy's
    ``keepdims`` and tuple axes like :func:`var`."""
    return _moment2(x, axis, ddof, kwargs, "stat.std", jnp.sqrt)


def var(x, axis=None, ddof: int = 0, **kwargs):
    """Variance with ddof semantics (reference statistics.py:1559-1705;
    single-pass merged moments are XLA's reduction plan).

    Note: like the reference, ``ddof`` ∈ {0, 1} (bessel correction via
    ``bessel=True`` kwarg is also accepted)."""
    return _moment2(x, axis, ddof, kwargs, "stat.var", lambda r: r)


# split semantics for heat_tpu.analysis.splitflow (see core/_split_semantics.py)
from ._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {
        "reduction": (
            "argmax", "argmin", "max", "mean", "median", "min", "std",
            "var", "kurtosis", "skew",
        ),
        "binary": ("maximum", "minimum"),
    },
)
