"""Version portability for the handful of jax APIs that moved between the
0.4.x line and current jax.

The package is written against the current surface (``jax.shard_map``,
``jax.enable_x64``, ``jax.lax.pcast``, ``ShapeDtypeStruct(vma=...)``,
``pltpu.CompilerParams``); jax 0.4.x ships the same capabilities under
older names (``jax.experimental.shard_map``, ``jax.experimental.enable_x64``,
``check_rep`` instead of ``check_vma``, ``TPUCompilerParams``) and predates
the varying-manual-axes type system entirely — there ``pcast`` is the
identity and ``vma`` is dropped.  Every module imports these five names
instead of reaching into jax directly, so the whole surface is patched in
one place when the installed jax moves again.
"""

from __future__ import annotations

import functools

import jax

__all__ = [
    "distributed_is_initialized",
    "enable_x64",
    "pcast",
    "shape_dtype_struct",
    "shard_map",
    "tpu_compiler_params",
]

_HAS_VMA = hasattr(jax, "shard_map")  # the vma type system landed with it


if _HAS_VMA:
    _shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
        kw = {} if check_vma is None else {"check_rep": check_vma}
        return _old_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with the ``check_vma`` knob mapped to 0.4.x's
    ``check_rep`` (same meaning: per-device output-type validation)."""
    if _HAS_VMA:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
    )


if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:
    from jax.experimental import enable_x64  # noqa: F401  (0.4.x home)


def pcast(x, axes, to="varying"):
    """``jax.lax.pcast`` — aligns a fresh (axis-invariant) carry with the
    varying loop values it will join.  Identity on 0.4.x, which has no
    varying-type system (its ``check_rep`` infers replication per-op)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to=to)
    return x


def shape_dtype_struct(shape, dtype, vma=()):
    """``jax.ShapeDtypeStruct`` carrying ``vma`` only where jax knows the
    kwarg (pallas_call out_shape under shard_map on current jax)."""
    if _HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def distributed_is_initialized():
    """``jax.distributed.is_initialized()`` — on 0.4.x, read the client off
    the distributed global state directly (same check, pre-public name)."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    from jax._src import distributed

    return distributed.global_state.client is not None


@functools.lru_cache(maxsize=None)
def _compiler_params_cls():
    from jax.experimental.pallas import tpu as pltpu

    return getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (``TPUCompilerParams`` on 0.4.x)."""
    return _compiler_params_cls()(**kwargs)
