"""Trace-mode state shared by the compile cache, the communication layer,
DNDarray, and :mod:`heat_tpu.core.fuse`.

``heat_tpu`` normally runs ops eagerly: every op commits its result's
layout with a real ``device_put`` and any host-side inspection
(``float(x)``, ``repr(x)``, ``x.numpy()``) simply reads the committed
array back.  Under :func:`heat_tpu.fuse` the same library code runs once
*inside* a ``jax.jit`` trace, where arrays are abstract tracers: committed
shardings do not exist yet (layout requests become
``jax.lax.with_sharding_constraint`` hints for GSPMD) and reading a value
back is impossible by construction.  This module holds the process-global
flag that tells the rest of the core which of the two worlds it is in,
plus the diagnostic error raised when traced code demands a concrete
value.

It also hosts the *dispatch counter* — the test/bench shim that counts
device program launches at the library level.  Counting at the jax/XLA
layer is not reliable from Python (the C++ pjit fast path bypasses any
Python wrapper after the first call), so the counter is incremented by the
two places heat_tpu itself launches programs: the ``jitted()`` executable
wrapper and the ``device_put``-based reshard in the communication layer.
The counter's storage moved into :mod:`heat_tpu.telemetry` (one registry
for all runtime accounting, lock-guarded so threaded serving does not
lose increments); the functions here are the stable shim over it, and
:func:`counting_dispatches` is the leak-free way for tests to scope a
reading.

Kept free of jax imports so every core module can import it without
ordering constraints (:mod:`heat_tpu.telemetry._core` holds the same
property).
"""

from __future__ import annotations

import contextlib

from ..telemetry import _core as _telemetry

__all__ = [
    "FuseTraceError",
    "NO_OVERRIDE",
    "applying_layout_plan",
    "consume_layout_override",
    "trace_mode",
    "in_trace",
    "layout_plan_active",
    "require_concrete",
    "record_dispatch",
    "dispatch_count",
    "reset_dispatch_count",
    "counting_dispatches",
]


class FuseTraceError(RuntimeError):
    """A value-forcing operation ran on a traced DNDarray.

    Raised when code inside an ``ht.fuse``-compiled pipeline (or a
    ``fuse.trace()`` block) tries to materialize a concrete value —
    ``float(x)``, ``x.item()``, ``print(x)``, ``x.numpy()``, file I/O.
    Inside a trace there is no value yet, only an abstract shape; the fix
    is to keep the computation on-device (``jnp.where`` / ``lax.cond``
    instead of Python ``if``), or to move the host-side step outside the
    fused function.
    """


_trace_depth = 0


def in_trace() -> bool:
    """True while a ``fuse`` trace (or explicit ``fuse.trace()`` block)
    is active on this thread of control."""
    return _trace_depth > 0


@contextlib.contextmanager
def trace_mode():
    """Enter tracing mode: the communication layer swaps committed-layout
    inspection for ``with_sharding_constraint`` hints and value-forcing
    DNDarray operations raise :class:`FuseTraceError`.  Re-entrant."""
    global _trace_depth
    _trace_depth += 1
    try:
        yield
    finally:
        _trace_depth -= 1


def require_concrete(what: str) -> None:
    """Raise the diagnostic :class:`FuseTraceError` if tracing is active.

    Called by every value-forcing DNDarray entry point with a short
    description of the operation (``"float()"``, ``".numpy()"`` …).
    """
    if _trace_depth > 0:
        raise FuseTraceError(
            f"{what} forces a concrete value, but this DNDarray is being "
            "traced inside ht.fuse — no value exists yet. Keep the decision "
            "on-device (jnp.where / lax.cond) or move this step outside the "
            "fused function."
        )


# ---------------------------------------------------------------------- #
# layout-plan overrides (ht.autoshard → manipulations.resplit)            #
# ---------------------------------------------------------------------- #
#: sentinel distinguishing "no override recorded" from "override to None"
NO_OVERRIDE = object()

_layout_plan = None  # {signature: [apply, ...]} FIFO while a plan is active


def layout_plan_active() -> bool:
    """True while an ``ht.autoshard`` plan is being applied on this call."""
    return _layout_plan is not None


@contextlib.contextmanager
def applying_layout_plan(decisions):
    """Expose a solved layout plan to ``manipulations.resplit`` for the
    dynamic extent of one pipeline call.

    ``decisions`` is the solver's list (see
    :meth:`heat_tpu.comm._costs.LayoutSolver.solve`); each is keyed by the
    *signature* of the hand-written resplit it replaces — ``(shape,
    dtype, src split, requested dst)`` — NOT by call position, so library
    resplits the plan never saw (e.g. ``__binary_op``'s implicit reshard)
    pass through untouched.  Same-signature calls consume their overrides
    in FIFO order, matching the solver's program-order chain walk.  The
    table is rebuilt per call: a plan application never leaks into the
    next call, and nesting restores the outer plan.
    """
    global _layout_plan
    table = {}
    for d in decisions:
        key = (tuple(d["shape"]), d["dtype"], d["src"], d["requested"])
        table.setdefault(key, []).append(d["apply"])
    prev = _layout_plan
    _layout_plan = table
    try:
        yield
    finally:
        _layout_plan = prev


def consume_layout_override(shape, dtype_name, src, requested):
    """Pop the next planned placement for a resplit with this signature,
    or :data:`NO_OVERRIDE` when the active plan has nothing for it."""
    if _layout_plan is None:
        return NO_OVERRIDE
    queue = _layout_plan.get((tuple(shape), dtype_name, src, requested))
    if not queue:
        return NO_OVERRIDE
    return queue.pop(0)


# ---------------------------------------------------------------------- #
# dispatch counting (shim over the telemetry registry)                    #
# ---------------------------------------------------------------------- #
def record_dispatch() -> None:
    """Count one device program launch.

    No-ops inside trace mode: a call that happens while tracing is being
    inlined into the enclosing program, not dispatched.  The increment
    itself lives in :mod:`heat_tpu.telemetry` — thread-safe, and visible
    as the ``dispatches`` counter when telemetry is enabled.
    """
    if _trace_depth == 0:
        _telemetry.record_dispatch()


def dispatch_count() -> int:
    """Device program launches recorded since the last reset."""
    return _telemetry.dispatch_count()


def reset_dispatch_count() -> None:
    _telemetry.reset_dispatch_count()


def counting_dispatches():
    """Scoped dispatch counting: ``with counting_dispatches() as d: ...``
    then read ``d.count`` — a baseline diff over the process counter, so
    tests never have to reset (and therefore never leak) global state.
    See :func:`heat_tpu.telemetry.counting_dispatches`."""
    return _telemetry.counting_dispatches()
