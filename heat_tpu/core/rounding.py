"""Rounding, clipping, and sign-structure elementwise ops.

Reference: heat/core/rounding.py:11-315 — all ``__local_op`` maps except
``clip`` (ternary) and ``modf`` (two outputs).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = ["abs", "absolute", "ceil", "clip", "fabs", "floor", "modf", "round", "sign", "trunc"]


def abs(x, out=None, dtype=None):
    """Elementwise absolute value (reference rounding.py:11-56)."""
    if dtype is not None and not issubclass(types.canonical_heat_type(dtype), types.generic):
        raise TypeError("dtype must be a heat data type")
    result = _operations.__local_op(jnp.abs, x, out, no_cast=True)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype), copy=False)
    return result


absolute = abs


def fabs(x, out=None):
    """Float absolute value, no int casting (reference rounding.py:57-86)."""
    return _operations.__local_op(jnp.abs, x, out)


def ceil(x, out=None):
    """Ceiling (reference rounding.py:87-117)."""
    return _operations.__local_op(jnp.ceil, x, out)


def clip(a, a_min, a_max, out=None):
    """Clamp values to [a_min, a_max] (reference rounding.py:118-156)."""
    from .sanitation import sanitize_in

    sanitize_in(a)
    if a_min is None and a_max is None:
        raise ValueError("either a_min or a_max must be set")

    def _clip(arr):
        return jnp.clip(arr, a_min, a_max)

    return _operations.__local_op(_clip, a, out, no_cast=True)


def floor(x, out=None):
    """Floor (reference rounding.py:157-187)."""
    return _operations.__local_op(jnp.floor, x, out)


def modf(x, out=None) -> Tuple[DNDarray, DNDarray]:
    """Split into fractional and integral parts (reference rounding.py:188-236)."""
    from .sanitation import sanitize_in

    sanitize_in(x)
    frac, integ = jnp.modf(x.larray.astype(jnp.float32) if jnp.issubdtype(x.larray.dtype, jnp.integer) else x.larray)
    dtype = types.canonical_heat_type(frac.dtype)
    fr = DNDarray(x.comm.apply_sharding(frac, x.split), x.shape, dtype, x.split, x.device, x.comm, x.balanced)
    it = DNDarray(x.comm.apply_sharding(integ, x.split), x.shape, dtype, x.split, x.device, x.comm, x.balanced)
    if out is not None:
        if not isinstance(out, tuple) or len(out) != 2:
            raise TypeError("out must be a 2-tuple of DNDarrays")
        out[0].larray = fr.larray
        out[1].larray = it.larray
        return out
    return fr, it


def round(x, decimals: int = 0, out=None, dtype=None):
    """Round to ``decimals`` places (reference rounding.py:237-284)."""

    def _round(arr):
        return jnp.round(arr, decimals)

    result = _operations.__local_op(_round, x, out)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype), copy=False)
    return result


def sign(x, out=None):
    """Elementwise sign (numpy-parity; reference provides via torch.sign)."""
    return _operations.__local_op(jnp.sign, x, out, no_cast=True)


def trunc(x, out=None):
    """Truncate toward zero (reference rounding.py:285-315)."""
    return _operations.__local_op(jnp.trunc, x, out)


# split semantics for heat_tpu.analysis.splitflow (see core/_split_semantics.py)
from ._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {"elementwise": ("abs", "fabs", "ceil", "floor", "round", "sign", "trunc")},
)
