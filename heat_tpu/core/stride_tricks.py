"""Shape/axis normalization helpers shared by every op.

Reference: heat/core/stride_tricks.py:5-192 (``broadcast_shape``,
``sanitize_axis``, ``sanitize_shape``, ``sanitize_slice``).  Pure shape
logic — identical semantics here; only the error messages and the numpy
implementation differ.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["broadcast_shape", "sanitize_axis", "sanitize_shape", "sanitize_slice"]


def broadcast_shape(shape_a: Sequence[int], shape_b: Sequence[int]) -> Tuple[int, ...]:
    """NumPy-semantics broadcast of two shapes (reference stride_tricks.py:5-53).

    Raises ValueError when the shapes are incompatible.
    """
    try:
        return tuple(np.broadcast_shapes(tuple(shape_a), tuple(shape_b)))
    except ValueError:
        raise ValueError(
            f"operands could not be broadcast, input shapes {tuple(shape_a)} {tuple(shape_b)}"
        )


def sanitize_axis(
    shape: Sequence[int], axis: Union[int, None, Sequence[int]]
) -> Union[int, None, Tuple[int, ...]]:
    """Normalize (possibly negative, possibly multiple) axes against ``shape``
    (reference stride_tricks.py:55-116)."""
    ndim = len(shape)
    if axis is None:
        return None
    if isinstance(axis, (list, tuple, np.ndarray)):
        axes = tuple(int(a) for a in axis)
        out = []
        for a in axes:
            if not isinstance(a, (int, np.integer)):
                raise TypeError(f"axis must be None or int or tuple of ints, got {type(a)}")
            if a < -ndim or a >= max(ndim, 1):
                raise ValueError(f"axis {a} is out of bounds for {ndim}-dimensional shape")
            out.append(a % ndim if ndim else 0)
        if len(set(out)) != len(out):
            raise ValueError("duplicate axes given")
        return tuple(out)
    if not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be None or int or tuple of ints, got {type(axis)}")
    axis = int(axis)
    if ndim == 0 and axis in (-1, 0):
        return None  # scalars ignore the axis (numpy semantics)
    if axis < -ndim or axis >= ndim:
        raise ValueError(f"axis {axis} is out of bounds for {ndim}-dimensional shape")
    return axis % ndim


def sanitize_shape(shape: Union[int, Sequence[int]], lval: int = 0) -> Tuple[int, ...]:
    """Normalize a shape argument to a tuple of non-negative ints
    (reference stride_tricks.py:118-161).  ``lval`` is the lowest legal
    entry (0 by default)."""
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    elif isinstance(shape, (list, tuple, np.ndarray)):
        shape = tuple(shape)
    else:
        raise TypeError(f"expected sequence object or single int, got {type(shape)}")
    out = []
    for s in shape:
        if not isinstance(s, (int, np.integer)):
            raise TypeError(f"expected int dimensions, got {type(s)}")
        s = int(s)
        if s < lval:
            raise ValueError(f"negative dimensions are not allowed, got {s}")
        out.append(s)
    return tuple(out)


def sanitize_slice(sl: slice, max_dim: int) -> slice:
    """Resolve a slice against a dimension length into non-negative
    start/stop/step (reference stride_tricks.py:163-192)."""
    if not isinstance(sl, slice):
        raise TypeError("can only be applied to slice objects")
    return slice(*sl.indices(max_dim))
