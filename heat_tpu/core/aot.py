"""Ahead-of-time executable export/install for fused programs.

The zero-cold-start half of fleet serving (docs/design.md §22): a warm
serving process captures its compiled ``ht.fuse`` predict programs,
lowers them through the staged AOT path
(``jfn.lower(specs).compile()`` — the same pipeline
:func:`heat_tpu.core._compile._timed_first_call` stages for timing) and
serializes the XLA executables via
:mod:`jax.experimental.serialize_executable`.  A fresh replica installs
the bundles straight into the fuse cache, so its first request is a
cache *replay* — zero traces, zero XLA compiles, verifiable on the
``fuse.cache.misses`` / ``compile.cache.misses`` counters.

Soundness is fingerprint-gated, never assumed:

- :func:`fingerprint` pins the format version, jax/jaxlib versions,
  backend platform, visible device count, and the policy key-context
  (:func:`heat_tpu.core._compile.context_token` — precision/threshold/
  redistribution/overlap/guard state).  A bundle whose fingerprint does
  not match the running process is *skipped*, not loaded.
- per-bundle, the capture comm's size and mesh shape must match the
  install comm — an executable compiled for one topology never replays
  on another.
- anything that cannot be exported soundly (unpicklable statics, mixed
  comms across operands, backends whose executables refuse
  serialization) is silently dropped from the bundle list; the replica
  then falls back to a fresh trace+compile for exactly those programs.

The fallback ladder is therefore: installed replay → (on any mismatch)
fresh compile — bit-identical results either way, only the cold-start
latency differs.
"""

from __future__ import annotations

import contextlib
import importlib
import pickle
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax

import sys as _sys

from ..telemetry import _core as _tel
from . import _compile
from . import fuse as _fuse_mod  # noqa: F401 - ensures the module is loaded

# the package rebinds the ``fuse`` attribute to the decorator function,
# so resolve the MODULE explicitly
_fuse = _sys.modules["heat_tpu.core.fuse"]

__all__ = [
    "capture_programs",
    "export_programs",
    "fingerprint",
    "install_programs",
]

#: bumped whenever the bundle layout changes — an old sidecar is a
#: fingerprint mismatch, not a parse error
_FORMAT_VERSION = 1

#: sentinel replacing live comm objects inside pickled key/meta parts
_COMM_SENTINEL = "__heat_tpu_comm__"


def fingerprint() -> Tuple:
    """The compatibility fingerprint an executable bundle is stamped
    with: equal fingerprints mean "this process can soundly replay that
    process's executables"."""
    import jaxlib

    return (
        _FORMAT_VERSION,
        jax.__version__,
        jaxlib.__version__,
        jax.default_backend(),
        jax.device_count(),
        tuple(_compile.context_token()),
    )


# --------------------------------------------------------------------- #
# capture
# --------------------------------------------------------------------- #
@contextlib.contextmanager
def capture_programs():
    """Record every cache-keyed fused-program call inside the block.

    Yields the capture dict (one entry per distinct fuse-cache key,
    recorded whether the call was a build or a replay); hand it to
    :func:`export_programs`.  Capture is observation only — the calls
    themselves run exactly as they would outside the block.
    """
    sink: Dict[Tuple, Dict[str, Any]] = {}
    _fuse._CAPTURE_SINKS.append(sink)
    try:
        yield sink
    finally:
        _fuse._CAPTURE_SINKS.remove(sink)


def _swap_comm(obj, comm, live):
    """Recursively replace ``comm``-equal objects with the sentinel
    (export, ``live=False``) or the sentinel with ``comm`` (install,
    ``live=True``) inside key/meta tuples."""
    if live:
        if isinstance(obj, str) and obj == _COMM_SENTINEL:
            return comm
    else:
        if isinstance(obj, type(comm)) and obj == comm:
            return _COMM_SENTINEL
    if isinstance(obj, tuple):
        return tuple(_swap_comm(o, comm, live) for o in obj)
    return obj


def _comms_in(obj, out: list) -> None:
    """Collect comm-like objects (anything with ``.size`` and
    ``.sharding``) from nested key/meta tuples."""
    if isinstance(obj, tuple):
        for o in obj:
            _comms_in(o, out)
    elif hasattr(obj, "size") and hasattr(obj, "sharding") and not isinstance(
        obj, (np.ndarray, jax.Array)
    ):
        out.append(obj)


def export_programs(capture: Dict[Tuple, Dict[str, Any]]) -> List[Dict[str, Any]]:
    """AOT-compile and serialize every captured program into picklable
    bundles.  Entries that cannot be exported soundly (see module docs)
    are dropped; the count of exported bundles is the caller's signal.
    """
    try:
        from jax.experimental import serialize_executable as _ser
    except ImportError:  # pragma: no cover - jax always ships it here
        return []
    bundles: List[Dict[str, Any]] = []
    for entry in capture.values():
        fn = entry["fn"]
        comm = entry["comm"]
        if comm is None:
            continue  # no DNDarray operand: nothing topology-bound to pin
        seen: list = []
        _comms_in(entry["keyparts"], seen)
        _comms_in(entry["program"].out_meta, seen)
        if any(c != comm for c in seen):
            continue  # mixed comms: one live substitute cannot rebuild the key
        try:
            jfn = entry["program"].jfn
            stashed = getattr(entry["program"], "aot_payload", None)
            if hasattr(jfn, "lower"):
                compiled = jfn.lower(entry["specs"]).compile()
                payload, in_tree, out_tree = _ser.serialize(compiled)
            elif stashed is not None:
                # an installed program: XLA cannot soundly re-serialize a
                # loaded executable (second-generation deserialization
                # fails symbol resolution), so re-export the original
                # payload the install stashed on the program
                payload, in_tree, out_tree = stashed
            else:
                continue
        except (ValueError, TypeError, AttributeError):
            continue  # backend refuses AOT serialization: fresh-compile rung
        bundle = {
            "fingerprint": fingerprint(),
            "fn": (fn.__module__, fn.__qualname__),
            "donate": entry["donate"],
            "plan_token": entry["plan_token"],
            "treedef": entry["treedef"],
            "keyparts": _swap_comm(entry["keyparts"], comm, live=False),
            "comm_size": int(comm.size),
            "mesh_shape": tuple(getattr(comm, "_mesh_shape", (comm.size,))),
            "out_treedef": entry["program"].out_treedef,
            "out_meta": _swap_comm(entry["program"].out_meta, comm, live=False),
            "guarded": entry["program"].guarded,
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        }
        try:
            pickle.dumps(bundle)
        except Exception:
            continue  # unpicklable static/meta leaf: fresh-compile rung
        bundles.append(bundle)
    if _tel.enabled and bundles:
        _tel.inc("aot.exported", len(bundles))
    return bundles


# --------------------------------------------------------------------- #
# install
# --------------------------------------------------------------------- #
def _resolve_fn(module: str, qualname: str):
    obj: Any = importlib.import_module(module)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    if isinstance(obj, _fuse._FusedFunction):
        obj = obj._fn  # the raw fn is what fuse keys on
    return obj


def install_programs(bundles: List[Dict[str, Any]], *, comm) -> int:
    """Install serialized executables into the fuse cache for ``comm``.

    Returns how many bundles were installed; every skipped bundle (wrong
    fingerprint, topology mismatch, unresolvable function) simply leaves
    its program to the fresh-compile rung of the ladder.  After a
    successful install the next call of the captured pipeline with the
    captured operand layout is a pure cache replay: zero traces, zero
    compiles, one dispatch.
    """
    try:
        from jax.experimental import serialize_executable as _ser
    except ImportError:  # pragma: no cover
        return 0
    want = fingerprint()
    installed = 0
    for bundle in bundles:
        if bundle.get("fingerprint") != want:
            continue
        if int(bundle.get("comm_size", -1)) != int(comm.size):
            continue
        if tuple(bundle.get("mesh_shape", ())) != tuple(
            getattr(comm, "_mesh_shape", (comm.size,))
        ):
            continue
        try:
            fn = _resolve_fn(*bundle["fn"])
        except (ImportError, AttributeError):
            continue
        try:
            compiled = _ser.deserialize_and_load(
                bundle["payload"], bundle["in_tree"], bundle["out_tree"]
            )
        except Exception:
            # ValueError/TypeError on tree mismatch, XlaRuntimeError on
            # unresolvable symbols — every flavour lands on the
            # fresh-compile rung
            continue
        program = _fuse._Program(compiled)
        program.out_treedef = bundle["out_treedef"]
        program.out_meta = _swap_comm(bundle["out_meta"], comm, live=True)
        program.guarded = bool(bundle["guarded"])
        program.aot_payload = (
            bundle["payload"], bundle["in_tree"], bundle["out_tree"]
        )
        key = (
            fn,
            bundle["donate"],
            bundle["plan_token"],
            bundle["treedef"],
            _swap_comm(bundle["keyparts"], comm, live=True),
            comm,
            _compile.context_token(),
        )
        _fuse._FUSE_CACHE[key] = program
        installed += 1
    if _tel.enabled:
        if installed:
            _tel.inc("aot.installed", installed)
        _tel.gauge("fuse.cache.size", len(_fuse._FUSE_CACHE))
    return installed
