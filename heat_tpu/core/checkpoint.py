"""Fitted-estimator checkpointing.

An extension the reference lacks: its estimators expose ``get_params``
(reference base.py:30-55) but have no save/restore of FITTED state —
persistence there is data-level only (``ht.save``/``ht.load``, reference
io.py:622-921; SURVEY §5.4 calls this out).  Training an estimator on a
large mesh and re-fitting it in every consumer process is exactly the
workflow a TPU deployment cannot afford, so this module closes the gap
on top of the existing parallel IO layer:

- one HDF5 file per estimator;
- a typed JSON manifest (file attribute) describing constructor params
  and fitted attributes: scalars inline, small host numpy arrays inline,
  large host numpy arrays spilled to datasets, nested fitted estimators
  recursively, DNDarrays by dataset key;
- all datasets + the manifest written in ONE file open with ONE
  cross-process failure barrier (io._save_hdf5_many — multihost-safe:
  process 0 writes, every process joins the slab collectives);
- split layouts recorded per dataset and restored exactly on load;
- DNDarrays shared between a parent and a nested estimator (Spectral's
  ``_labels`` IS its KMeans's ``labels_``) are written once and re-linked
  on load.

What gets captured: constructor parameters (``get_params``) plus the
attributes named by ``BaseEstimator._checkpoint_attrs()`` — by default
every public ``*_`` instance attribute (the sklearn fitted convention);
estimators whose fitted state lives in private storage override it
(``_KCluster``, ``Spectral``, ``Lasso``).
"""

from __future__ import annotations

import importlib
import json
from typing import Any, Dict, Tuple

import numpy as np

from ..telemetry import _core as _tel
from . import io as _io
from . import types
from .base import BaseEstimator
from .dndarray import DNDarray

__all__ = ["list_checkpoints", "load_estimator", "save_estimator"]

_MANIFEST_ATTR = "heat_tpu_estimator"
#: manifest schema version this build WRITES (as ``format_version``);
#: v1 manifests (which carried the version under the legacy ``format``
#: key) remain readable — the entry kinds are a superset-compatible set
_FORMAT_VERSION = 2
_READABLE_VERSIONS = (1, 2)
#: inline-manifest budget for host numpy arrays; anything bigger spills
#: to an HDF5 dataset instead of the JSON attribute
_NPARRAY_INLINE_MAX = 16384


class _SaveContext:
    """Dataset accumulator with identity dedup: the same DNDarray (or the
    same host array object) reachable twice — e.g. Spectral._labels is
    its nested KMeans's labels_ — is written once."""

    def __init__(self):
        self.datasets: Dict[str, DNDarray] = {}
        self._by_id: Dict[int, str] = {}
        # id() keys are only valid while the object lives — retain every
        # identity object so a freed temporary's recycled address can
        # never produce a false dedup hit
        self._keepalive: list = []

    def add(self, value: DNDarray, key: str, ident=None) -> str:
        """Register ``value`` under ``key`` unless the identity object
        (``ident``, default the value itself — pass the ORIGINAL host
        array when spilling a numpy attribute) was registered before."""
        obj = value if ident is None else ident
        existing = self._by_id.get(id(obj))
        if existing is not None:
            return existing
        self._by_id[id(obj)] = key
        self._keepalive.append(obj)
        self.datasets[key] = value
        return key


def _encode(value, key: str, ctx: _SaveContext) -> Dict[str, Any]:
    """One manifest entry for ``value``; DNDarrays (and spilled host
    arrays) land in ``ctx`` under ``key`` (or an earlier key if dedup
    hits)."""
    if isinstance(value, DNDarray):
        return {
            "kind": "dndarray",
            "key": ctx.add(value, key),
            "split": value.split,
            "dtype": value.dtype.__name__,
        }
    if isinstance(value, BaseEstimator):
        return {"kind": "estimator", "manifest": _manifest(value, key + "/", ctx)}
    import jax

    ident = None
    if isinstance(value, jax.Array):
        # dedup keys on the ORIGINAL device array: np.asarray makes a
        # fresh host copy per attribute, so two attributes aliasing one
        # jax.Array would otherwise write two datasets
        ident = value
        value = np.asarray(value)
        if value.ndim == 0:
            value = value.item()
    if isinstance(value, np.generic):
        value = value.item()
    is_bf16 = isinstance(value, np.ndarray) and value.dtype == np.dtype("bfloat16")
    if isinstance(value, np.ndarray) and (value.dtype.kind in "biuf" or is_bf16):
        # non-numeric dtypes (datetime64, structured, object) fall
        # through to the descriptive TypeError below: neither json
        # inlining nor the heat dataset spill can round-trip them.
        # bfloat16 (numpy kind 'V' via ml_dtypes) IS numeric: its dtype
        # is recorded by NAME (its .str is a lossy '<V2') and its HDF5
        # spill widens exactly to f32 (h5py has no bf16)
        obj = ident if ident is not None else value
        if value.size > _NPARRAY_INLINE_MAX:
            # library-managed host state (e.g. GaussianNB theta_ on many
            # features) must not fail the save — spill it to a dataset.
            # Dedup keys on the original object: two attributes aliasing
            # one array write one dataset
            existing = ctx._by_id.get(id(obj))
            if existing is not None:
                arr = ctx.datasets[existing]
                used = existing
            else:
                from . import factories

                host = np.ascontiguousarray(value)
                if is_bf16:
                    host = host.astype(np.float32)  # exact widening
                arr = factories.array(host)
                used = ctx.add(arr, key, ident=obj)
            return {
                "kind": "nparray_dataset",
                "key": used,
                "dtype": value.dtype.name,
                "heat_dtype": arr.dtype.__name__,
            }
        return {
            "kind": "nparray",
            "dtype": value.dtype.name,
            "shape": list(value.shape),
            # bf16 tolist() yields exact python floats — json-safe
            "data": value.ravel().tolist(),
        }
    if value is None or isinstance(value, (bool, int, float, str)):
        return {"kind": "scalar", "value": value}
    if isinstance(value, (list, tuple)):
        if all(v is None or isinstance(v, (bool, int, float, str)) for v in value):
            # JSON collapses tuples into lists; record which it was so the
            # restored param compares equal to the original
            return {
                "kind": "scalar",
                "value": list(value),
                "tuple": isinstance(value, tuple),
            }
    raise TypeError(
        f"cannot checkpoint {key!r} of type {type(value).__name__}: {value!r} "
        "(supported: DNDarray, estimators, scalars, strings, numeric "
        "bool/int/uint/float host numpy arrays, flat scalar lists)"
    )


def _is_heat_tpu_module(mod_name: str) -> bool:
    """One allowlist predicate for BOTH the save-time guard (_manifest)
    and the load-time import guard (_resolve_class), so the two can
    never drift apart."""
    return mod_name == "heat_tpu" or mod_name.startswith("heat_tpu.")


def _manifest(est: BaseEstimator, prefix: str, ctx: _SaveContext):
    cls = type(est)
    mod = cls.__module__
    if not _is_heat_tpu_module(mod):
        # _resolve_class refuses non-heat_tpu imports on load; failing
        # only there would let the save "succeed" and error much later
        # with a confusing message — reject at save time instead
        raise TypeError(
            f"cannot checkpoint {mod}.{cls.__qualname__}: only heat_tpu "
            "estimator classes are re-importable at load time"
        )
    out: Dict[str, Any] = {
        "class": f"{cls.__module__}:{cls.__qualname__}",
        "params": {},
        "fitted": {},
    }
    params = est.get_params(deep=False)
    for name, value in params.items():
        out["params"][name] = _encode(value, f"{prefix}params/{name}", ctx)
    for name in est._checkpoint_attrs():
        if name in params or not hasattr(est, name):
            continue
        out["fitted"][name] = _encode(
            getattr(est, name), f"{prefix}fitted/{name}", ctx
        )
    return out


def save_estimator(est: BaseEstimator, path: str) -> None:
    """Write ``est`` — constructor params plus fitted state — to one HDF5
    file.  Safe on multihost: every dataset and the manifest go through
    one lockstep writer pass with a single failure-propagation barrier
    (io._save_hdf5_many)."""
    if not _io.supports_hdf5():
        raise RuntimeError("h5py is required for estimator checkpointing")
    if not isinstance(est, BaseEstimator):
        raise TypeError(f"est must be a BaseEstimator, got {type(est)}")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    import os

    if os.path.splitext(path)[-1].strip().lower() not in _io.HDF5_EXTENSIONS:
        # guard EVERY entry point (est.save, ht.save, save_estimator):
        # HDF5 bytes under a .nc/.csv name would misdirect the loader
        raise ValueError("estimator checkpoints are HDF5: use a .h5/.hdf5 path")

    ctx = _SaveContext()
    manifest = {
        "format_version": _FORMAT_VERSION,
        "root": _manifest(est, "", ctx),
    }
    if _tel.enabled:
        _tel.inc("checkpoint.saves")
        with _tel.span("ckpt:save_estimator", cls=type(est).__name__, path=path):
            _io._save_hdf5_many(
                path,
                sorted(ctx.datasets.items()),
                attrs={_MANIFEST_ATTR: json.dumps(manifest)},
            )
        _tel.record_event(
            "checkpoint", site=type(est).__name__, op="save", path=path
        )
        return
    _io._save_hdf5_many(
        path,
        sorted(ctx.datasets.items()),
        attrs={_MANIFEST_ATTR: json.dumps(manifest)},
    )


def list_checkpoints(directory: str):
    """Scan one directory (non-recursively) for estimator checkpoints.

    Returns one dict per HDF5 file carrying an estimator manifest, sorted
    by filename: ``{"path", "file", "format_version", "class"}`` with
    ``class`` the root estimator's ``module:qualname``.  Files without an
    HDF5 extension are skipped, as are valid HDF5 *data* files (no
    manifest attribute).  An HDF5-named file that cannot be opened, or
    whose manifest attribute is not valid JSON, raises ``ValueError``
    naming the offending file — a registry root must surface a corrupted
    model version, not silently drop it.  Opens go through the same
    seeded-retry policy as :func:`load_estimator`, so a transient EIO
    heals instead of failing the scan.
    """
    if not _io.supports_hdf5():
        raise RuntimeError("h5py is required for estimator checkpointing")
    import os

    import h5py

    if not os.path.isdir(directory):
        raise ValueError(f"{directory} is not a directory")
    out = []
    for name in sorted(os.listdir(directory)):
        if os.path.splitext(name)[-1].strip().lower() not in _io.HDF5_EXTENSIONS:
            continue
        path = os.path.join(directory, name)

        def _open(path=path):
            _io._faults().io_open(path)
            return h5py.File(path, "r")

        try:
            f = _io._retry_open(_open, "checkpoint.list_checkpoints")
        except OSError as e:
            raise ValueError(
                f"{path} is not a readable checkpoint file (missing, "
                f"truncated, or not HDF5): {e}"
            ) from e
        with f:
            raw = f.attrs.get(_MANIFEST_ATTR)
        if raw is None:
            continue
        try:
            manifest = json.loads(raw)
        except (TypeError, ValueError) as e:
            raise ValueError(f"{path}: corrupt estimator manifest: {e}") from e
        if not isinstance(manifest, dict):
            raise ValueError(
                f"{path}: corrupt estimator manifest: expected a JSON "
                f"object, got {type(manifest).__name__}"
            )
        root = manifest.get("root")
        out.append(
            {
                "path": path,
                "file": name,
                "format_version": manifest.get(
                    "format_version", manifest.get("format")
                ),
                "class": root.get("class") if isinstance(root, dict) else None,
            }
        )
    return out


def _resolve_class(class_path: str):
    mod_name, _, qual = class_path.partition(":")
    if not _is_heat_tpu_module(mod_name):
        raise ValueError(
            f"refusing to import estimator class from {mod_name!r} "
            "(only heat_tpu estimators are loadable)"
        )
    mod = importlib.import_module(mod_name)
    obj: Any = mod
    for part in qual.split("."):
        obj = getattr(obj, part)
    if not (isinstance(obj, type) and issubclass(obj, BaseEstimator)):
        raise TypeError(f"{class_path} is not a BaseEstimator subclass")
    return obj


def _decode(entry: Dict[str, Any], path: str, cache: Dict[str, Any]):
    kind = entry["kind"]
    if kind == "scalar":
        value = entry["value"]
        if entry.get("tuple"):
            value = tuple(value)
        return value
    if kind == "nparray":
        return np.asarray(entry["data"], dtype=np.dtype(entry["dtype"])).reshape(
            entry["shape"]
        )
    if kind == "dndarray":
        key = entry["key"]
        if key not in cache:
            dtype = getattr(types, entry["dtype"])
            try:
                cache[key] = _io.load_hdf5(path, key, dtype=dtype, split=entry["split"])
            except KeyError as e:
                raise ValueError(
                    f"{path}: checkpoint dataset {key!r} is missing "
                    "(truncated or corrupted save)"
                ) from e
        return cache[key]
    if kind == "nparray_dataset":
        key = entry["key"]
        if key not in cache:
            dtype = getattr(types, entry["heat_dtype"])
            try:
                loaded = _io.load_hdf5(path, key, dtype=dtype, split=None)
            except KeyError as e:
                raise ValueError(
                    f"{path}: checkpoint dataset {key!r} is missing "
                    "(truncated or corrupted save)"
                ) from e
            cache[key] = loaded.numpy().astype(np.dtype(entry["dtype"]))
        return cache[key]
    if kind == "estimator":
        return _instantiate(entry["manifest"], path, cache)
    raise ValueError(f"unknown checkpoint entry kind {kind!r}")


def _instantiate(
    manifest: Dict[str, Any], path: str, cache: Dict[str, Any]
) -> BaseEstimator:
    cls = _resolve_class(manifest["class"])
    kwargs = {
        name: _decode(entry, path, cache)
        for name, entry in manifest["params"].items()
    }
    est = cls(**kwargs)
    for name, entry in manifest["fitted"].items():
        setattr(est, name, _decode(entry, path, cache))
    return est


def load_estimator(path: str) -> BaseEstimator:
    """Reconstruct an estimator saved by :func:`save_estimator`: the class
    is re-imported, constructed from its saved parameters (DNDarray
    params load with their recorded split), and the fitted attributes —
    including nested fitted estimators — are restored.  Arrays the save
    deduplicated load once and are re-linked."""
    if not _io.supports_hdf5():
        raise RuntimeError("h5py is required for estimator checkpointing")
    import h5py

    def _open():
        _io._faults().io_open(path)
        return h5py.File(path, "r")

    try:
        # transient EIO at the open heals under the bounded, seeded retry
        # policy; only an exhausted policy surfaces as the ValueError below
        f = _io._retry_open(_open, "checkpoint.load_estimator")
    except OSError as e:
        raise ValueError(
            f"{path} is not a readable estimator checkpoint (missing, "
            f"truncated, or not HDF5): {e}"
        ) from e
    with f:
        raw = f.attrs.get(_MANIFEST_ATTR)
        if raw is None:
            raise ValueError(f"{path} is not an estimator checkpoint")
        manifest = json.loads(raw)
    # v2 writes format_version; v1 recorded it under the legacy "format"
    version = manifest.get("format_version", manifest.get("format"))
    if version not in _READABLE_VERSIONS:
        raise ValueError(
            f"{path}: unsupported checkpoint format_version {version!r} "
            f"(this build reads versions {list(_READABLE_VERSIONS)})"
        )
    if _tel.enabled:
        _tel.inc("checkpoint.loads")
        with _tel.span("ckpt:load_estimator", path=path):
            est = _instantiate(manifest["root"], path, {})
        _tel.record_event(
            "checkpoint", site=type(est).__name__, op="load", path=path
        )
        return est
    return _instantiate(manifest["root"], path, {})
