"""Global string representations for DNDarrays.

Reference: heat/core/printing.py:20-164 — there, a full print gathers via
``resplit_(None)`` and a summarized print has each rank extract edge items
followed by a rank-0 gather (:77-135).  In the single-controller model the
global array is directly addressable, so printing is numpy formatting of
(a summary of) the global array; XLA fetches only the shards the host
touches.
"""

from __future__ import annotations

import numpy as np

__all__ = ["get_printoptions", "set_printoptions"]

# torch-style default print options (reference printing.py:10-18)
__PRINT_OPTIONS = {
    "precision": 4,
    "threshold": 1000,
    "edgeitems": 3,
    "linewidth": 120,
    "sci_mode": None,
}


def get_printoptions() -> dict:
    """View of the current print options."""
    return dict(__PRINT_OPTIONS)


def set_printoptions(
    precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None
):
    """Configure printing (reference printing.py:20-57)."""
    if profile == "default":
        __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
    elif profile == "short":
        __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
    elif profile == "full":
        __PRINT_OPTIONS.update(precision=4, threshold=float("inf"), edgeitems=3, linewidth=120)
    for key, val in (
        ("precision", precision),
        ("threshold", threshold),
        ("edgeitems", edgeitems),
        ("linewidth", linewidth),
        ("sci_mode", sci_mode),
    ):
        if val is not None:
            __PRINT_OPTIONS[key] = val


def __str__(x) -> str:
    """Format a DNDarray (reference printing.py:58-163)."""
    arr = np.asarray(x.larray)
    opts = __PRINT_OPTIONS
    body = np.array2string(
        arr,
        precision=opts["precision"],
        threshold=opts["threshold"],
        edgeitems=opts["edgeitems"],
        max_line_width=opts["linewidth"],
        separator=", ",
    )
    tail = [f"dtype=ht.{x.dtype.__name__}", f"device={x.device}", f"split={x.split}"]
    return f"DNDarray({body}, {', '.join(tail)})"
