"""Elementwise comparisons.

Reference: heat/core/relational.py:9-254 — all via ``__binary_op``; results
are uint8 there (torch legacy); here they are ``ht.bool`` (numpy semantics),
a documented divergence.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "gt", "le", "lt", "ne"]


def eq(t1, t2):
    """Elementwise == (reference relational.py:9-54)."""
    return _operations.__binary_op(jnp.equal, t1, t2)


def equal(t1, t2) -> bool:
    """True iff both arrays are identical in shape and value
    (reference relational.py:55-94: local equal + MPI LAND)."""
    if isinstance(t1, DNDarray):
        a1 = t1.larray
    else:
        a1 = jnp.asarray(t1)
    if isinstance(t2, DNDarray):
        a2 = t2.larray
    else:
        a2 = jnp.asarray(t2)
    if tuple(a1.shape) != tuple(a2.shape):
        return False
    return bool(jnp.all(a1 == a2))


def ge(t1, t2):
    """Elementwise >= (reference relational.py:95-140)."""
    return _operations.__binary_op(jnp.greater_equal, t1, t2)


def gt(t1, t2):
    """Elementwise > (reference relational.py:141-186)."""
    return _operations.__binary_op(jnp.greater, t1, t2)


def le(t1, t2):
    """Elementwise <= (reference relational.py:187-212)."""
    return _operations.__binary_op(jnp.less_equal, t1, t2)


def lt(t1, t2):
    """Elementwise < (reference relational.py:213-238)."""
    return _operations.__binary_op(jnp.less, t1, t2)


def ne(t1, t2):
    """Elementwise != (reference relational.py:239-254)."""
    return _operations.__binary_op(jnp.not_equal, t1, t2)


# split semantics for heat_tpu.analysis.splitflow (see core/_split_semantics.py)
from ._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {"binary": ("eq", "ge", "gt", "le", "lt", "ne")},
)
