"""The generic operation engine behind every elementwise/reduction op.

Reference: heat/core/_operations.py:19-456 — four wrappers (``__binary_op``,
``__local_op``, ``__reduce_op``, ``__cum_op``) implement every op in the
framework.  There, each wrapper manages split alignment, Bcasts for
broadcasting across the split axis, neutral-element fills for empty chunks,
and the Allreduce for cross-split reductions.

On global jax arrays all of that disappears into XLA: broadcasting is
``jnp`` broadcasting, cross-shard reduction is a compiler-inserted
all-reduce, and empty chunks cannot exist.  What remains — and what these
wrappers implement — is the reference's *semantics*: dtype promotion rules,
split-axis bookkeeping for results, ``out=`` handling, and the split
compatibility policy.  One deliberate improvement: operands with different
split axes are auto-resharded instead of raising ``NotImplementedError``
(reference _operations.py:94-97), since resharding is a single XLA
collective here.
"""

from __future__ import annotations

import builtins
from typing import Callable, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import factories, sanitation, types
from ._compile import cache_stable, jitted
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = ["__binary_op", "__local_op", "__reduce_op", "__cum_op"]


def _freeze(kwargs: dict):
    """Hashable view of an op's static kwargs, or None if not hashable
    (→ caller falls back to eager dispatch)."""
    try:
        items = tuple(sorted(kwargs.items()))
        hash(items)
        return items
    except TypeError:
        return None


def _is_padded(t) -> bool:
    """True when ``t`` is a DNDarray whose at-rest buffer carries a padded
    (ragged) split axis."""
    return (
        isinstance(t, DNDarray)
        and t.split is not None
        and t.padshape != t.gshape
    )


def _binary_arrays(t1, t2, anchor):
    """Choose the compute arrays for a binary op.

    When the anchor's at-rest buffer is padded and the other operand's
    padding lines up (same padded split axis, or broadcast dim 1/absent
    there, or a scalar), the op runs directly on the buffers: elementwise
    garbage in the pad rows stays in the pad rows, and the result commits
    sharded with NO boundary collective.  Anything misaligned falls back
    to the true-shape views (correct, but committing a ragged result costs
    the boundary).

    Returns ``(a1, a2, fused)``.
    """

    def true_view(t):
        if np.isscalar(t):
            return t
        return t.larray if isinstance(t, DNDarray) else jnp.asarray(t)

    if not _is_padded(anchor):
        return true_view(t1), true_view(t2), False
    s = anchor.split
    n = anchor.gshape[s]
    pn = anchor.padshape[s]

    def aligned(t):
        if t is anchor or np.isscalar(t):
            return True
        if not isinstance(t, DNDarray):
            return False
        if t.split is not None:
            # must be the same padded axis at the same position and length
            return (
                t.ndim == anchor.ndim
                and t.split == s
                and t.gshape[s] == n
                and t.padshape[s] == pn
            )
        # replicated: the dim aligning with the padded axis (right-aligned
        # broadcasting) must be 1 or absent
        d = s - (anchor.ndim - t.ndim)
        return d < 0 or t.gshape[d] == 1

    if aligned(t1) and aligned(t2):
        a1 = t1 if np.isscalar(t1) else t1._buffer
        a2 = t2 if np.isscalar(t2) else t2._buffer
        return a1, a2, True
    return true_view(t1), true_view(t2), False


def _canonical_result(result):
    """Map op results whose jnp dtype has no heat analog back into the
    lattice.  jax promotes unsigned accumulations to uint16/32/64; the heat
    hierarchy — like the reference's (types.py:62-210) — carries uint8 as
    its only unsigned type, and the reference's torch kernels return int64
    for integer reductions, so wide-unsigned results cast to int64."""
    kind = np.dtype(result.dtype).kind
    if kind == "u" and np.dtype(result.dtype).itemsize > 1:
        return result.astype(jnp.int64)
    return result


def __binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Generic elementwise binary op (reference _operations.py:19-171).

    Performs scalar promotion, split resolution, heat dtype promotion, the
    jnp computation on global arrays (XLA handles any cross-shard
    broadcast — the reference's explicit ``Bcast`` at :103-125), and result
    wrapping.
    """
    fn_kwargs = fn_kwargs or {}

    scalar_1 = np.isscalar(t1)
    scalar_2 = np.isscalar(t2)
    if scalar_1 and scalar_2:
        # pure scalars: compute and wrap (reference :40-56)
        res = operation(jnp.asarray(t1), jnp.asarray(t2), **fn_kwargs)
        return factories.array(res)

    if scalar_1:
        anchor = t2
    elif isinstance(t1, DNDarray):
        anchor = t1
        if isinstance(t2, DNDarray):
            if t1.split is None and t2.split is not None:
                # replicated (op) split: the result carries the non-None
                # split (reference :85-97 — a replicated operand adopts
                # the other's layout).  Anchoring on the replicated side
                # would also GATHER the split operand — strictly worse.
                anchor = t2
            elif (
                t2.split is not None
                and t2.split != t1.split
                and t1.ndim == t2.ndim
            ):
                # both split, differently: reshard t2 to t1's layout (the
                # reference raises here; one XLA collective instead).  A
                # replicated t2 is excluded: GSPMD consumes it in place,
                # and resharding it would be a pointless eager dispatch.
                t2 = t2.resplit(t1.split)
    else:
        raise TypeError(f"expected a DNDarray or scalar, got {type(t1)}")
    if not isinstance(anchor, DNDarray):
        raise TypeError(f"expected a DNDarray or scalar, got {type(anchor)}")

    a1, a2, fused = _binary_arrays(t1, t2, anchor)

    # heat dtype promotion (reference :138; delegated to the jax lattice,
    # which implements the same torch-flavored rules).  Python scalars go
    # straight into the jitted executable as ARGUMENTS: jax traces them as
    # weak-typed 0-d values, so one compiled program serves every scalar
    # value AND the weak-promotion result dtype matches the eager jnp
    # semantics — the r3 wrapper pre-cast them through jnp.asarray +
    # result_type instead, which profiling showed was ~60% of the whole
    # eager per-op cost (VERDICT r3 #7).
    statics = _freeze(fn_kwargs)
    # `operation` in the key is safe only for cache-stable callables
    # (module-level jnp functions); unstable ones take the eager path
    if statics is not None and cache_stable(operation):
        fn = jitted(
            ("binary", operation, statics),  # spmdlint: disable=SPMD401
            lambda: lambda x, y: operation(x, y, **fn_kwargs),
        )
        try:
            result = fn(a1, a2)
        except (OverflowError, TypeError):
            # e.g. uint8 array + 2**70: the weak scalar cannot trace —
            # eager jnp reproduces the wrap/raise semantics
            result = operation(a1, a2, **fn_kwargs)
    else:
        result = operation(a1, a2, **fn_kwargs)
    result = _canonical_result(result)
    out_dtype = types.canonical_heat_type(result.dtype)

    # split of the result: anchor's split, adjusted for broadcasting
    split = anchor.split
    if split is not None:
        # broadcasting may prepend dims: re-anchor split from the right
        split = split + (result.ndim - anchor.ndim)
        if split < 0 or result.ndim == 0:
            split = None
    comm = anchor.comm
    device = anchor.device
    result = comm.apply_sharding(result, split)
    if fused:
        # buffers computed padded: the wrap's gshape is the broadcast of
        # the TRUE shapes (the padded result is the at-rest buffer)
        s1 = () if np.isscalar(t1) else tuple(t1.shape)
        s2 = () if np.isscalar(t2) else tuple(t2.shape)
        true_shape = broadcast_shape(s1, s2)
    else:
        true_shape = tuple(result.shape)
    wrapped = DNDarray(result, true_shape, out_dtype, split, device, comm, True)

    if out is not None:
        sanitation.sanitize_out(out, wrapped.shape, wrapped.split, device)
        out.larray = wrapped.larray.astype(out.dtype.jax_type())
        return out
    return wrapped


def __local_op(
    operation: Callable,
    x,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    **kwargs,
) -> DNDarray:
    """Generic comm-free elementwise map, e.g. sin/exp
    (reference _operations.py:266-335).

    Float-promotes exact input types unless ``no_cast`` (reference :295-300).
    """
    sanitation.sanitize_in(x)
    if out is not None and not isinstance(out, DNDarray):
        raise TypeError(f"expected out to be None or a DNDarray, but was {type(out)}")

    padded = _is_padded(x)
    arr = x._buffer if padded else x.larray
    cast = None
    if not no_cast and types.heat_type_is_exact(x.dtype):
        cast = jnp.float32 if x.dtype is not types.int64 else jnp.float64
    statics = _freeze(kwargs)
    # keyed on `operation` only when cache-stable, else eager (SPMD401)
    if statics is not None and cache_stable(operation):
        fn = jitted(
            ("local", operation, cast, statics),  # spmdlint: disable=SPMD401
            lambda: lambda a: operation(a.astype(cast) if cast else a, **kwargs),
        )
        result = fn(arr)
    else:
        result = operation(arr.astype(cast) if cast else arr, **kwargs)
    result = _canonical_result(result)
    dtype = types.canonical_heat_type(result.dtype)
    # _layout keeps grid splits tuples intact (the compat int would drop
    # every mesh axis past the first and mis-unpad the result)
    result = x.comm.apply_sharding(result, x._layout if result.ndim else None)
    if padded:
        if tuple(result.shape) == tuple(arr.shape):
            # elementwise on the padded buffer: result IS the at-rest buffer
            gshape = x.gshape
        else:
            # a shape-changing op slipped through on a padded buffer — the
            # pad rows may have leaked into the result; redo on the true view
            arr = x.larray
            result = _canonical_result(
                operation(arr.astype(cast) if cast else arr, **kwargs)
            )
            dtype = types.canonical_heat_type(result.dtype)
            result = x.comm.apply_sharding(result, x._layout if result.ndim else None)
            gshape = tuple(result.shape)
    else:
        gshape = tuple(result.shape)
    wrapped = DNDarray(result, gshape, dtype, x._layout, x.device, x.comm, x.balanced)
    if out is not None:
        sanitation.sanitize_out(out, wrapped.shape, wrapped.split, x.device)
        out.larray = wrapped.larray.astype(out.dtype.jax_type())
        return out
    return wrapped


def __reduce_op(
    reduction: Callable,
    x,
    axis,
    out: Optional[DNDarray] = None,
    neutral=None,
    keepdims: Optional[bool] = None,
    dtype=None,
    **kwargs,
) -> DNDarray:
    """Generic reduction (reference _operations.py:337-456).

    The reference computes a local partial then Allreduces across the split
    (:425-429) with neutral-element fills for empty chunks (:391-404); here
    the reduction runs on the global array and XLA inserts the all-reduce.
    Split bookkeeping matches the reference: reducing across the split axis
    yields split=None, otherwise the split index shifts down past removed
    axes.
    """
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    keepdims = bool(keepdims) if keepdims is not None else False

    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
    cast = dtype.jax_type() if dtype is not None else None

    # split bookkeeping first (reference :446-456) — the padded path needs
    # the result's split axis to re-pad inside the compiled program
    split = x.split
    if split is not None:
        axes = (axis,) if isinstance(axis, int) else (tuple(range(x.ndim)) if axis is None else axis)
        if split in axes:
            split = None
        elif not keepdims:
            split = split - builtins.sum(1 for a in axes if a < split)

    padded = _is_padded(x)
    pad_in = (x.split, x.gshape[x.split]) if padded else None
    out_split_pad = split if padded else None
    comm = x.comm
    statics = _freeze(kwargs)

    # collective-precision policy seam (heat_tpu.comm): a sum whose axes
    # cover the split needs a cross-device combine — when the policy asks
    # for compression, run local partials + the block-scaled quantized
    # ring in ONE program instead of letting GSPMD insert an exact
    # all-reduce.  Pad rows are zeros, so partial sums are exact; only
    # sum compresses (max/min/prod are not pad-safe or not linear).
    compressed = None
    if (
        split is None
        and x.split is not None
        and statics == ()
        and reduction is jnp.sum
        and comm.size > 1
    ):
        from ..comm import compressed as _cq

        axes_t = (
            (axis,)
            if isinstance(axis, int)
            else (tuple(range(x.ndim)) if axis is None else tuple(axis))
        )
        out_elems = 1
        for d, s in enumerate(x.gshape):
            if d not in axes_t:
                out_elems *= int(s)
        mode = _cq.reduce_mode(x._buffer.dtype, out_elems * 4)
        if mode is not None:
            compressed = _cq.reduce_q(
                x._buffer,
                comm=comm,
                split=x.split,
                axes=axes_t,
                keepdims=keepdims,
                mode=mode,
                out_dtype=cast or x._buffer.dtype,
            )
    # keyed on `reduction` only when cache-stable, else eager (SPMD401)
    if compressed is not None:
        result = compressed
        padded = False
    elif statics is not None and cache_stable(reduction):
        def make():
            def f(a):
                if pad_in is not None:
                    # slice the buffer to its true length INSIDE the program:
                    # pad rows never reach the reduction, and no boundary
                    # crossing materializes the ragged view
                    sl = [slice(None)] * a.ndim
                    sl[pad_in[0]] = slice(0, pad_in[1])
                    a = a[tuple(sl)]
                r = reduction(a, axis=axis, keepdims=keepdims, **kwargs)
                if cast is not None:
                    r = r.astype(cast)
                if out_split_pad is not None and r.ndim:
                    n_out = int(r.shape[out_split_pad])
                    pn = comm.padded_size(n_out)
                    if pn != n_out:
                        w = [(0, 0)] * r.ndim
                        w[out_split_pad] = (0, pn - n_out)
                        r = jnp.pad(r, w)
                        r = jax.lax.with_sharding_constraint(
                            r, comm.sharding(r.ndim, out_split_pad)
                        )
                return r

            return f

        fn = jitted(
            ("reduce", reduction, axis, keepdims, cast, statics, pad_in, out_split_pad,
             comm if padded else None),  # spmdlint: disable=SPMD401
            make,
        )
        result = fn(x._buffer if padded else x.larray)
    else:
        result = reduction(x.larray, axis=axis, keepdims=keepdims, **kwargs)
        if cast is not None:
            result = result.astype(cast)
        padded = False  # eager fallback computed on the true view
    result = _canonical_result(result)
    out_dtype = types.canonical_heat_type(result.dtype)

    if result.ndim == 0:
        split = None
    result = x.comm.apply_sharding(result, split)
    if padded and split is not None:
        gshape = list(result.shape)
        gshape[split] = x.gshape[x.split]  # surviving split axis: true length
        gshape = tuple(gshape)
    else:
        gshape = tuple(result.shape)
    wrapped = DNDarray(result, gshape, out_dtype, split, x.device, x.comm, True)

    if out is not None:
        sanitation.sanitize_out(out, wrapped.shape, wrapped.split, x.device)
        out.larray = wrapped.larray.astype(out.dtype.jax_type())
        return out
    return wrapped


def __cum_op(
    operation: Callable,
    x,
    axis: int,
    out: Optional[DNDarray] = None,
    dtype=None,
) -> DNDarray:
    """Generic cumulative op (reference _operations.py:173-264).

    The reference does local cumop + ``Exscan`` of each rank's last slice +
    local combine (:236-258); XLA's scan lowering performs the equivalent
    segmented scan across shards.
    """
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative operations require an explicit axis")
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
    cast = dtype.jax_type() if dtype is not None else None
    padded = _is_padded(x)
    scan_op = {jnp.cumsum: "sum", jnp.cumprod: "prod"}.get(operation)
    if scan_op is not None and axis == x.split and x.comm.size > 1:
        # cum-op ALONG the sharded axis: GSPMD's partitioned scan is
        # pathological (sequential per element) — use the explicit
        # two-level prefix scan (local cum-op + shard-offset all-gather)
        from ..parallel import prefix_scan

        # the at-rest buffer feeds the scan directly: pad rows TRAIL the
        # axis, so no real row's prefix ever includes one — garbage pads
        # only poison the totals of all-pad trailing shards, i.e. pad rows
        # of the result.  Going through .larray would commit the ragged
        # view replicated at the boundary first.
        result = prefix_scan(
            x._buffer if padded else x.larray, scan_op, comm=x.comm, axis=axis
        )
        if cast is not None:
            result = result.astype(cast)
        result = _canonical_result(result)
        out_dtype = types.canonical_heat_type(result.dtype)
        result = x.comm.apply_sharding(result, x.split)  # padded ⇒ divisible
    else:
        # any other axis is unpadded: the buffer feeds the op directly
        arr = x._buffer if padded and axis != x.split else x.larray
        if cache_stable(operation):
            fn = jitted(
                ("cum", operation, axis, cast),  # spmdlint: disable=SPMD401
                lambda: lambda a: (
                    lambda r: r.astype(cast) if cast is not None else r
                )(operation(a, axis=axis)),
            )
            result = fn(arr)
        else:
            result = operation(arr, axis=axis)
            if cast is not None:
                result = result.astype(cast)
        result = _canonical_result(result)
        out_dtype = types.canonical_heat_type(result.dtype)
        result = x.comm.apply_sharding(result, x.split)
    wrapped = DNDarray(result, x.gshape, out_dtype, x.split, x.device, x.comm, x.balanced)
    if out is not None:
        sanitation.sanitize_out(out, wrapped.shape, wrapped.split, x.device)
        out.larray = wrapped.larray.astype(out.dtype.jax_type())
        return out
    return wrapped
