"""Reproducible counter-based random number generation.

Reference: heat/core/random.py:25-822 — a stateless Threefry-2x32/64
counter-based RNG whose outputs are identical regardless of process count:
a global 128-bit (seed, counter) state maps each rank's chunk of the global
index space to counter vectors, which Threefry encrypts (:638-798).

JAX's PRNG **is** threefry counter-based — the same design (this is the
"RNG is a gift" correspondence noted in SURVEY.md §7).  The global (seed,
counter) state lives here; each draw folds the counter into the key and
advances it by the number of elements drawn, so results are reproducible
and independent of the mesh size — the reference's defining RNG property —
while generation itself runs sharded on device.

Divergence (documented): normal sampling uses JAX's native algorithm, not
the reference's Kundu transform (random.py:218); moments and distribution
are equivalent, exact streams differ.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import devices as _devices
from . import factories, types
from .communication import comm_for_device, sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "get_state",
    "permutation",
    "rand",
    "randint",
    "randn",
    "random",
    "random_sample",
    "randperm",
    "ranf",
    "sample",
    "seed",
    "set_state",
    "uniform",
]

# global RNG state: (seed, counter) — reference random.py:16-24
__seed: int = 0
__counter: int = 0


def seed(seed: Optional[int] = None) -> None:
    """(Re-)seed the global generator (reference random.py:588-605)."""
    global __seed, __counter
    if seed is None:
        seed = int(np.random.SeedSequence().entropy % (2**63))
    __seed = int(seed)
    __counter = 0


def get_state() -> Tuple[str, int, int, int, float]:
    """Return the generator state tuple
    (reference random.py:163-179: ('Threefry', seed, counter, 0, 0.0))."""
    return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple) -> None:
    """Restore a state tuple (reference random.py:606-637)."""
    global __seed, __counter
    if not isinstance(state, tuple) or len(state) not in (3, 5):
        raise ValueError("state needs to be a 3- or 5-tuple")
    if state[0] != "Threefry":
        raise ValueError("algorithm must be 'Threefry'")
    __seed = int(state[1])
    __counter = int(state[2])


def _consume(n: int) -> jax.Array:
    """Fold the current counter into the key and advance it by ``n``
    elements (the counter-advancement contract of reference
    random.py:25-163)."""
    global __counter
    key = jax.random.fold_in(jax.random.PRNGKey(__seed), __counter % (2**31))
    __counter += int(n)
    return key


def _finalize(garr, dtype, split, device, comm) -> DNDarray:
    device = _devices.sanitize_device(device)
    comm = comm_for_device(device.platform) if comm is None else sanitize_comm(comm)
    garr = comm.apply_sharding(garr, split if garr.ndim else None)
    return DNDarray(garr, tuple(garr.shape), dtype, split, device, comm, True)


def rand(*args, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples (reference random.py:319-382)."""
    shape = sanitize_shape(args) if args else ()
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.float32, types.float64, types.bfloat16, types.float16):
        raise ValueError(f"Unsupported dtype {dtype.__name__} for rand")
    split = sanitize_axis(shape, split)
    n = int(np.prod(shape)) if shape else 1
    key = _consume(n)
    garr = jax.random.uniform(key, shape, dtype=dtype.jax_type())
    return _finalize(garr, dtype, split, device, comm)


def random_sample(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples for a shape tuple
    (reference random.py:550-585; aliases ``random``/``ranf``/``sample``;
    no/empty shape yields a single sample of shape (1,) as there)."""
    # falsy shapes (None, (), 0) all yield one sample, matching the
    # reference's `if not shape` exactly (diverges from numpy, which
    # returns an empty array for shape=0)
    if not shape:
        shape = (1,)
    shape = sanitize_shape(shape)
    return rand(*shape, dtype=dtype, split=split, device=device, comm=comm)


random = ranf = sample = random_sample


def uniform(low=0.0, high=1.0, size=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [low, high) samples (reference random.py: uniform wrapper)."""
    size = () if size is None else sanitize_shape(size)
    r = rand(*size, dtype=dtype, split=split, device=device, comm=comm)
    if low != 0.0 or high != 1.0:
        from . import arithmetics

        r = arithmetics.add(arithmetics.mul(r, high - low), low)
    return r


def randint(
    low,
    high=None,
    size=None,
    dtype=types.int32,
    split=None,
    device=None,
    comm=None,
) -> DNDarray:
    """Uniform random integers in [low, high) (reference random.py:383-462)."""
    if high is None:
        low, high = 0, low
    if size is None:
        size = ()
    elif isinstance(size, (int, np.integer)):
        size = (int(size),)
    size = sanitize_shape(size)
    if low >= high:
        raise ValueError(f"low >= high ({low} >= {high})")
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.int64, types.int32, types.int16, types.int8, types.uint8):
        raise ValueError(f"Unsupported dtype {dtype.__name__} for randint")
    split = sanitize_axis(size, split)
    n = int(np.prod(size)) if size else 1
    key = _consume(n)
    garr = jax.random.randint(key, size, int(low), int(high), dtype=dtype.jax_type())
    return _finalize(garr, dtype, split, device, comm)


def randn(*args, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples (reference random.py:463-510; Kundu transform
    :218-241 replaced by JAX's native normal — documented divergence)."""
    shape = sanitize_shape(args) if args else ()
    dtype = types.canonical_heat_type(dtype)
    split = sanitize_axis(shape, split)
    n = int(np.prod(shape)) if shape else 1
    key = _consume(n)
    garr = jax.random.normal(key, shape, dtype=dtype.jax_type())
    return _finalize(garr, dtype, split, device, comm)


def randperm(n: int, dtype=types.int64, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of arange(n) (reference random.py:511-555)."""
    if not isinstance(n, (int, np.integer)):
        raise TypeError(f"n must be an integer, got {type(n)}")
    dtype = types.canonical_heat_type(dtype)
    key = _consume(int(n))
    garr = jax.random.permutation(key, int(n)).astype(dtype.jax_type())
    split = sanitize_axis((int(n),), split)
    return _finalize(garr, dtype, split, device, comm)


def permutation(x, split=None, device=None, comm=None) -> DNDarray:
    """Permute a sequence or shuffle an array along axis 0
    (reference random.py:242-318)."""
    if isinstance(x, (int, np.integer)):
        return randperm(int(x), split=split, device=device, comm=comm)
    if isinstance(x, DNDarray):
        key = _consume(x.shape[0] if x.ndim else 1)
        garr = jax.random.permutation(key, x.larray, axis=0)
        return _finalize(garr, x.dtype, x.split if split is None else split, device or x.device, comm or x.comm)
    arr = jnp.asarray(np.asarray(x))
    key = _consume(arr.shape[0] if arr.ndim else 1)
    garr = jax.random.permutation(key, arr, axis=0)
    return _finalize(garr, types.canonical_heat_type(garr.dtype), split, device, comm)
