"""Tile decompositions of sharded arrays.

Reference: heat/core/tiling.py:9-1258 — ``SplitTiles`` (one tile per
rank × split-slab, used by ``resplit_``) and ``SquareDiagTiles``
(diagonal-aligned tiles driving the tiled QR).

In the TPU design both consumers are gone: ``resplit`` is a single XLA
reshard and QR is TSQR (see linalg/qr.py).  What remains useful — and what
this module provides — is the *geometry*: a queryable map from mesh
positions to global index ranges, used by IO, diagnostics, and tests.
``SplitTiles`` is fully functional; ``SquareDiagTiles`` provides the
diagonal-aligned tile grid geometry (without the QR-internal caching
machinery the reference couples it to).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

__all__ = ["SplitTiles", "SquareDiagTiles"]


class SplitTiles:
    """One tile per (mesh position × split slab)
    (reference tiling.py:9-302).

    For an array split along one axis over ``size`` positions, the tile
    grid is the cartesian product of each dimension's shard boundaries.
    """

    def __init__(self, arr):
        self.__arr = arr
        comm, shape = arr.comm, arr.shape
        size = comm.size
        # per-dimension cut points: the split axis uses the shard boundaries,
        # other axes are a single slab (reference tile_ends_g, tiling.py:36-60)
        ends = []
        for dim, n in enumerate(shape):
            if dim == arr.split:
                cuts = []
                for r in range(size):
                    off, lshape, _ = comm.chunk(shape, dim, rank=r)
                    cuts.append(off + lshape[dim])
                ends.append(np.asarray(cuts, dtype=np.int64))
            else:
                ends.append(np.asarray([n], dtype=np.int64))
        self.__tile_ends = ends

    @property
    def arr(self):
        return self.__arr

    @property
    def tile_ends_g(self) -> List[np.ndarray]:
        """Global end index of every tile along every dimension."""
        return self.__tile_ends

    @property
    def tile_locations(self) -> np.ndarray:
        """Owner mesh position of each tile along the split axis
        (reference tiling.py:90-123)."""
        arr = self.__arr
        if arr.split is None:
            return np.zeros(tuple(len(e) for e in self.__tile_ends), dtype=np.int64)
        shape = tuple(len(e) for e in self.__tile_ends)
        owners = np.zeros(shape, dtype=np.int64)
        idx = [slice(None)] * len(shape)
        for r in range(shape[arr.split]):
            idx[arr.split] = r
            owners[tuple(idx)] = r
        return owners

    def tile_slices(self, pos: Tuple[int, ...]) -> Tuple[slice, ...]:
        """Global-coordinate slices of the tile at grid position ``pos``."""
        slices = []
        for dim, p in enumerate(pos):
            ends = self.__tile_ends[dim]
            start = 0 if p == 0 else int(ends[p - 1])
            slices.append(slice(start, int(ends[p])))
        return tuple(slices)

    def __getitem__(self, key):
        """The tile's data (a jax array view) at grid position ``key``
        (reference tiling.py:160-302)."""
        if isinstance(key, int):
            key = (key,)
        pos = list(key) + [0] * (len(self.__tile_ends) - len(key))
        return self.__arr.larray[self.tile_slices(tuple(pos))]


class SquareDiagTiles:
    """Diagonal-aligned square tile grid (reference tiling.py:303-1258).

    Computes the reference's width-matched row/column tile decomposition
    where tiles along the global diagonal are square (``tiles_per_proc``
    knob, reference :344).  The QR driver that consumed the caching/
    match_tiles machinery is replaced by TSQR; the geometry remains for
    introspection and for algorithms that want diagonal-aligned blocking.
    """

    def __init__(self, arr, tiles_per_proc: int = 1):
        if arr.ndim != 2:
            raise ValueError("SquareDiagTiles requires a 2-D DNDarray")
        if tiles_per_proc < 1:
            raise ValueError("tiles_per_proc must be >= 1")
        self.__arr = arr
        comm = arr.comm
        size = comm.size
        m, n = arr.shape
        k = min(m, n)
        # divide the diagonal extent into size * tiles_per_proc near-equal tiles
        ntiles = max(size * tiles_per_proc, 1)
        base = k // ntiles
        rem = k % ntiles
        widths = [base + (1 if i < rem else 0) for i in range(ntiles)]
        widths = [w for w in widths if w > 0]
        row_ends = list(np.cumsum(widths))
        if row_ends and row_ends[-1] < m:
            row_ends[-1] = m  # last row tile absorbs the overhang
        col_ends = list(np.cumsum(widths))
        if col_ends and col_ends[-1] < n:
            col_ends[-1] = n
        self.__row_ends = row_ends
        self.__col_ends = col_ends
        self.__tiles_per_proc = tiles_per_proc

    @property
    def arr(self):
        return self.__arr

    @property
    def tiles_per_proc(self) -> int:
        return self.__tiles_per_proc

    @property
    def row_indices(self) -> List[int]:
        """Global start row of each tile row (reference :700-740)."""
        return [0] + self.__row_ends[:-1]

    @property
    def col_indices(self) -> List[int]:
        """Global start column of each tile column."""
        return [0] + self.__col_ends[:-1]

    def get_start_stop(self, key: Tuple[int, int]) -> Tuple[int, int, int, int]:
        """(row_start, row_stop, col_start, col_stop) of tile ``key``
        (reference tiling.py:810-930)."""
        r, c = key
        rs = 0 if r == 0 else self.__row_ends[r - 1]
        cs = 0 if c == 0 else self.__col_ends[c - 1]
        return int(rs), int(self.__row_ends[r]), int(cs), int(self.__col_ends[c])

    def __getitem__(self, key) -> "np.ndarray":
        """Tile data at (row, col) (reference local_get, tiling.py:933)."""
        rs, re, cs, ce = self.get_start_stop(key)
        return self.__arr.larray[rs:re, cs:ce]

    def local_get(self, key):
        """Alias of ``__getitem__`` (reference tiling.py:933-955)."""
        return self[key]
