"""Tile decompositions of sharded arrays.

Reference: heat/core/tiling.py:9-1258 — ``SplitTiles`` (one tile per
rank × split-slab, used by ``resplit_``) and ``SquareDiagTiles``
(diagonal-aligned tiles driving the tiled QR).

In the TPU design both consumers are gone: ``resplit`` is a single XLA
reshard and QR is TSQR (see linalg/qr.py).  What remains useful — and what
this module provides — is the *geometry*: a queryable map from mesh
positions to global index ranges, used by IO, diagnostics, and tests.
``SplitTiles`` is fully functional; ``SquareDiagTiles`` provides the
diagonal-aligned tile grid geometry (without the QR-internal caching
machinery the reference couples it to).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

__all__ = ["SplitTiles", "SquareDiagTiles"]


class SplitTiles:
    """One tile per (mesh position × split slab)
    (reference tiling.py:9-302).

    For an array split along one axis over ``size`` positions, the tile
    grid is the cartesian product of each dimension's shard boundaries.
    """

    def __init__(self, arr):
        self.__arr = arr
        comm, shape = arr.comm, arr.shape
        size = comm.size
        # per-dimension cut points: the split axis uses the shard boundaries,
        # other axes are a single slab (reference tile_ends_g, tiling.py:36-60)
        ends = []
        for dim, n in enumerate(shape):
            if dim == arr.split:
                cuts = []
                for r in range(size):
                    off, lshape, _ = comm.chunk(shape, dim, rank=r)
                    cuts.append(off + lshape[dim])
                ends.append(np.asarray(cuts, dtype=np.int64))
            else:
                ends.append(np.asarray([n], dtype=np.int64))
        self.__tile_ends = ends

    @property
    def arr(self):
        return self.__arr

    @property
    def tile_ends_g(self) -> List[np.ndarray]:
        """Global end index of every tile along every dimension."""
        return self.__tile_ends

    @property
    def tile_locations(self) -> np.ndarray:
        """Owner mesh position of each tile along the split axis
        (reference tiling.py:90-123)."""
        arr = self.__arr
        if arr.split is None:
            return np.zeros(tuple(len(e) for e in self.__tile_ends), dtype=np.int64)
        shape = tuple(len(e) for e in self.__tile_ends)
        owners = np.zeros(shape, dtype=np.int64)
        idx = [slice(None)] * len(shape)
        for r in range(shape[arr.split]):
            idx[arr.split] = r
            owners[tuple(idx)] = r
        return owners

    def tile_slices(self, pos: Tuple[int, ...]) -> Tuple[slice, ...]:
        """Global-coordinate slices of the tile at grid position ``pos``
        (partial keys select position 0 on the omitted trailing dims, like
        ``__getitem__``)."""
        if isinstance(pos, (int, np.integer)):
            pos = (pos,)
        pos = tuple(pos) + (0,) * (len(self.__tile_ends) - len(pos))
        slices = []
        for dim, p in enumerate(pos):
            if not isinstance(p, (int, np.integer)):
                raise TypeError(
                    f"tile keys must be ints, got {type(p)}"
                )  # reference tiling.py:166-171
            ends = self.__tile_ends[dim]
            start = 0 if p == 0 else int(ends[p - 1])
            slices.append(slice(start, int(ends[p])))
        return tuple(slices)

    def __getitem__(self, key):
        """The tile's data (a jax array view) at grid position ``key``
        (reference tiling.py:160-302)."""
        return self.__arr.larray[self.tile_slices(key)]

    def __setitem__(self, key, value):
        """Overwrite the tile at grid position ``key`` (reference
        tiling.py:271-302 — there a local torch slice assignment on the
        owning rank; here one functional ``.at[].set`` on the global
        array, which XLA keeps shard-local when the slice is)."""
        self.__arr.larray = self.__arr.larray.at[self.tile_slices(key)].set(value)

    @property
    def lshape_map(self) -> np.ndarray:
        """Shard-shape table of the tiled array (reference tiling.py:127)."""
        return self.__arr.lshape_map

    @property
    def tile_dimensions(self) -> List[np.ndarray]:
        """Width of every tile along every dimension
        (reference tiling.py:156-159)."""
        dims = []
        for ends in self.__tile_ends:
            starts = np.concatenate([[0], ends[:-1]])
            dims.append(ends - starts)
        return dims

    def get_tile_size(self, pos: Tuple[int, ...]) -> Tuple[int, ...]:
        """Shape of the tile at grid position ``pos``
        (reference tiling.py:264-270)."""
        return tuple(s.stop - s.start for s in self.tile_slices(pos))


class SquareDiagTiles:
    """Diagonal-aligned square tile grid (reference tiling.py:303-1258).

    Computes the reference's width-matched row/column tile decomposition
    where tiles along the global diagonal are square (``tiles_per_proc``
    knob, reference :344).  The QR driver that consumed the caching/
    match_tiles machinery is replaced by TSQR; the geometry remains for
    introspection and for algorithms that want diagonal-aligned blocking.
    """

    def __init__(self, arr, tiles_per_proc: int = 1):
        from .sanitation import sanitize_in

        sanitize_in(arr)  # reference tiling.py:349-352: TypeError contract
        if not isinstance(tiles_per_proc, (int, np.integer)) or isinstance(
            tiles_per_proc, bool
        ):
            raise TypeError(f"tiles_per_proc must be an int, got {type(tiles_per_proc)}")
        if arr.ndim != 2:
            raise ValueError("SquareDiagTiles requires a 2-D DNDarray")
        if tiles_per_proc < 1:
            raise ValueError("tiles_per_proc must be >= 1")
        self.__arr = arr
        comm = arr.comm
        size = comm.size
        m, n = arr.shape
        k = min(m, n)
        # divide the diagonal extent into size * tiles_per_proc near-equal tiles
        ntiles = max(size * tiles_per_proc, 1)
        base = k // ntiles
        rem = k % ntiles
        widths = [base + (1 if i < rem else 0) for i in range(ntiles)]
        widths = [w for w in widths if w > 0]
        row_ends = list(np.cumsum(widths))
        if row_ends and row_ends[-1] < m:
            row_ends[-1] = m  # last row tile absorbs the overhang
        col_ends = list(np.cumsum(widths))
        if col_ends and col_ends[-1] < n:
            col_ends[-1] = n
        self.__row_ends = row_ends
        self.__col_ends = col_ends
        self.__tiles_per_proc = tiles_per_proc

    @property
    def arr(self):
        return self.__arr

    @property
    def tiles_per_proc(self) -> int:
        return self.__tiles_per_proc

    @property
    def row_indices(self) -> List[int]:
        """Global start row of each tile row (reference :700-740)."""
        return [0] + self.__row_ends[:-1]

    @property
    def col_indices(self) -> List[int]:
        """Global start column of each tile column."""
        return [0] + self.__col_ends[:-1]

    def get_start_stop(self, key: Tuple[int, int]) -> Tuple[int, int, int, int]:
        """(row_start, row_stop, col_start, col_stop) of tile ``key``
        (reference tiling.py:810-930)."""
        r, c = key
        rs = 0 if r == 0 else self.__row_ends[r - 1]
        cs = 0 if c == 0 else self.__col_ends[c - 1]
        return int(rs), int(self.__row_ends[r]), int(cs), int(self.__col_ends[c])

    def __getitem__(self, key) -> "np.ndarray":
        """Tile data at (row, col) (reference local_get, tiling.py:933)."""
        rs, re, cs, ce = self.get_start_stop(key)
        return self.__arr.larray[rs:re, cs:ce]

    def __setitem__(self, key, value) -> None:
        """Overwrite tile ``(row, col)`` (reference tiling.py:1215-1258 —
        an owning-rank torch slice write; here one functional ``.at[].set``
        on the global array)."""
        rs, re, cs, ce = self.get_start_stop(key)
        self.__arr.larray = self.__arr.larray.at[rs:re, cs:ce].set(value)

    def local_get(self, key):
        """Alias of ``__getitem__`` (reference tiling.py:933-955; local and
        global coordinates coincide in the single-controller model)."""
        return self[key]

    def local_set(self, key, value) -> None:
        """Alias of ``__setitem__`` (reference tiling.py:957-1018)."""
        self[key] = value

    @property
    def lshape_map(self) -> np.ndarray:
        """Shard-shape table of the tiled array (reference tiling.py:701)."""
        return self.__arr.lshape_map

    @property
    def tile_rows(self) -> int:
        """Number of tile rows (reference tiling.py:791-799)."""
        return len(self.__row_ends)

    @property
    def tile_columns(self) -> int:
        """Number of tile columns (reference tiling.py:731-739)."""
        return len(self.__col_ends)

    def __per_position(self, ends: List[int], axis: int) -> List[int]:
        """Tiles along ``axis`` held by each mesh position: the full grid
        when ``axis`` is not the split axis (only the split axis is
        distributed), else the tiles overlapping the position's shard."""
        comm, shape, split = self.__arr.comm, self.__arr.shape, self.__arr.split
        if split is None or split != axis:
            return [len(ends)] * comm.size
        counts = []
        for r in range(comm.size):
            off, lshape, _ = comm.chunk(shape, axis, rank=r)
            lo, hi = off, off + lshape[axis]
            starts = [0] + list(ends[:-1])
            counts.append(
                sum(1 for s, e in zip(starts, ends) if s < hi and e > lo)
            )
        return counts

    @property
    def tile_rows_per_process(self) -> List[int]:
        """Tile rows overlapping each mesh position's shard
        (reference tiling.py:801-809: tile rows *on* each rank; with the
        canonical layout a tile may straddle two positions — it is then
        counted for both)."""
        return self.__per_position(self.__row_ends, 0)

    @property
    def tile_columns_per_process(self) -> List[int]:
        """Tile columns overlapping each mesh position's shard
        (reference tiling.py:741-749)."""
        return self.__per_position(self.__col_ends, 1)

    @property
    def last_diagonal_process(self) -> int:
        """Mesh position owning the end of the global diagonal
        (reference tiling.py:711-719)."""
        arr = self.__arr
        split = arr.split if arr.split is not None else 0
        k = min(arr.shape[0], arr.shape[1])
        _, lshape, _ = arr.comm.chunk(arr.shape, split, rank=0)
        width = max(lshape[split], 1)
        return min((k - 1) // width, arr.comm.size - 1) if k else 0

    @property
    def tile_map(self) -> np.ndarray:
        """(tile_rows, tile_columns, 3) table of [row_start, col_start,
        owner position] per tile (reference tiling.py:751-789; ownership
        follows the split axis of the canonical layout)."""
        arr = self.__arr
        rows, cols = self.row_indices, self.col_indices
        out = np.zeros((len(rows), len(cols), 3), dtype=np.int64)
        split = arr.split if arr.split is not None else 0
        _, lshape, _ = arr.comm.chunk(arr.shape, split, rank=0)
        width = max(lshape[split], 1)
        for i, rstart in enumerate(rows):
            for j, cstart in enumerate(cols):
                start = rstart if split == 0 else cstart
                owner = min(start // width, arr.comm.size - 1)
                out[i, j] = (rstart, cstart, owner)
        return out

    def __owned_tiles(self, rank: int, axis: int) -> List[int]:
        """Global tile indices along ``axis`` OWNED by ``rank`` (ownership
        = the position holding a tile's start row/column, exactly the rule
        ``tile_map`` uses — unlike the per-process overlap tables, it
        assigns each tile to one position, so prefix offsets stay exact
        even when a tile straddles shard boundaries)."""
        arr = self.__arr
        starts = self.row_indices if axis == 0 else self.col_indices
        split = arr.split if arr.split is not None else 0
        if split != axis:
            return list(range(len(starts)))
        _, lshape, _ = arr.comm.chunk(arr.shape, split, rank=0)
        width = max(lshape[split], 1)
        return [
            i for i, s in enumerate(starts)
            if min(s // width, arr.comm.size - 1) == rank
        ]

    def local_to_global(self, key: Tuple[int, int], rank: int) -> Tuple[int, int]:
        """Map a process-local tile key to the global tile grid
        (reference tiling.py:1020-1082): the local index counts the tiles
        ``rank`` owns (``tile_map`` ownership) along the split axis."""
        r, c = key
        arr = self.__arr
        if arr.split == 0 or arr.split is None:
            owned = self.__owned_tiles(rank, 0)
            if r >= len(owned):
                raise IndexError(f"rank {rank} owns {len(owned)} tile rows, got index {r}")
            return int(owned[r]), int(c)
        owned = self.__owned_tiles(rank, 1)
        if c >= len(owned):
            raise IndexError(f"rank {rank} owns {len(owned)} tile columns, got index {c}")
        return int(r), int(owned[c])

    def match_tiles(self, tiles_to_match: "SquareDiagTiles") -> None:
        """Align this grid's tile boundaries with another array's grid
        (reference tiling.py:1084-1213, used there to keep Q's tiles
        composable with R's during the tiled QR).  The boundary lists are
        adopted from ``tiles_to_match`` clipped to this array's shape,
        with the final tile absorbing any overhang — the reference's
        redistribution step is unnecessary here because the canonical
        GSPMD layout never moves."""
        if not isinstance(tiles_to_match, SquareDiagTiles):
            raise TypeError(
                f"tiles_to_match must be SquareDiagTiles, got {type(tiles_to_match)}"
            )
        m, n = self.__arr.shape

        def adopt(ends: List[int], limit: int) -> List[int]:
            clipped = [int(e) for e in ends if e < limit]
            return clipped + [limit]

        self.__row_ends = adopt(tiles_to_match._SquareDiagTiles__row_ends, m)
        self.__col_ends = adopt(tiles_to_match._SquareDiagTiles__col_ends, n)
