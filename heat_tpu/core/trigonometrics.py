"""Trigonometric and hyperbolic elementwise maps.

Reference: heat/core/trigonometrics.py:30-421 — all ``__local_op`` maps.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations

__all__ = [
    "arccos",
    "acos",
    "arcsin",
    "asin",
    "arctan",
    "atan",
    "arctan2",
    "atan2",
    "cos",
    "cosh",
    "deg2rad",
    "degrees",
    "rad2deg",
    "radians",
    "sin",
    "sinh",
    "tan",
    "tanh",
]


def arccos(x, out=None):
    """Inverse cosine (reference trigonometrics.py:30-62)."""
    return _operations.__local_op(jnp.arccos, x, out)


acos = arccos


def arcsin(x, out=None):
    """Inverse sine (reference trigonometrics.py:63-95)."""
    return _operations.__local_op(jnp.arcsin, x, out)


asin = arcsin


def arctan(x, out=None):
    """Inverse tangent (reference trigonometrics.py:96-128)."""
    return _operations.__local_op(jnp.arctan, x, out)


atan = arctan


def arctan2(x1, x2):
    """Quadrant-aware inverse tangent of x1/x2
    (reference trigonometrics.py:129-171)."""
    from . import _operations as ops

    def _atan2(a, b):
        a = a.astype(jnp.float32) if jnp.issubdtype(a.dtype, jnp.integer) else a
        b = b.astype(jnp.float32) if jnp.issubdtype(b.dtype, jnp.integer) else b
        return jnp.arctan2(a, b)

    return ops.__binary_op(_atan2, x1, x2)


atan2 = arctan2


def cos(x, out=None):
    """Cosine (reference trigonometrics.py:172-204)."""
    return _operations.__local_op(jnp.cos, x, out)


def cosh(x, out=None):
    """Hyperbolic cosine (reference trigonometrics.py:205-237)."""
    return _operations.__local_op(jnp.cosh, x, out)


def deg2rad(x, out=None):
    """Degrees → radians (reference trigonometrics.py:238-262)."""
    return _operations.__local_op(jnp.deg2rad, x, out)


radians = deg2rad


def rad2deg(x, out=None):
    """Radians → degrees (reference trigonometrics.py:263-287)."""
    return _operations.__local_op(jnp.rad2deg, x, out)


degrees = rad2deg


def sin(x, out=None):
    """Sine (reference trigonometrics.py:288-320)."""
    return _operations.__local_op(jnp.sin, x, out)


def sinh(x, out=None):
    """Hyperbolic sine (reference trigonometrics.py:321-353)."""
    return _operations.__local_op(jnp.sinh, x, out)


def tan(x, out=None):
    """Tangent (reference trigonometrics.py:354-387)."""
    return _operations.__local_op(jnp.tan, x, out)


def tanh(x, out=None):
    """Hyperbolic tangent (reference trigonometrics.py:388-421)."""
    return _operations.__local_op(jnp.tanh, x, out)


# split semantics for heat_tpu.analysis.splitflow (see core/_split_semantics.py)
from ._split_semantics import declare_split_semantics_table  # noqa: E402

declare_split_semantics_table(
    __name__,
    {
        "elementwise": (
            "arccos", "arcsin", "arctan", "cos", "cosh", "deg2rad",
            "rad2deg", "sin", "sinh", "tan", "tanh",
        ),
        "binary": ("arctan2",),
    },
)
