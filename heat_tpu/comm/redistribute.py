"""Planned redistribution: ``resplit``/``alltoall`` as compiled schedules.

``resplit`` is the framework's most expensive layout primitive.  The
reference implements it as one monolithic ``Alltoallv``
(reference communication.py:764-881) that materialises worst-case
receive buffers; our port's monolithic path hands the whole src→dst
change to a single GSPMD reshard (:meth:`XlaCommunication.apply_sharding`)
— fast when XLA pattern-matches an all-to-all, but opaque, and in the
general case lowered as **all-gather + slice**: every device briefly
holds the full array.

This module is the alternative: a redistribution **planner** in the
style of *Memory-efficient array redistribution through portable
collective communication* (arXiv 2112.01075).  :func:`plan` decomposes
any (src split → dst split) change over the 1-D mesh into a short
schedule of primitive steps —

``("pad", axis, n)``
    local zero-pad of a ragged target axis to the canonical padded
    length (``size * shard_width(n)``),
``("slice", axis)``
    dynamic-slice discard: each device keeps its own slab along
    ``axis`` (replicated → split; zero wire bytes),
``("allgather", axis)``
    all-gather fraction: the split axis is gathered back to full length
    (split → replicated; ``(p-1)/p`` of the array per device),
``("view", axis)`` / ``("assemble", axis)``
    local reshape bookkeeping around the rotation stage, and
``("rotate", k)``
    one :func:`jax.lax.ppermute` hop with shift ``k``: every device
    ships exactly the ``1/p²``-sized piece of the global array that
    position ``(i+k) mod p`` needs — the split→split schedule is
    ``p-1`` such rotations, moving ``(p-1)/p²`` of the array per device
    (a factor ``p`` fewer wire bytes than gather-and-slice) while never
    holding more than input shard + output shard + one piece.

The cost model (:meth:`Plan.wire_bytes` / :meth:`Plan.peak_live_bytes`,
:func:`monolithic_model` for the one-shot reshard's envelope) follows
:func:`heat_tpu.comm.compressed.wire_model`'s conventions — per-device
bytes, block-padded compressed payloads — and is the same arithmetic the
telemetry ledger is credited with, so benched ratios and accounted bytes
cannot drift apart.  ``plan(..., max_live_bytes=)`` turns the model into
a hard bound: a schedule whose modeled peak exceeds it raises instead of
silently over-allocating.

Plans execute as **ONE compiled program** (a ``jitted`` ``shard_map``
whose cache key includes the plan signature and, via
:func:`heat_tpu.core._compile.register_key_context`, the redistribution
*and* collective-precision policies).  Exact transmission is the
default and is bitwise-identical to the monolithic reshard; under
``set_collective_precision("bf16"|"int8_block"|"auto")`` the wire-moving
steps (rotations and gather fractions) ride the block-scaled quantized
encoding of :mod:`heat_tpu.comm.compressed`.

Policy
    ``ht.comm.set_redistribution("planned" | "monolithic" | "auto")``.
    ``"monolithic"`` keeps the seed's single GSPMD reshard;
    ``"planned"`` routes every eligible eager ``resplit`` /
    ``alltoall`` / ``commit_split`` through the planner; ``"auto"``
    (the default) applies the planner only where it beats the
    monolithic envelope — split→split changes of at least
    :func:`get_redistribution_threshold` bytes — and leaves everything
    else on the proven monolithic path.  Tracers (``ht.fuse`` / user
    jit), single-device meshes, multi-process meshes, and
    non-canonically-committed inputs always fall back.  The policy is
    part of every program cache key, so flipping it retraces instead of
    replaying a stale program.

Telemetry: each executed plan opens a ``comm:resplit`` span and credits
its modeled bytes to the wire ledger under op ``"resplit"``
(``comm.collectives.resplit`` counter, ``comm.wire_ratio`` gauges),
plus a ``comm.resplit.planned`` counter — docs/design.md §14.
"""

from __future__ import annotations

import contextlib
import math
from dataclasses import dataclass
from typing import Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..core._compile import context_token, jitted, register_key_context
from ..core._jax_compat import shard_map
from ..telemetry import _core as _tel
from . import _costs
from . import compressed as _cq
from .compressed import BLOCK
from .overlap import overlap_enabled, timed_dispatch

__all__ = [
    "Plan",
    "get_redistribution",
    "get_redistribution_threshold",
    "grid_redistribute_or_none",
    "monolithic_model",
    "plan",
    "plan_cache_size",
    "clear_plan_cache",
    "redistribute",
    "redistribution",
    "set_redistribution",
    "set_redistribution_threshold",
]

_POLICIES = ("planned", "monolithic", "auto")
_POLICY = "auto"
#: "auto" plans only split→split changes of at least this many bytes —
#: below it the p-1 rotation hops cost more dispatch latency than the
#: monolithic reshard's single collective saves in wire time.
_AUTO_THRESHOLD = 1 << 16


# --------------------------------------------------------------------- #
# policy (mirrors compressed.set_collective_precision)                   #
# --------------------------------------------------------------------- #
def set_redistribution(policy: str) -> None:
    """Set the process-wide redistribution policy.

    ``"monolithic"``
        Every layout change is one GSPMD reshard (the seed behavior).
    ``"planned"``
        Every eligible eager layout change runs the planner's compiled
        schedule (bitwise-identical values; bounded peak memory).
    ``"auto"``
        The default: planner for split→split changes of at least
        :func:`get_redistribution_threshold` bytes, monolithic
        otherwise.
    """
    global _POLICY
    if policy not in _POLICIES:
        raise ValueError(
            f"unknown redistribution policy {policy!r}: expected one of {_POLICIES}"
        )
    _POLICY = policy


def get_redistribution() -> str:
    """The current process-wide redistribution policy."""
    return _POLICY


@contextlib.contextmanager
def redistribution(policy: str):
    """Context-manager form of :func:`set_redistribution`."""
    prev = _POLICY
    set_redistribution(policy)
    try:
        yield
    finally:
        set_redistribution(prev)


def set_redistribution_threshold(nbytes: int) -> None:
    """Minimum array size (bytes) that ``"auto"`` policy plans."""
    global _AUTO_THRESHOLD
    nbytes = int(nbytes)
    if nbytes < 0:
        raise ValueError("threshold must be non-negative")
    _AUTO_THRESHOLD = nbytes


def get_redistribution_threshold() -> int:
    """Current ``"auto"``-policy array-size threshold in bytes."""
    return _AUTO_THRESHOLD


@register_key_context
def _redist_token() -> Tuple:
    """The redistribution policy's contribution to every compiled-program
    cache key (``jitted`` and the ``ht.fuse`` cache): flipping the policy
    keys fresh entries instead of replaying programs whose layout
    behavior was decided under the other policy."""
    return ("redist", _POLICY, _AUTO_THRESHOLD)


# --------------------------------------------------------------------- #
# the plan                                                               #
# --------------------------------------------------------------------- #
@dataclass(frozen=True)
class Plan:
    """One redistribution schedule plus its cost model.

    Immutable and hashable — :attr:`key` is the program-cache signature
    (the executing ``jitted`` entry is keyed on it, so equal plans share
    one compiled program).
    """

    global_shape: Tuple[int, ...]  # TRUE (unpadded) global shape
    dtype: str                     # jnp dtype name
    #: 1-D plans carry split ints; N-D (grid) plans carry splits tuples
    #: (``splits[d]`` = mesh axis sharding array dim ``d``)
    src: Union[int, Tuple[Optional[int], ...], None]
    dst: Union[int, Tuple[Optional[int], ...], None]
    size: int
    mode: Optional[str]            # wire mode of compressible steps
    steps: Tuple[Tuple, ...]
    #: modeled bytes each device puts on the wire (mode-dependent)
    wire_bytes: int
    #: same traffic shipped as the exact dtype (the bench denominator)
    exact_wire_bytes: int
    #: modeled peak live bytes per device while the program runs
    peak_live_bytes: int
    max_live_bytes: Optional[int] = None
    #: set on grid plans: the mesh the splits tuples index into.  The
    #: schedule is the per-mesh-axis 1-D factoring of
    #: :func:`heat_tpu.comm._costs.grid_plan_cost` — wire bytes sum over
    #: stages, the peak is the max stage peak, still ONE dispatch.
    mesh_shape: Optional[Tuple[int, ...]] = None

    @property
    def key(self) -> Tuple:
        return (
            self.global_shape, self.dtype, self.src, self.dst,
            self.size, self.mode, self.steps, self.mesh_shape,
        )

    @property
    def out_shape(self) -> Tuple[int, ...]:
        """Global shape of the result: the true shape with ragged
        destination axes padded to their canonical lengths."""
        shape = list(self.global_shape)
        if self.mesh_shape is not None:
            for d, g in enumerate(self.dst):
                if g is not None:
                    p = self.mesh_shape[g]
                    shape[d] = p * (-(-shape[d] // p))
            return tuple(shape)
        if self.dst is not None:
            w = -(-shape[self.dst] // self.size)
            shape[self.dst] = self.size * w
        return tuple(shape)

    def wire_model(self, compute_ms_per_step: float = 0.0) -> dict:
        """Cost-model dict in the :func:`compressed.wire_model` shape —
        the single source for bench headlines and telemetry accounting.

        ``critical_path_ms`` prices the schedule's wire time under both
        ring schedules (:func:`heat_tpu.comm._costs.critical_path_ms`):
        ``"serial"`` sums wire + compute per hop, ``"overlap"`` is the
        pipelined ``max(wire, compute)`` roofline the overlap policy
        targets.  ``compute_ms_per_step`` defaults to 0 (pure wire
        bound); bench passes its measured per-step compute probe."""
        exact = self.exact_wire_bytes
        hops = sum(1 for s in self.steps if s[0] == "rotate")
        return {
            "steps": len(self.steps),
            "rotate_hops_per_device": hops,
            "exact_wire_bytes": exact,
            "wire_bytes": self.wire_bytes,
            "peak_live_bytes": self.peak_live_bytes,
            "bytes_ratio": round(self.wire_bytes / exact, 4) if exact else None,
            "critical_path_ms": {
                "serial": _costs.critical_path_ms(
                    self.wire_bytes, hops, compute_ms_per_step, overlap=False
                ),
                "overlap": _costs.critical_path_ms(
                    self.wire_bytes, hops, compute_ms_per_step, overlap=True
                ),
            },
        }

    def explain(self) -> str:
        """Human-readable schedule (one line per step)."""
        head = (
            f"redistribute {self.global_shape} {self.dtype} "
            f"split {self.src} -> {self.dst} over {self.size} devices "
            f"[wire {self.wire_bytes} B/dev, peak {self.peak_live_bytes} B/dev"
            + (f", mode {self.mode}" if self.mode else "")
            + "]"
        )
        lines = [head]
        for s in self.steps:
            lines.append(f"  {s[0]}" + (f" {s[1:]}" if len(s) > 1 else ""))
        if not self.steps:
            lines.append("  (no-op)")
        return "\n".join(lines)


def _itemsize(dtype) -> int:
    return jnp.dtype(dtype).itemsize


def _encoded_bytes(n_elems: int, mode: Optional[str], itemsize: int) -> int:
    """Bytes one payload of ``n_elems`` occupies on the wire under
    ``mode`` — delegates to the shared jax-free model in
    :mod:`heat_tpu.comm._costs` (block-padded; one f32 scale per BLOCK
    for int8), which the static analyzer loads by file path."""
    return _costs.encoded_bytes(n_elems, mode, itemsize)


def monolithic_model(global_shape, dtype, src, dst, size: int) -> dict:
    """Per-device cost envelope of the one-shot GSPMD reshard.

    split→None is an all-gather (``(p-1)/p`` of the array per device;
    the full array live).  None→split is a local slice (zero wire).
    split→split is modeled as the reference ``Alltoallv``'s envelope —
    the general GSPMD lowering gathers then slices, so the wire bytes
    are the all-gather's and the peak briefly holds the full array plus
    the input shard.  (When XLA does pattern-match a true all-to-all the
    monolithic wire cost drops to the planner's; this model is the
    *envelope* the planner must beat, mirroring the worst-case receive
    buffers of reference communication.py:764-881.)
    """
    shape = tuple(int(s) for s in global_shape)
    return _costs.monolithic_cost(shape, _itemsize(dtype), src, dst, size)


#: plan cache — keyed like the compile cache (request signature + the
#: registered key-context tokens, so policy flips re-plan)
_PLANS: dict = {}


def plan_cache_size() -> int:
    return len(_PLANS)


def clear_plan_cache() -> None:
    _PLANS.clear()


def _as_splits(spelling, ndim: int, mesh_ndim: int) -> Tuple[Optional[int], ...]:
    """Normalize a split spelling (None / int / tuple) to the splits
    tuple over an ``mesh_ndim``-axis mesh — the 1-D int form promotes to
    its one-hot tuple on mesh axis 0 (the exact ``split`` compat view)."""
    if spelling is None:
        return (None,) * ndim
    if isinstance(spelling, (tuple, list)):
        return tuple(None if g is None else int(g) for g in spelling)
    entries = [None] * ndim
    entries[int(spelling) % ndim] = 0
    return tuple(entries)


def plan(
    global_shape,
    dtype,
    src,
    dst,
    size: int,
    *,
    mesh_shape: Optional[Tuple[int, ...]] = None,
    max_live_bytes: Optional[int] = None,
) -> Plan:
    """Plan the redistribution of a ``global_shape`` array committed at
    split ``src`` to split ``dst`` over a ``size``-device mesh.

    ``global_shape`` is the TRUE shape; a ragged destination axis is
    padded by the schedule itself (matching
    :meth:`XlaCommunication.commit_split`), while a ragged *source* axis
    is rejected — canonically committed inputs are divisible by
    construction, anything else reaches the planner as replicated.

    On an N-D mesh (``mesh_shape`` with more than one axis), ``src`` and
    ``dst`` are splits TUPLES (``splits[d]`` = mesh axis sharding array
    dim ``d``; int/None spellings promote via the compat view) and the
    schedule is the per-mesh-axis 1-D factoring of
    :func:`heat_tpu.comm._costs.grid_plan_cost` — each stage reuses the
    rotate/allgather/slice step algebra along one named mesh axis, and
    the whole chain still executes as ONE compiled dispatch.

    ``max_live_bytes`` bounds the modeled per-device peak: a schedule
    that cannot fit raises ``ValueError`` (the split→split rotation
    schedule is already both minimal-traffic and minimal-memory, so the
    bound is a guarantee check, not a search knob — see design.md §14).
    For grid plans the bound applies to the max over stages.
    """
    shape = tuple(int(s) for s in global_shape)
    ndim = len(shape)
    p = int(size)
    if p < 1:
        raise ValueError(f"mesh size must be >= 1, got {p}")
    grid = mesh_shape is not None and len(tuple(mesh_shape)) > 1
    if not grid and (isinstance(src, (tuple, list)) or isinstance(dst, (tuple, list))):
        # tuple spellings over a 1-D mesh are exactly their compat ints
        if isinstance(src, (tuple, list)):
            src = next((d for d, g in enumerate(src) if g == 0), None)
        if isinstance(dst, (tuple, list)):
            dst = next((d for d, g in enumerate(dst) if g == 0), None)
    if grid:
        mesh_shape = tuple(int(s) for s in mesh_shape)
        if math.prod(mesh_shape) != p:
            raise ValueError(
                f"mesh_shape {mesh_shape} does not tile {p} device(s)"
            )
        src = _as_splits(src, ndim, len(mesh_shape))
        dst = _as_splits(dst, ndim, len(mesh_shape))
        ckey = (shape, jnp.dtype(dtype).name, src, dst, p, mesh_shape,
                max_live_bytes) + context_token()
        cached = _PLANS.get(ckey)
        if cached is not None:
            return cached
        p_obj = _build_grid_plan(shape, dtype, src, dst, mesh_shape, max_live_bytes)
        _PLANS[ckey] = p_obj
        return p_obj
    if src is not None:
        src = int(src) % ndim
    if dst is not None:
        dst = int(dst) % ndim
    if src is not None and shape[src] % p:
        raise ValueError(
            f"ragged source axis: shape {shape} axis {src} does not divide "
            f"over {p} devices (a canonically committed input is divisible; "
            "ragged arrays live replicated and plan as src=None)"
        )
    ckey = (shape, jnp.dtype(dtype).name, src, dst, p, max_live_bytes) + context_token()
    cached = _PLANS.get(ckey)
    if cached is not None:
        return cached
    p_obj = _build_plan(shape, dtype, src, dst, p, max_live_bytes)
    _PLANS[ckey] = p_obj
    return p_obj


def _build_plan(shape, dtype, src, dst, p, max_live_bytes) -> Plan:
    # the arithmetic lives in the shared jax-free model (comm/_costs.py),
    # which the static analyzer loads by file path — delegation, not
    # duplication, is what keeps lint's cost report and the runtime
    # ledger byte-identical
    dt = jnp.dtype(dtype).name
    cost = _costs.plan_cost(
        shape, dt, src, dst, p,
        mode_for=lambda nbytes: _cq.reduce_mode(dtype, nbytes),
    )
    if max_live_bytes is not None and cost["peak_live_bytes"] > max_live_bytes:
        raise ValueError(
            f"no schedule for {shape} {dt} split {src}->{dst} over {p} "
            f"devices fits max_live_bytes={max_live_bytes}: the minimal "
            f"schedule needs {cost['peak_live_bytes']} live bytes per device"
        )
    return Plan(
        global_shape=tuple(shape), dtype=dt, src=src, dst=dst, size=p,
        mode=cost["mode"], steps=cost["steps"],
        wire_bytes=int(cost["wire_bytes"]),
        exact_wire_bytes=int(cost["exact_wire_bytes"]),
        peak_live_bytes=int(cost["peak_live_bytes"]),
        max_live_bytes=max_live_bytes,
    )


def _build_grid_plan(shape, dtype, src, dst, mesh_shape, max_live_bytes) -> Plan:
    # same delegation as _build_plan: the stage factoring AND its byte
    # arithmetic live in the shared jax-free model
    dt = jnp.dtype(dtype).name
    cost = _costs.grid_plan_cost(
        shape, dt, src, dst, mesh_shape,
        mode_for=lambda nbytes: _cq.reduce_mode(dtype, nbytes),
    )
    if max_live_bytes is not None and cost["peak_live_bytes"] > max_live_bytes:
        raise ValueError(
            f"no schedule for {tuple(shape)} {dt} splits {src}->{dst} over "
            f"mesh {tuple(mesh_shape)} fits max_live_bytes={max_live_bytes}: "
            f"the minimal factored schedule needs {cost['peak_live_bytes']} "
            "live bytes per device"
        )
    return Plan(
        global_shape=tuple(shape), dtype=dt, src=src, dst=dst,
        size=int(math.prod(mesh_shape)),
        mode=cost["mode"], steps=cost["steps"],
        wire_bytes=int(cost["wire_bytes"]),
        exact_wire_bytes=int(cost["exact_wire_bytes"]),
        peak_live_bytes=int(cost["peak_live_bytes"]),
        max_live_bytes=max_live_bytes,
        mesh_shape=tuple(mesh_shape),
    )


# --------------------------------------------------------------------- #
# execution: one compiled shard_map program per plan                     #
# --------------------------------------------------------------------- #
def _ship_start(piece, mode: Optional[str]):
    """Phase 1 of one rotation ship: encode the piece into its wire
    leaves (the piece itself when transmission is exact)."""
    if mode is None:
        return (piece,)
    n = int(math.prod(piece.shape)) if piece.shape else 1
    flat = piece.reshape(-1).astype(jnp.float32)
    padded = max(BLOCK, -(-n // BLOCK) * BLOCK)
    flat = jnp.pad(flat, (0, padded - n))
    return _cq._encode(flat, mode, BLOCK)


def _ship_send(leaves, axis_name, perm):
    """Phase 2: put the wire leaves on the ring."""
    return tuple(jax.lax.ppermute(leaf, axis_name, perm) for leaf in leaves)


def _ship_finish(leaves, mode: Optional[str], shape, dtype):
    """Phase 3: decode the received leaves back into a piece."""
    if mode is None:
        return leaves[0]
    n = int(math.prod(shape)) if shape else 1
    return _cq._decode(leaves, mode)[:n].reshape(shape).astype(dtype)


def _ship(piece, axis_name, perm, mode: Optional[str]):
    """Move one rotation piece to its destination: a raw ppermute when
    transmission is exact, else encode → ppermute the wire leaves →
    decode (the quantize-once-forward-bytes discipline of the rings).
    The three phases are split out so the overlapped schedule can issue
    rotation ``k+1``'s send before finishing rotation ``k``."""
    leaves = _ship_send(_ship_start(piece, mode), axis_name, perm)
    return _ship_finish(leaves, mode, piece.shape, piece.dtype)


def _pad_axis(x, axis: int, pad: int):
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _axis_kernel(name: str, p: int, src, dst, src_len: int, dst_len: int,
                 mode: Optional[str], overlapped: bool):
    """The local body of ONE 1-D redistribution stage along the named
    mesh axis ``name`` (ring size ``p``) — the rotate/allgather/slice
    step algebra, parameterized so the 1-D program uses it directly and
    the grid program chains one stage per mesh axis.  ``src_len`` /
    ``dst_len`` are the stage-global extents of the moving dims (the
    whole-array extents for a 1-D plan; the current padded extents of a
    grid stage, whose other sharded dims are already local inside the
    grid ``shard_map``)."""
    if dst is not None:
        w_d = -(-dst_len // p)
        pad_d = p * w_d - dst_len

    if src is None:
        # replicated -> split: pad (maybe) + dynamic-slice discard
        def kernel(x):
            if pad_d:
                x = _pad_axis(x, dst, pad_d)
            i = jax.lax.axis_index(name)
            return jax.lax.dynamic_slice_in_dim(x, i * w_d, w_d, axis=dst)

    elif dst is None:
        # split -> replicated: all-gather fraction (compressed ring when
        # the precision policy says so — quantize once, forward bytes)
        def kernel(x):
            if mode is None:
                return jax.lax.all_gather(x, name, axis=src, tiled=True)
            moved = jnp.moveaxis(x, src, 0)
            stacked = _cq.ring_allgather_q(moved, name, size=p, mode=mode, block=BLOCK)
            full = stacked.reshape((p * moved.shape[0],) + moved.shape[1:])
            return jnp.moveaxis(full, 0, src)

    else:
        # split -> split: view the local slab as p destination pieces,
        # keep our own, rotate the other p-1 to their owners
        w_s = src_len // p

        def kernel(x):
            if pad_d:
                x = _pad_axis(x, dst, pad_d)
            i = jax.lax.axis_index(name)
            out_shape = list(x.shape)
            out_shape[src] = p * w_s
            out_shape[dst] = w_d
            out = jnp.zeros(tuple(out_shape), x.dtype)

            def piece_at(j):
                return jax.lax.dynamic_slice_in_dim(x, j * w_d, w_d, axis=dst)

            out = jax.lax.dynamic_update_slice_in_dim(
                out, piece_at(i), i * w_s, axis=src
            )
            pshape = tuple(
                w_d if a == dst else d for a, d in enumerate(x.shape)
            )

            def send(k):
                perm = [(t, (t + k) % p) for t in range(p)]
                return _ship_send(
                    _ship_start(piece_at((i + k) % p), mode), name, perm
                )

            if overlapped:
                # pipelined rotations: the p-1 ships are data-independent,
                # so rotation k+1's encode + ppermute is issued before
                # rotation k's decode + update — at most two pieces in
                # flight, and each hop's wire hides behind the previous
                # hop's decode math.  Same encode/decode per piece, updates
                # at distinct offsets: bitwise-equal to the serial arm.
                inflight = send(1)
                for k in range(1, p):
                    nxt = send(k + 1) if k + 1 < p else None
                    pc = _ship_finish(inflight, mode, pshape, x.dtype)
                    out = jax.lax.dynamic_update_slice_in_dim(
                        out, pc, ((i - k) % p) * w_s, axis=src
                    )
                    inflight = nxt
            else:
                for k in range(1, p):
                    pc = _ship_finish(send(k), mode, pshape, x.dtype)
                    out = jax.lax.dynamic_update_slice_in_dim(
                        out, pc, ((i - k) % p) * w_s, axis=src
                    )
            return out

    return kernel


def _make_program(p_obj: Plan, comm):
    """Build the one compiled program executing ``p_obj`` — a single
    ``shard_map`` whose body runs every step of the schedule (a chain of
    per-mesh-axis ``shard_map`` stages inside the one program for grid
    plans)."""
    if not p_obj.steps:  # identity: let apply_sharding's no-op path handle it
        return None
    if p_obj.mesh_shape is not None:
        return _make_grid_program(p_obj, comm)
    mesh, name = comm._mesh, comm.axis_name
    p = p_obj.size
    src, dst, mode = p_obj.src, p_obj.dst, p_obj.mode
    shape = p_obj.global_shape
    ndim = len(shape)
    # pipelined rotation schedule under the overlap policy (in every
    # compiled-program cache key via the registered token)
    overlapped = overlap_enabled(p)

    kernel = _axis_kernel(
        name, p, src, dst,
        shape[src] if src is not None else 0,
        shape[dst] if dst is not None else 0,
        mode, overlapped,
    )
    in_spec = PartitionSpec() if src is None else comm.spec(ndim, src)
    out_spec = PartitionSpec() if dst is None else comm.spec(ndim, dst)

    def _f(x):
        return shard_map(
            kernel, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
            check_vma=False,
        )(x)

    return _f


def _make_grid_program(p_obj: Plan, comm):
    """The one compiled program of a grid plan: a chain of per-mesh-axis
    1-D stages (each a ``shard_map`` over the full grid mesh whose body
    moves data along ONE named axis via :func:`_axis_kernel`), executed
    inside a single ``jitted`` program — one dispatch for the whole
    factored schedule.  Stage order, extents, and wire modes are replayed
    from :func:`heat_tpu.comm._costs.grid_plan_cost`, the same arithmetic
    the plan's byte figures came from."""
    mesh = comm._mesh
    names = comm.axis_names
    mesh_shape = p_obj.mesh_shape
    shape = p_obj.global_shape
    ndim = len(shape)
    cost = _costs.grid_plan_cost(
        shape, p_obj.dtype, p_obj.src, p_obj.dst, mesh_shape,
        mode_for=lambda nbytes: _cq.reduce_mode(p_obj.dtype, nbytes),
    )
    state = list(p_obj.src)
    ext = list(shape)
    stage_fns = []
    for (g, sd, td), mode in zip(cost["stages"], cost["stage_modes"]):
        p = mesh_shape[g]
        kernel = _axis_kernel(
            names[g], p, sd, td,
            ext[sd] if sd is not None else 0,
            ext[td] if td is not None else 0,
            mode, overlap_enabled(p),
        )
        in_spec = comm.spec(ndim, tuple(state))
        if sd is not None:
            state[sd] = None
        if td is not None:
            state[td] = g
            ext[td] = p * (-(-ext[td] // p))
        out_spec = comm.spec(ndim, tuple(state))
        stage_fns.append((kernel, in_spec, out_spec))

    def _f(x):
        for kernel, in_spec, out_spec in stage_fns:
            x = shard_map(
                kernel, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                check_vma=False,
            )(x)
        return x

    return _f


def redistribute(
    array,
    split: Optional[int],
    comm=None,
    *,
    src: Optional[int] = None,
    max_live_bytes: Optional[int] = None,
):
    """Redistribute a global array to ``split`` via the planned schedule.

    The explicit entry point under the policy seam: plans (cached), then
    executes the schedule as ONE compiled dispatch, crediting the
    telemetry ledger.  ``src`` defaults to the array's committed split
    axis.  Values are bitwise-identical to the monolithic reshard; a
    ragged destination axis comes back padded to its canonical length
    (the :meth:`~heat_tpu.core.communication.XlaCommunication.commit_split`
    contract).
    """
    from ..core.communication import sanitize_comm

    comm = sanitize_comm(comm)
    if comm.mesh_ndim > 1:
        if src is None:
            src = comm._splits_of(array)
        p_obj = plan(
            tuple(int(s) for s in array.shape), array.dtype, src, split,
            comm.size, mesh_shape=comm.mesh_shape,
            max_live_bytes=max_live_bytes,
        )
        return execute(array, p_obj, comm)
    if src is None:
        src = comm._split_axis_of(array)
    p_obj = plan(
        tuple(int(s) for s in array.shape), array.dtype, src, split, comm.size,
        max_live_bytes=max_live_bytes,
    )
    return execute(array, p_obj, comm)


def grid_redistribute_or_none(array, dst_splits, comm, allow_pad: bool):
    """The N-D-mesh redistribution-policy seam behind
    :meth:`XlaCommunication.resplit` / ``commit_split``: the planned grid
    result, or None when the change stays on the monolithic path.

    Fallback mirrors the 1-D ``_planned_resplit`` contract: policy
    "monolithic"; tracers and fuse traces; host values and empty arrays;
    multi-process meshes; sources committed on a foreign mesh, ragged, or
    non-canonical; ragged destinations when the caller's contract forbids
    padding.  Policy "auto" additionally demands a sharded→sharded change
    of at least :func:`get_redistribution_threshold` bytes.
    """
    from ..core._tracing import in_trace

    policy = get_redistribution()
    if policy == "monolithic" or comm.size == 1:
        return None
    if isinstance(array, jax.core.Tracer) or in_trace():
        return None
    if not isinstance(array, jax.Array) or not getattr(array, "ndim", 0):
        return None
    if any(int(s) == 0 for s in array.shape) or jax.process_count() > 1:
        return None
    mesh_shape = comm.mesh_shape
    dst = tuple(dst_splits)
    src = comm._splits_of(array)
    if any(g is not None for g in src):
        if getattr(array.sharding, "mesh", None) != comm._mesh:
            return None
        if any(
            g is not None and int(array.shape[d]) % mesh_shape[g]
            for d, g in enumerate(src)
        ):
            return None  # ragged source: monolithic handles it replicated
    if src == dst:
        return None  # no-op: apply_sharding's early-outs are cheaper
    if not allow_pad and any(
        g is not None and int(array.shape[d]) % mesh_shape[g]
        for d, g in enumerate(dst)
    ):
        return None
    if policy == "auto" and (
        all(g is None for g in src)
        or all(g is None for g in dst)
        or _nelems(array.shape) * jnp.dtype(array.dtype).itemsize
        < get_redistribution_threshold()
    ):
        return None
    p_obj = plan(
        tuple(int(s) for s in array.shape), array.dtype, src, dst, comm.size,
        mesh_shape=mesh_shape,
    )
    return execute(array, p_obj, comm)


def _nelems(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def execute(array, p_obj: Plan, comm):
    """Run a :class:`Plan` on ``array`` as one compiled dispatch."""
    if tuple(int(s) for s in array.shape) != p_obj.global_shape:
        raise ValueError(
            f"plan was built for shape {p_obj.global_shape}, got {tuple(array.shape)}"
        )
    fn_make = _make_program(p_obj, comm)
    if fn_make is None:  # no-op plan: just certify the layout
        return comm.apply_sharding(array, p_obj.dst)
    # out_shardings pins the exact committed spec form: shard_map's
    # out_specs normalize trailing Nones away, and the result must
    # compare EQUAL to the monolithic reshard's sharding (callers use
    # sharding equality for their no-op early-outs)
    out_sh = comm.sharding(len(p_obj.global_shape), p_obj.dst)
    plan_sig = p_obj.key  # plain data: (shape, dtype, src, dst, size, mode, steps)
    fn = jitted(
        ("comm.resplit", comm, plan_sig), lambda: fn_make,
        jit_kwargs={"out_shardings": out_sh},
    )
    eager = not isinstance(array, jax.core.Tracer)
    if _tel.enabled and eager:
        _tel.account_bytes(
            "resplit", p_obj.mode or "f32", p_obj.exact_wire_bytes, p_obj.wire_bytes
        )
        _tel.inc("comm.resplit.planned")
        ring_ov = overlap_enabled(p_obj.size) and any(
            s[0] == "rotate" for s in p_obj.steps
        )
        with _tel.span(
            "comm:resplit",
            src=p_obj.src, dst=p_obj.dst, mesh=p_obj.size,
            steps=len(p_obj.steps), mode=p_obj.mode or "f32",
        ):
            return timed_dispatch("resplit", ring_ov, lambda: fn(array))
    return fn(array)
