"""Latency-hiding policy for the ring collectives.

Every hot ring in the tree — zig-zag causal ring attention, the
compressed allreduce/allgather rings, planned-redistribution rotations,
and the generic ``ring_map`` primitive — alternates "ship a slab" and
"do math on the slab".  Run strictly step-by-step, each round pays
``compute + wire``; TPU hardware runs the ICI DMA and the MXU
concurrently, so the roofline is ``max(compute, wire)``.  This module is
the ONE policy seam that flips the ring bodies between the two
schedules:

``ht.comm.set_overlap("on")``
    Every converted ring runs its double-buffered body: round ``k``
    issues the ``ppermute`` for the round-``k+1`` operand while the
    round-``k`` operand is consumed (two slabs — ``cur``, ``inflight``
    — carried through the ``fori_loop``), or, for rings whose hops are
    data-dependent (the compressed reduce-scatter), splits each payload
    into two independent streams whose wire and math interleave.  The
    fold schedule is bitwise-pinned: the overlapped body performs the
    same adds on the same operands in the same order as the serial one.
``ht.comm.set_overlap("off")``
    The serial step-by-step bodies — the exact twin every overlapped
    ring is validated against in the same run.
``ht.comm.set_overlap("auto")``
    The default: overlap on TPU backends (where the DMA actually runs
    concurrently with compute), serial elsewhere — CPU test runs keep
    the seed's dispatch shape unless a test opts in.

Like the collective-precision and redistribution knobs, the policy is
registered in every compiled-program cache key
(:func:`heat_tpu.core._compile.register_key_context`), so flipping it
retraces fresh programs instead of replaying bodies built under the
other schedule — which is also what lets one run hold the overlapped
ring and its serial twin side by side.

Telemetry (all behind the single ``_tel.enabled`` predicate — zero
overhead while disabled):

- ``comm.ring.dispatch.overlapped`` / ``comm.ring.dispatch.serial``
  counters and the ``comm.overlap_ratio`` gauge (overlapped fraction of
  eager ring dispatches so far);
- per-ring ``comm:<ring>:step:issue`` / ``comm:<ring>:step:consume``
  span pairs around each eager ring dispatch: the *issue* span covers
  the (asynchronous) dispatch enqueue, the *consume* span covers the
  wait for the result — in a Perfetto trace an overlapped ring shows a
  short issue slice and the whole wait in consume.  Spans are host-side
  by construction (SPMD205): they wrap the eager call site, never the
  traced body.

docs/design.md §18 documents the double-buffer carry shapes and the
overlap-efficiency bench metric built on this policy.
"""

from __future__ import annotations

import contextlib
from typing import Tuple

import jax

from ..core._compile import register_key_context
from ..telemetry import _core as _tel

__all__ = [
    "get_overlap",
    "overlap",
    "overlap_enabled",
    "set_overlap",
    "timed_dispatch",
]

_MODES = ("on", "off", "auto")
_OVERLAP = "auto"


# --------------------------------------------------------------------- #
# policy (mirrors compressed.set_collective_precision)                   #
# --------------------------------------------------------------------- #
def set_overlap(mode: str) -> None:
    """Set the process-wide ring-overlap policy.

    ``"on"``
        Every converted ring runs its double-buffered (latency-hiding)
        body.
    ``"off"``
        The serial step-by-step bodies (the exact twins).
    ``"auto"``
        The default: double-buffered on TPU backends, serial elsewhere.
    """
    global _OVERLAP
    if mode not in _MODES:
        raise ValueError(
            f"unknown overlap mode {mode!r}: expected one of {_MODES}"
        )
    _OVERLAP = mode


def get_overlap() -> str:
    """The current process-wide ring-overlap policy."""
    return _OVERLAP


@contextlib.contextmanager
def overlap(mode: str):
    """Context-manager form of :func:`set_overlap`."""
    prev = _OVERLAP
    set_overlap(mode)
    try:
        yield
    finally:
        set_overlap(prev)


@register_key_context
def _overlap_token() -> Tuple:
    """The overlap policy's contribution to every compiled-program cache
    key: flipping the policy keys fresh entries (the serial twin and the
    overlapped ring coexist in one run), instead of replaying a body
    built under the other schedule.  The backend check inside
    :func:`overlap_enabled` is deliberately NOT part of the token — the
    process backend is fixed for the life of the cache."""
    return ("overlap", _OVERLAP)


def overlap_enabled(size: int) -> bool:
    """Whether a ring over ``size`` devices should trace its
    double-buffered body under the current policy.

    Size-1 "rings" have no wire to hide and always stay serial; under
    ``"auto"`` only TPU backends — where DMA and MXU genuinely run
    concurrently — pay the double-buffer's extra live slab.
    """
    if _OVERLAP == "off" or size <= 1:
        return False
    if _OVERLAP == "on":
        return True
    return jax.default_backend() == "tpu"


# --------------------------------------------------------------------- #
# telemetry: overlap ratio + issue/consume span pairs                    #
# --------------------------------------------------------------------- #
def _note_ring(overlapped: bool) -> None:
    """Count one eager ring dispatch and refresh the
    ``comm.overlap_ratio`` gauge.  Caller holds the ``_tel.enabled``
    predicate."""
    _tel.inc(
        "comm.ring.dispatch.overlapped" if overlapped
        else "comm.ring.dispatch.serial"
    )
    with _tel._lock:
        ov = _tel._counters.get("comm.ring.dispatch.overlapped", 0)
        se = _tel._counters.get("comm.ring.dispatch.serial", 0)
    _tel.gauge("comm.overlap_ratio", ov / (ov + se))


def timed_dispatch(ring: str, overlapped: bool, launch):
    """Run one eager ring dispatch under a ``comm:<ring>:step`` span
    pair: the *issue* span times the dispatch enqueue, the *consume*
    span times the wait for the result (``jax.block_until_ready``).
    With telemetry disabled this is exactly ``launch()`` — one predicate
    read, no spans, no sync (the zero-overhead contract)."""
    if not _tel.enabled:
        return launch()
    _note_ring(overlapped)
    with _tel.span(f"comm:{ring}:step:issue", overlapped=overlapped):
        out = launch()
    with _tel.span(f"comm:{ring}:step:consume", overlapped=overlapped):
        jax.block_until_ready(out)
    return out
