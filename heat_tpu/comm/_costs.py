"""Pure wire/memory cost arithmetic shared by runtime and static analysis.

This module is the ONE place the byte models live:

- :func:`ring_wire_model` — bytes per device for one ring collective
  (the public :func:`heat_tpu.comm.compressed.wire_model` delegates here),
- :func:`plan_cost` — the planned-redistribution schedule and its
  wire/peak model (:func:`heat_tpu.comm.redistribute.plan` delegates its
  arithmetic here),
- :func:`monolithic_cost` — the one-shot GSPMD reshard envelope
  (:func:`heat_tpu.comm.redistribute.monolithic_model` delegates here),
- :func:`resolve_mode` — the collective-precision policy arithmetic
  (which payloads compress, given an explicit policy + threshold),
- :class:`LayoutSolver` — the cost-driven auto-layout search behind
  ``ht.autoshard`` (docs/design.md §21): dynamic programming with an
  optional beam bound over a splitflow layout-transfer summary, pricing
  every candidate seam placement with the SAME :func:`plan_cost` /
  :func:`grid_plan_cost` / :func:`critical_path_ms` arithmetic the
  runtime is credited with, plus :func:`summa_grid_model` for locked
  matmul panels riding along in the objective.

It deliberately imports NOTHING from jax or the rest of the package
(stdlib only), so the static analyzer in
:mod:`heat_tpu.analysis.splitflow` can load it by file path — via
``importlib.util.spec_from_file_location`` — and compute the exact bytes
the telemetry ledger will be credited with at runtime, without ever
importing jax.  Because the runtime paths *delegate* to these functions
rather than duplicating them, the statically reported numbers and the
runtime-accounted numbers cannot drift apart; the oracle lane in
``tests/test_splitflow_oracle.py`` asserts the equality end-to-end.

All byte figures are PER DEVICE, matching the telemetry ledger's
convention (docs/design.md §14).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

__all__ = [
    "BLOCK",
    "DEFAULT_H2D_GBPS",
    "DEFAULT_HOST_READ_GBPS",
    "DEFAULT_ICI_GBPS",
    "LayoutSolver",
    "critical_path_ms",
    "encoded_bytes",
    "grid_panel_bounds",
    "grid_plan_cost",
    "grid_qr_model",
    "itemsize",
    "layout_rank",
    "monolithic_cost",
    "plan_cost",
    "qdwh_svd_model",
    "resolve_mode",
    "ring_wire_model",
    "stream_model",
    "summa_grid_model",
]

#: Quantization block length: one f32 scale per this many payload values.
#: 128 is the TPU lane width, so every block is one register row and the
#: scale overhead is 4/128 bytes/value (wire ratio ~0.258x of exact f32).
BLOCK = 128

#: dtype-name → bytes per element, for the dtypes the package produces.
#: A plain table (not ``np.dtype``) keeps this module stdlib-only.
_ITEMSIZES = {
    "bool": 1, "int8": 1, "uint8": 1,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "float32": 4, "int32": 4, "uint32": 4,
    "float64": 8, "int64": 8, "uint64": 8,
    "complex64": 8, "complex128": 16,
}

#: dtype names the collective-precision policy may compress; everything
#: else always rides the wire exact (spmdlint SPMD203's runtime twin).
_COMPRESSIBLE = ("float32", "bfloat16")

#: Nominal per-link ICI bandwidth (GB/s, one direction) used when a
#: critical-path estimate needs a wire-time denominator and no measured
#: figure is supplied.  A planning constant, not a measurement — bench
#: headlines always pair modeled time with a same-run measured twin.
DEFAULT_ICI_GBPS = 45.0


def critical_path_ms(
    wire_bytes: int,
    hops: int,
    compute_ms_per_step: float = 0.0,
    *,
    gbps: float = DEFAULT_ICI_GBPS,
    overlap: bool = False,
) -> float:
    """Modeled critical-path time of a ring whose ``wire_bytes`` travel
    in ``hops`` equal steps, each step followed (serial) or accompanied
    (overlap) by ``compute_ms_per_step`` of math.

    ``overlap=False`` is the strictly alternating schedule — every hop
    pays wire + compute in sequence.  ``overlap=True`` is the
    double-buffered schedule: after one warm-up hop, each step costs
    ``max(wire, compute)`` — the concurrent-DMA/MXU roofline the overlap
    policy targets (docs/design.md §18).  ``hops == 0`` degenerates to a
    single transfer plus one compute step on both schedules.
    """
    h = max(int(hops), 1)
    step_wire = (int(wire_bytes) / h) / (float(gbps) * 1e6)  # ms
    if not overlap:
        return h * (step_wire + float(compute_ms_per_step))
    return step_wire + h * max(step_wire, float(compute_ms_per_step))


#: Nominal sustained host storage read bandwidth (GB/s) for the
#: streaming-ingest model when no measured figure is supplied — local
#: NVMe territory; like :data:`DEFAULT_ICI_GBPS`, a planning constant the
#: bench always pairs with a same-run measured twin.
DEFAULT_HOST_READ_GBPS = 2.0

#: Nominal host→device copy bandwidth (GB/s, one direction) — a PCIe-class
#: placeholder for the ``device_put`` leg of the streaming pipeline.
DEFAULT_H2D_GBPS = 8.0


def stream_model(
    chunk_bytes: int,
    chunks: int,
    compute_ms_per_chunk: float = 0.0,
    *,
    read_gbps: float = DEFAULT_HOST_READ_GBPS,
    h2d_gbps: float = DEFAULT_H2D_GBPS,
    prefetch: bool = True,
) -> dict:
    """Modeled time of an out-of-core streaming fit: ``chunks`` slabs of
    ``chunk_bytes`` each read from storage, copied host→device, and
    consumed by one compiled segment of ``compute_ms_per_chunk``.

    The two schedules are :func:`critical_path_ms`'s pair transplanted to
    the io boundary (docs/design.md §24): serial is
    ``h·(read + copy + compute)``; the double-buffered schedule hides the
    ingest stage behind compute after one warm-up slab —
    ``(read + copy) + h·max(read + copy, compute)``.  ``peak_host_slabs``
    is the schedule's host-memory bound (two live slabs overlapped, one
    serial), which :func:`heat_tpu.io.stream.slab_peak` is asserted
    against.  ``bound`` names the roofline side the overlapped schedule
    sits on: ``"ingest"`` when the stream cannot feed the device fast
    enough (read+copy > compute), else ``"compute"``.
    """
    h = max(int(chunks), 1)
    cb = int(chunk_bytes)
    read_ms = cb / (float(read_gbps) * 1e6)
    h2d_ms = cb / (float(h2d_gbps) * 1e6)
    stage_ms = read_ms + h2d_ms
    compute_ms = float(compute_ms_per_chunk)
    serial_ms = h * (stage_ms + compute_ms)
    overlapped_ms = stage_ms + h * max(stage_ms, compute_ms)
    best_ms = overlapped_ms if prefetch else serial_ms
    return {
        "chunks": h,
        "chunk_bytes": cb,
        "read_ms_per_chunk": read_ms,
        "h2d_ms_per_chunk": h2d_ms,
        "compute_ms_per_chunk": compute_ms,
        "serial_ms": serial_ms,
        "overlapped_ms": overlapped_ms,
        "speedup": serial_ms / overlapped_ms if overlapped_ms > 0.0 else 1.0,
        "prefetch": bool(prefetch),
        "peak_host_slabs": 2 if prefetch else 1,
        "bound": "ingest" if stage_ms >= compute_ms else "compute",
        "modeled_ms": best_ms,
    }


def itemsize(dtype_name: str) -> int:
    """Bytes per element of a canonical dtype name (e.g. ``"float32"``)."""
    try:
        return _ITEMSIZES[str(dtype_name)]
    except KeyError:
        raise ValueError(f"unknown dtype name {dtype_name!r}") from None


def resolve_mode(
    dtype_name: str,
    payload_nbytes: int,
    precision: str = "f32",
    threshold: int = 1 << 16,
) -> Optional[str]:
    """Wire mode a payload rides under the given precision policy.

    Returns ``"bf16"`` / ``"int8_block"``, or ``None`` for exact
    transmission — the same decision table as
    :func:`heat_tpu.comm.compressed.reduce_mode` with the process-global
    policy passed in explicitly (that function delegates here after its
    own contract checks).
    """
    if precision == "f32" or precision is None:
        return None
    if str(dtype_name) not in _COMPRESSIBLE:
        return None
    if precision == "auto":
        return "int8_block" if int(payload_nbytes) >= int(threshold) else None
    return precision


def encoded_bytes(n_elems: int, mode: Optional[str], item: int) -> int:
    """Bytes one payload of ``n_elems`` occupies on the wire under
    ``mode`` (block-padded; one f32 scale per :data:`BLOCK` for int8)."""
    if mode is None:
        return int(n_elems) * int(item)
    padded = max(BLOCK, -(-int(n_elems) // BLOCK) * BLOCK)
    if mode == "int8_block":
        return padded + (padded // BLOCK) * 4
    return padded * 2  # bf16


def ring_wire_model(n_elems: int, size: int, mode: Optional[str], *,
                    block: int = BLOCK, op: str = "allreduce") -> dict:
    """Bytes-moved model for one ring collective, per device.

    The single source of the 0.258x claim: exact f32 ships 4 B/element,
    ``int8_block`` 1 B/element plus one f32 scale per ``block`` elements
    (132/512 per 128-block), ``bf16`` 2 B/element.  ``op="allreduce"``
    models the reduce-scatter + all-gather ring (each device sends
    ``2*(size-1)`` chunks of ``ceil(n/size)`` elements padded to the
    block grid); ``op="allgather"`` the one-way ring (``size-1`` hops of
    the ``n_elems``-element local shard).
    """
    p = max(int(size), 1)
    if op == "allreduce":
        chunk = -(-int(n_elems) // p)
        hops = 2 * (p - 1)
    elif op == "allgather":
        chunk = int(n_elems)
        hops = p - 1
    else:
        raise ValueError(f"unknown ring op {op!r}")
    chunk_p = -(-chunk // int(block)) * int(block)
    exact = hops * chunk_p * 4
    if mode == "int8_block":
        wire = hops * (chunk_p + (chunk_p // int(block)) * 4)
    elif mode == "bf16":
        wire = hops * chunk_p * 2
    else:  # exact transmission (policy answered None / "f32")
        wire = exact
    return {
        "ring_hops_per_device": hops,
        "chunk_elems_padded": chunk_p,
        "exact_wire_bytes": exact,
        "wire_bytes": wire,
        "bytes_ratio": round(wire / exact, 4) if exact else None,
    }


def _nelems(shape: Tuple[int, ...]) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def monolithic_cost(shape: Tuple[int, ...], item: int,
                    src: Optional[int], dst: Optional[int], size: int) -> dict:
    """Per-device cost envelope of the one-shot GSPMD reshard.

    split→None is an all-gather (``(p-1)/p`` of the array per device; the
    full array live).  None→split is a local slice (zero wire).
    split→split is modeled as the reference ``Alltoallv``'s envelope —
    the general GSPMD lowering gathers then slices, so the wire bytes are
    the all-gather's and the peak briefly holds the full array plus the
    input shard.
    """
    p = max(int(size), 1)
    total = _nelems(shape) * int(item)
    if p == 1 or src == dst or (src is None and dst is None):
        return {"exact_wire_bytes": 0, "wire_bytes": 0, "peak_live_bytes": total}
    if src is None:  # replicated -> split: local slice
        return {
            "exact_wire_bytes": 0,
            "wire_bytes": 0,
            "peak_live_bytes": total + total // p,
        }
    gather = (p - 1) * (total // p)  # each device receives p-1 foreign shards
    peak = total + total // p  # full array + own shard live at the boundary
    return {"exact_wire_bytes": gather, "wire_bytes": gather, "peak_live_bytes": peak}


def plan_cost(
    shape: Tuple[int, ...],
    dtype_name: str,
    src: Optional[int],
    dst: Optional[int],
    size: int,
    *,
    mode_for: Optional[Callable[[int], Optional[str]]] = None,
    overlap: bool = False,
) -> dict:
    """Schedule + cost model of the planned redistribution.

    The arithmetic half of :func:`heat_tpu.comm.redistribute.plan`:
    returns ``{steps, mode, wire_bytes, exact_wire_bytes,
    peak_live_bytes}`` for a ``shape`` array committed at split ``src``
    moving to split ``dst`` over ``size`` devices.  ``mode_for`` maps a
    wire payload's byte count to its compression mode (defaults to exact
    transmission); the runtime passes the live collective-precision
    policy, the static analyzer whatever policy it is asked to model.

    ``overlap=True`` models the pipelined rotation schedule (two pieces
    in flight instead of one): wire bytes are unchanged, the split→split
    peak grows by one piece (plus its f32 staging when compressed).

    Steps and figures are identical to the runtime planner's — the
    runtime delegates here, so they cannot diverge.
    """
    shape = tuple(int(s) for s in shape)
    item = itemsize(dtype_name)
    p = max(int(size), 1)
    n = _nelems(shape)
    total = n * item
    mode_for = mode_for or (lambda nbytes: None)

    if p == 1 or src == dst or not shape or n == 0:
        at_rest = total if src is None else total // p
        return {
            "steps": (), "mode": None, "wire_bytes": 0,
            "exact_wire_bytes": 0, "peak_live_bytes": at_rest,
        }

    if dst is not None:
        w_d = -(-shape[dst] // p)
        pad_d = p * w_d - shape[dst]

    if src is None:
        # replicated -> split: pure local slice-discard, zero wire.
        steps = []
        if pad_d:
            steps.append(("pad", dst, shape[dst]))
        steps.append(("slice", dst))
        padded_total = (n // shape[dst]) * (p * w_d) * item
        peak = padded_total + padded_total // p  # full input + own slab
        return {
            "steps": tuple(steps), "mode": None, "wire_bytes": 0,
            "exact_wire_bytes": 0, "peak_live_bytes": peak,
        }

    if dst is None:
        # split -> replicated: all-gather fraction.  Each device ships
        # its shard p-1 times around the ring; mode compresses the
        # payload.
        shard_elems = n // p
        mode = mode_for(shard_elems * item)
        exact = (p - 1) * shard_elems * item
        wire = (p - 1) * encoded_bytes(shard_elems, mode, item)
        peak = total // p + total  # own shard + assembled full array
        if mode is not None:
            peak += shard_elems * 4  # f32 staging of the encoded payload
        return {
            "steps": (("allgather", src),), "mode": mode, "wire_bytes": wire,
            "exact_wire_bytes": exact, "peak_live_bytes": peak,
        }

    # split -> split: p-1 ppermute rotations over 1/p²-sized pieces.
    # Wire (p-1)/p² of the array per device — p× less than gather+slice —
    # and peak = input shard + output shard + one piece in flight.
    w_s = shape[src] // p
    rest = n // shape[src] // shape[dst]  # elements off the two split axes
    piece_elems = w_s * w_d * rest
    mode = mode_for(piece_elems * item)
    steps = []
    if pad_d:
        steps.append(("pad", dst, shape[dst]))
    steps.append(("view", dst))
    steps.extend(("rotate", k) for k in range(1, p))
    steps.append(("assemble", src))
    exact = (p - 1) * piece_elems * item
    wire = (p - 1) * encoded_bytes(piece_elems, mode, item)
    slab = p * piece_elems * item  # == padded input shard == output shard
    in_flight = 2 if overlap else 1  # pipelined rotations double-buffer
    peak = 2 * slab + in_flight * piece_elems * item
    if mode is not None:
        peak += in_flight * piece_elems * 4  # f32 staging of encoded pieces
    return {
        "steps": tuple(steps), "mode": mode, "wire_bytes": wire,
        "exact_wire_bytes": exact, "peak_live_bytes": peak,
    }


def _dim_of(layout, g: int) -> Optional[int]:
    """Array dim sharded by mesh axis ``g`` under ``layout`` (splits
    tuple: ``layout[d]`` is the mesh axis sharding dim ``d``)."""
    for d, x in enumerate(layout):
        if x == g:
            return d
    return None


def _check_splits(name: str, splits, ndim: int, mesh_ndim: int) -> Tuple:
    splits = tuple(None if g is None else int(g) for g in splits)
    if len(splits) != ndim:
        raise ValueError(
            f"{name} splits {splits} has arity {len(splits)} for a "
            f"{ndim}-dimensional shape"
        )
    seen = set()
    for g in splits:
        if g is None:
            continue
        if not 0 <= g < mesh_ndim:
            raise ValueError(
                f"{name} splits {splits}: mesh axis {g} out of range for a "
                f"{mesh_ndim}-axis mesh"
            )
        if g in seen:
            raise ValueError(f"{name} splits {splits}: mesh axis {g} used twice")
        seen.add(g)
    return splits


def grid_plan_cost(
    shape: Tuple[int, ...],
    dtype_name: str,
    src_splits: Tuple[Optional[int], ...],
    dst_splits: Tuple[Optional[int], ...],
    mesh_shape: Tuple[int, ...],
    *,
    mode_for: Optional[Callable[[int], Optional[str]]] = None,
    overlap: bool = False,
) -> dict:
    """Schedule + cost model of a planned N-D (grid) redistribution.

    Factors the (``src_splits`` → ``dst_splits``) layout change into a
    short sequence of per-mesh-axis 1-D **stages**, each priced by
    :func:`plan_cost` over the sub-mesh of that axis.  The greedy
    ordering moves each mesh axis directly (``src dim → dst dim``) when
    its target dim is free; a cyclic layout transpose (e.g. ``(0, 1) →
    (1, 0)`` on a 2-D mesh) is broken by routing one axis through
    replicated, exactly like the 1-D planner's split→None→split escape
    hatch.  Every stage's 1-D cost is evaluated on the stage-local
    extents — dims held sharded by *other* mesh axes enter at their local
    (padded) widths — so wire bytes are the sum of stage wires and the
    modeled peak is the max of stage peaks.

    Source-sharded dims must divide their mesh axis (the canonical
    commit invariant; ragged arrays reach planners replicated, as in the
    1-D contract).  Returns the :func:`plan_cost` dict extended with
    ``stages`` (``(mesh_axis, src_dim, dst_dim)`` triples — the runtime
    program builder replays exactly these) and ``out_shape`` (the true
    shape with ragged destination dims padded).  Step tuples carry the
    mesh axis as their second element: ``("rotate", g, k)``.
    """
    shape = tuple(int(s) for s in shape)
    ndim = len(shape)
    mesh_shape = tuple(max(int(p), 1) for p in mesh_shape)
    mesh_ndim = len(mesh_shape)
    src = _check_splits("source", src_splits, ndim, mesh_ndim)
    dst = _check_splits("destination", dst_splits, ndim, mesh_ndim)
    item = itemsize(dtype_name)
    mode_for = mode_for or (lambda nbytes: None)
    for d, g in enumerate(src):
        if g is not None and shape[d] % mesh_shape[g]:
            raise ValueError(
                f"ragged source axis: shape {shape} dim {d} does not divide "
                f"over {mesh_shape[g]} devices along mesh axis {g} (a "
                "canonically committed input is divisible; ragged dims live "
                "replicated and plan as src=None)"
            )

    # greedy stage factoring over the mesh axes whose dim assignment moves
    state = list(src)
    remaining = {g for g in range(mesh_ndim) if _dim_of(state, g) != _dim_of(dst, g)}
    stages = []
    while remaining:
        progressed = False
        for g in sorted(remaining):
            sd, td = _dim_of(state, g), _dim_of(dst, g)
            if td is not None and state[td] is not None and state[td] != g:
                continue  # target dim held by another mesh axis: blocked
            stages.append((g, sd, td))
            if sd is not None:
                state[sd] = None
            if td is not None:
                state[td] = g
            remaining.discard(g)
            progressed = True
        if not progressed:
            # cyclic layout transpose: break the lowest blocked axis's
            # move through replicated; its None→dst leg runs once the
            # axis holding its target dim has moved off
            g = min(remaining)
            sd = _dim_of(state, g)
            stages.append((g, sd, None))
            state[sd] = None

    # price each stage on its stage-local extents
    ext = list(shape)  # current padded global extents
    state = list(src)
    steps, stage_modes = [], []
    wire = exact = 0
    at_rest = _nelems(shape) * item
    for g in (x for x in src if x is not None):
        at_rest //= mesh_shape[g]
    peak = at_rest
    for g, sd, td in stages:
        p = mesh_shape[g]
        eff = []
        for d in range(ndim):
            h = state[d]
            if d in (sd, td) or h is None or h == g:
                eff.append(ext[d])
            else:
                eff.append(ext[d] // mesh_shape[h])  # local width elsewhere
        sub = plan_cost(
            tuple(eff), dtype_name, sd, td, p, mode_for=mode_for, overlap=overlap
        )
        steps.extend((s[0], g) + s[1:] for s in sub["steps"])
        stage_modes.append(sub["mode"])
        wire += sub["wire_bytes"]
        exact += sub["exact_wire_bytes"]
        peak = max(peak, sub["peak_live_bytes"])
        if sd is not None:
            state[sd] = None
        if td is not None:
            state[td] = g
            ext[td] = p * (-(-ext[td] // p))
    mode = next((m for m in stage_modes if m is not None), None)
    out_shape = list(shape)
    for d, g in enumerate(dst):
        if g is not None:
            p = mesh_shape[g]
            out_shape[d] = p * (-(-out_shape[d] // p))
    return {
        "steps": tuple(steps), "mode": mode, "wire_bytes": int(wire),
        "exact_wire_bytes": int(exact), "peak_live_bytes": int(peak),
        "stages": tuple(stages), "stage_modes": tuple(stage_modes),
        "out_shape": tuple(out_shape),
    }


def layout_rank(layout) -> Tuple:
    """Deterministic total order over layout spellings — the solver's
    tie-break.  Replicated sorts first, then int splits by axis, then
    splits tuples entrywise (``None`` entries below mesh axes), so equal
    argmin costs always resolve to the same plan on every run."""
    if layout is None:
        return (0, ())
    if isinstance(layout, tuple):
        return (2, tuple(-1 if g is None else int(g) for g in layout))
    return (1, (int(layout),))


def _one_hot(layout, ndim: int, mesh_ndim: int):
    """Promote the 1-D compat spelling to a splits tuple on mesh axis 0
    (the ``normalize_splits`` convention); tuples pass through."""
    if isinstance(layout, tuple):
        return tuple(None if g is None else int(g) for g in layout)
    out = [None] * int(ndim)
    if layout is not None:
        out[int(layout)] = 0
    return tuple(out)


class LayoutSolver:
    """Cost-driven auto-layout search over a splitflow call summary.

    The solver behind ``ht.autoshard`` (docs/design.md §21).  Input is a
    *layout-transfer summary* — plain data exported by
    :mod:`heat_tpu.analysis.splitflow.summary` — whose ``seams`` are the
    pipeline's layout-change events in program order, each carrying a
    literal shape/dtype, the hand-placed ``src``/``dst`` layouts, chain
    provenance (``prev``: the seam producing this seam's operand, when
    that intermediate is dead), and the op layer's declared layout
    ``alternatives`` (``core/_split_semantics.layout_alternatives``).

    Search space: for every chain of seams over one value, each
    non-pinned intermediate placement ranges over the declared
    alternatives (1-D splits and splits tuples); the chain's final
    placement stays pinned to the hand layout, so a solved pipeline is a
    drop-in — identical output metadata, bitwise-identical values.
    Choosing the incoming layout again elides the seam entirely.  Each
    seam additionally prices its collective-precision arm
    (``choose_precision=True``: the ambient-policy mode vs exact f32 —
    block padding and scale rows make compression a *loss* on small
    payloads, which ``resolve_mode``'s threshold alone cannot see).

    Objective (lexicographic): total ``wire_bytes``, then total
    :func:`critical_path_ms` under the solver's overlap arm (so the
    PR 11 double-buffered schedule is priced, not just byte counts),
    then :func:`layout_rank` of the placement path — a deterministic
    tie-break, identical plan on every run.  Exact dynamic programming
    per chain; ``beam_width`` bounds the per-position frontier for large
    alternative sets (pruning is by the same objective, so it stays
    deterministic).  Locked ``matmul`` seams ride along in both totals
    via :func:`summa_grid_model` — priced, never re-placed (v1).

    Stdlib-only on purpose: the static analyzer loads this file by path,
    and the runtime delegates to the same arithmetic, so the plan a
    pipeline executes and the bytes its ledger is credited with cannot
    drift from the numbers solved here.
    """

    def __init__(
        self,
        size: Optional[int] = None,
        *,
        mesh_shape: Optional[Tuple[int, ...]] = None,
        precision: Optional[str] = "f32",
        threshold: int = 1 << 16,
        overlap: bool = False,
        compute_ms_per_step: float = 0.0,
        gbps: float = DEFAULT_ICI_GBPS,
        beam_width: int = 64,
        choose_precision: bool = False,
    ):
        if mesh_shape is not None:
            self.mesh_shape = tuple(max(int(p), 1) for p in mesh_shape)
            self.size = 1
            for p in self.mesh_shape:
                self.size *= p
        else:
            self.size = max(int(size if size is not None else 1), 1)
            self.mesh_shape = None
        self.precision = precision
        self.threshold = int(threshold)
        self.overlap = bool(overlap)
        self.compute_ms_per_step = float(compute_ms_per_step)
        self.gbps = float(gbps)
        self.beam_width = max(int(beam_width), 1)
        self.choose_precision = bool(choose_precision)

    # ------------------------------------------------------------------ #
    # pricing                                                             #
    # ------------------------------------------------------------------ #
    def price(self, shape, dtype_name, src, dst, *, choose=None) -> dict:
        """Price one layout change with the runtime's own arithmetic.

        Tuple spellings (or any solver built with ``mesh_shape``) route
        through :func:`grid_plan_cost`; the 1-D compat spelling through
        :func:`plan_cost`.  With ``choose`` (default: the solver's
        ``choose_precision``) the cheaper of the ambient-policy mode and
        exact transmission wins, ties to exact.
        """
        shape = tuple(int(s) for s in shape)
        choose = self.choose_precision if choose is None else bool(choose)
        grid = self.mesh_shape is not None and (
            len(self.mesh_shape) > 1
            or isinstance(src, tuple) or isinstance(dst, tuple)
        )

        def ambient(nbytes):
            return resolve_mode(dtype_name, nbytes, self.precision, self.threshold)

        arms = [ambient]
        if choose:
            arms.append(lambda nbytes: None)
        best = None
        for mode_for in arms:
            if grid:
                plan = grid_plan_cost(
                    shape, dtype_name,
                    _one_hot(src, len(shape), len(self.mesh_shape)),
                    _one_hot(dst, len(shape), len(self.mesh_shape)),
                    self.mesh_shape, mode_for=mode_for, overlap=self.overlap,
                )
            else:
                plan = plan_cost(
                    shape, dtype_name, src, dst, self.size,
                    mode_for=mode_for, overlap=self.overlap,
                )
            hops = sum(1 for s in plan["steps"] if s[0] == "rotate")
            arm = {
                "wire_bytes": plan["wire_bytes"],
                "exact_wire_bytes": plan["exact_wire_bytes"],
                "peak_live_bytes": plan["peak_live_bytes"],
                "mode": plan["mode"],
                "hops": hops,
                "critical_path_ms": {
                    "serial": critical_path_ms(
                        plan["wire_bytes"], hops, self.compute_ms_per_step,
                        gbps=self.gbps, overlap=False,
                    ),
                    "overlap": critical_path_ms(
                        plan["wire_bytes"], hops, self.compute_ms_per_step,
                        gbps=self.gbps, overlap=True,
                    ),
                },
            }
            key = (arm["wire_bytes"], 0 if arm["mode"] is None else 1)
            if best is None or key < best[0]:
                best = (key, arm)
        return best[1]

    def matmul_cost(self, m: int, k: int, n: int, *, mode=None) -> dict:
        """Locked-rider pricing of a matmul seam: the grid SUMMA model on
        this solver's mesh (1-D meshes price as a degenerate ``(p, 1)``
        grid — the row-ring panel schedule)."""
        mesh = self.mesh_shape if (
            self.mesh_shape is not None and len(self.mesh_shape) == 2
        ) else (self.size, 1)
        return summa_grid_model(
            m, k, n, mesh, mode=mode, overlap=self.overlap,
            compute_ms_per_step=self.compute_ms_per_step, gbps=self.gbps,
        )

    # ------------------------------------------------------------------ #
    # search                                                              #
    # ------------------------------------------------------------------ #
    def _cp(self, priced: dict) -> float:
        return priced["critical_path_ms"]["overlap" if self.overlap else "serial"]

    def _candidates(self, seam: dict, locked: bool):
        hand = seam["dst"]
        if locked:
            return [hand]
        alts = seam.get("alternatives") or ()
        cands = list(alts)
        if hand not in cands:
            cands.append(hand)
        cands.sort(key=layout_rank)
        return cands

    def solve(self, summary: dict) -> dict:
        """Search the summary's layout space; return the argmin plan.

        The plan is plain data: per-seam ``decisions`` keyed by the
        runtime signature ``(shape, dtype, solved-incoming layout,
        hand-requested layout)`` — what ``manipulations.resplit`` sees at
        the call site under the solved plan — plus solved and hand
        totals and a stable ``fingerprint`` (part of the fuse cache key).
        """
        import hashlib

        seams = [dict(s) for s in summary.get("seams", ())]
        by_index = {s["index"]: s for s in seams}
        next_of = {}
        for s in seams:
            prev = s.get("prev")
            if prev is not None and prev in by_index:
                next_of[prev] = s["index"]
        heads = [
            s["index"] for s in seams
            if s["op"] in ("resplit", "noop_collective")
            and (s.get("prev") is None or s["prev"] not in by_index)
        ]

        decisions = []
        totals = {"wire": 0, "exact": 0, "cp_serial": 0.0, "cp_overlap": 0.0}
        hand = {"wire": 0, "exact": 0, "cp_serial": 0.0, "cp_overlap": 0.0}

        def _tally(bucket, priced):
            bucket["wire"] += priced["wire_bytes"]
            bucket["exact"] += priced["exact_wire_bytes"]
            bucket["cp_serial"] += priced["critical_path_ms"]["serial"]
            bucket["cp_overlap"] += priced["critical_path_ms"]["overlap"]

        for s in seams:
            if s["op"] == "matmul":
                if s.get("shape") is not None and len(s["shape"]) == 3:
                    m, k, n = (int(x) for x in s["shape"])
                    rider = self.matmul_cost(m, k, n)
                    for bucket in (totals, hand):
                        bucket["wire"] += rider["wire_bytes"]
                        bucket["exact"] += rider["exact_wire_bytes"]
                        bucket["cp_serial"] += rider["critical_path_ms"]["serial"]
                        bucket["cp_overlap"] += rider["critical_path_ms"]["overlap"]
                continue
            _tally(hand, self.price(
                s["shape"], s["dtype"], s["src"], s["dst"], choose=False
            ))
            if s["op"] == "implicit_resplit":
                # locked v1: the binary-op anchor stays; priced, not moved
                priced = self.price(
                    s["shape"], s["dtype"], s["src"], s["dst"], choose=False
                )
                _tally(totals, priced)
                decisions.append(self._decision(s, s["src"], s["dst"], priced))

        for head in sorted(heads):
            chain = [by_index[head]]
            while chain[-1]["index"] in next_of:
                chain.append(by_index[next_of[chain[-1]["index"]]])
            entry = chain[0]["src"]
            # frontier: layout -> (wire, cp, rank-path, placements)
            frontier = {entry: (0, 0.0, (), ())}
            priced_edges = []
            for pos, seam in enumerate(chain):
                last = pos == len(chain) - 1
                locked = last or bool(seam.get("pinned"))
                cands = self._candidates(seam, locked)
                nxt = {}
                edge_prices = {}
                for lay in sorted(frontier, key=layout_rank):
                    w, cp, rp, path = frontier[lay]
                    for cand in cands:
                        p = self.price(seam["shape"], seam["dtype"], lay, cand)
                        edge_prices[(lay, cand)] = p
                        tup = (
                            w + p["wire_bytes"], cp + self._cp(p),
                            rp + (layout_rank(cand),), path + ((lay, cand),),
                        )
                        cur = nxt.get(cand)
                        if cur is None or tup[:3] < cur[:3]:
                            nxt[cand] = tup
                if len(nxt) > self.beam_width:
                    keep = sorted(nxt, key=lambda c: nxt[c][:3])[: self.beam_width]
                    nxt = {c: nxt[c] for c in keep}
                frontier = nxt
                priced_edges.append(edge_prices)
            final = min(frontier, key=lambda c: frontier[c][:3])
            _, _, _, path = frontier[final]
            for pos, (seam, (incoming, chosen)) in enumerate(zip(chain, path)):
                p = priced_edges[pos][(incoming, chosen)]
                _tally(totals, p)
                decisions.append(self._decision(seam, incoming, chosen, p))

        decisions.sort(key=lambda d: d["seam"])
        canonical = (
            "autoshard-plan", summary.get("function"),
            self.mesh_shape or self.size, self.precision, self.threshold,
            self.overlap, self.choose_precision,
            tuple(
                (d["seam"], d["shape"], d["dtype"],
                 layout_rank(d["src"]), layout_rank(d["requested"]),
                 layout_rank(d["apply"]), d["mode"], d["wire_bytes"])
                for d in decisions
            ),
        )
        fingerprint = hashlib.sha256(repr(canonical).encode()).hexdigest()[:16]
        return {
            "function": summary.get("function"),
            "fingerprint": fingerprint,
            "mesh": self.mesh_shape or self.size,
            "precision": self.precision,
            "overlap": self.overlap,
            "decisions": decisions,
            "modeled_wire_bytes": totals["wire"],
            "modeled_exact_bytes": totals["exact"],
            "modeled_critical_path_ms": {
                "serial": totals["cp_serial"], "overlap": totals["cp_overlap"],
            },
            "hand_wire_bytes": hand["wire"],
            "hand_exact_bytes": hand["exact"],
            "hand_critical_path_ms": {
                "serial": hand["cp_serial"], "overlap": hand["cp_overlap"],
            },
        }

    def _decision(self, seam, incoming, chosen, priced) -> dict:
        return {
            "seam": seam["index"],
            "op": seam["op"],
            "line": seam.get("line"),
            "shape": tuple(int(x) for x in seam["shape"]),
            "dtype": seam["dtype"],
            "src": incoming,
            "requested": seam["dst"],
            "apply": chosen,
            "elide": layout_rank(chosen) == layout_rank(incoming),
            "mode": priced["mode"],
            "wire_bytes": priced["wire_bytes"],
            "exact_bytes": priced["exact_wire_bytes"],
            "critical_path_ms": dict(priced["critical_path_ms"]),
        }


def summa_grid_model(
    m: int,
    k: int,
    n: int,
    mesh_shape: Tuple[int, int],
    *,
    mode: Optional[str] = None,
    overlap: bool = False,
    layout: str = "grid",
    compute_ms_per_step: float = 0.0,
    gbps: float = DEFAULT_ICI_GBPS,
) -> dict:
    """Per-device wire/memory model of the grid SUMMA matmul.

    ``layout`` selects the operand schedule on the ``r×c`` mesh:

    * ``"grid"`` — A splits ``(0, 1)``, B splits ``(0, 1)``: the schedule
      runs ``L = r*c`` k-panels of width ``w = ceil(k / L)``; each panel
      step broadcasts A's ``(m/r, w)`` panel along the mesh columns (a
      masked psum over the ``c``-ring) and B's ``(w, n/c)`` panel along
      the mesh rows (over the ``r``-ring).
    * ``"rowcol"`` — A splits ``(0, None)``, B splits ``(None, 1)``: every
      device already owns A's full k rows for its row block and B's full
      k columns for its column block, so the same L-panel accumulation
      runs entirely rank-local — ZERO wire.  This is the layout whose
      modeled bytes are strictly below the redistribute-to-``(0, 1)``-
      then-SUMMA alternative (which pays the full grid broadcast wire).
    * ``"colrow"`` — A splits ``(None, 1)``, B splits ``(0, None)``: the
      k axis is the sharded axis of both operands, and the panel
      broadcasts (owner slices its own row/column block before the masked
      psum) ship exactly the grid schedule's bytes — wire PARITY with
      redistribute-then-SUMMA; the win is eliding the two planned
      redistribution dispatches and their committed copies.

    All three run the identical L-step panel-ordered accumulation, so
    they share one bitwise replicated twin.  Figures assume f32 panels
    (:func:`ring_wire_model`'s exact-byte convention); degenerate mesh
    axes contribute zero wire.  This function is the single source the
    runtime telemetry is credited from (``core/linalg/basics.py``) and
    the bench headline prices — delegation keeps accounted and modeled
    bytes identical.
    """
    if layout not in ("grid", "rowcol", "colrow"):
        raise ValueError(f"unknown SUMMA layout {layout!r}")
    r, c = (max(int(s), 1) for s in mesh_shape)
    L = r * c
    w = -(-int(k) // L) if k else 0
    mloc = -(-int(m) // r)
    nloc = -(-int(n) // c)
    if layout == "rowcol":
        hops = exact = wire = 0
    else:
        a_step = ring_wire_model(mloc * w, c, mode, op="allreduce")
        b_step = ring_wire_model(w * nloc, r, mode, op="allreduce")
        hops = L * (a_step["ring_hops_per_device"] + b_step["ring_hops_per_device"])
        exact = L * (a_step["exact_wire_bytes"] + b_step["exact_wire_bytes"])
        wire = L * (a_step["wire_bytes"] + b_step["wire_bytes"])
    # at-rest operands + accumulator + in-flight panels (x2 double-buffered)
    bufs = 2 if overlap else 1
    if layout == "rowcol":
        a_rest, b_rest = mloc * (L * w), (L * w) * nloc
    elif layout == "colrow":
        a_rest, b_rest = (r * mloc) * (r * w), (c * w) * (c * nloc)
    else:
        a_rest, b_rest = mloc * (r * w), (c * w) * nloc
    peak = 4 * (
        a_rest + b_rest + mloc * nloc
        + bufs * (mloc * w + w * nloc)
    )
    return {
        "mesh": (r, c),
        "layout": layout,
        "panels": L,
        "panel_width": w,
        "panel_a_elems": mloc * w,
        "panel_b_elems": w * nloc,
        "hops": hops,
        "exact_wire_bytes": exact,
        "wire_bytes": wire,
        "bytes_ratio": round(wire / exact, 4) if exact else None,
        "peak_live_bytes": peak,
        "critical_path_ms": {
            "serial": critical_path_ms(
                wire, hops, compute_ms_per_step, gbps=gbps, overlap=False
            ),
            "overlap": critical_path_ms(
                wire, hops, compute_ms_per_step, gbps=gbps, overlap=True
            ),
        },
    }


def grid_panel_bounds(
    n: int, c: int, tiles_per_proc: int = 1
) -> Tuple[Tuple[int, int, int], ...]:
    """The column-panel schedule of the grid blocked QR: one
    ``(owner mesh column, local column offset, width)`` triple per panel.

    Columns live block-distributed over the ``c`` mesh columns in chunks
    of ``nloc = ceil(n / c)``; each chunk's REAL width (``valid_counts``
    algebra — pads only ever trail the last nonempty chunks) is cut into
    ``tiles_per_proc`` tiles.  Pad columns are never part of any panel:
    the kernel and the wire model both iterate this exact tuple, which is
    what keeps modeled and executed collectives in lock-step."""
    c = max(int(c), 1)
    nloc = -(-int(n) // c)
    out = []
    for jc in range(c):
        vc = min(nloc, max(0, int(n) - jc * nloc))
        if vc <= 0:
            continue
        nb = -(-vc // max(int(tiles_per_proc), 1))
        lo = 0
        while lo < vc:
            out.append((jc, lo, min(nb, vc - lo)))
            lo += nb
    return tuple(out)


def grid_qr_model(
    m: int,
    n: int,
    mesh_shape: Tuple[int, int],
    *,
    tiles_per_proc: int = 1,
    mode: Optional[str] = None,
    overlap: bool = False,
    compute_ms_per_step: float = 0.0,
    gbps: float = DEFAULT_ICI_GBPS,
) -> dict:
    """Per-device wire model of the grid blocked/CAQR QR (``m >= n``,
    operand splits ``(0, 1)`` on an ``r×c`` mesh).

    Per panel of width ``nb`` (schedule from :func:`grid_panel_bounds`):

    1. panel broadcast — masked psum of the owner column's ``(m/r, nb)``
       slab along the mesh columns (``c``-ring allreduce);
    2. BCGS2 reorthogonalization (every panel after the first) — the
       ``(n/c, nb)`` projection-coefficient stack gathered down the mesh
       rows, then the ``((m/r + n/c), nb)`` correction/coefficient bundle
       gathered along the mesh columns (both all-gathers followed by a
       panel-ordered local sum, keeping the combine bitwise-pinnable);
    3. TSQR combine — the ``(nb, nb)`` R factors all-gathered down the
       mesh rows;
    4. trailing coefficients — the ``(nb, n/c)`` W partials all-gathered
       down the mesh rows and summed in row order.

    All genuine reductions go through all-gather + ordered local sum
    rather than psum: a psum's internal reduction order is unspecified,
    and the twin discipline (docs/design.md §23) requires every combine
    to be reproducible op-for-op on the replicated golden.  Figures
    assume f32 (the :func:`ring_wire_model` convention).
    """
    r, c = (max(int(s), 1) for s in mesh_shape)
    mloc = -(-int(m) // r)
    nloc = -(-int(n) // c)
    bounds = grid_panel_bounds(n, c, tiles_per_proc)
    hops = exact = wire = 0
    for idx, (_jc, _lo, nb) in enumerate(bounds):
        steps = [
            ring_wire_model(mloc * nb, c, mode, op="allreduce"),
            ring_wire_model(nb * nb, r, mode, op="allgather"),
            ring_wire_model(nb * nloc, r, mode, op="allgather"),
        ]
        if idx:
            steps.append(ring_wire_model(nloc * nb, r, mode, op="allgather"))
            steps.append(
                ring_wire_model((mloc + nloc) * nb, c, mode, op="allgather")
            )
        for s in steps:
            hops += s["ring_hops_per_device"]
            exact += s["exact_wire_bytes"]
            wire += s["wire_bytes"]
    nb_max = max((b[2] for b in bounds), default=0)
    # working set: A + Q + R columns at rest, plus the widest panel's
    # broadcast slab, TSQR stack, and W row block (x2 when the lookahead
    # arm keeps the next panel in flight)
    bufs = 2 if overlap else 1
    peak = 4 * (
        2 * mloc * nloc + (c * nloc) * nloc
        + bufs * (mloc * nb_max + r * nb_max * nb_max + r * nb_max * nloc)
    )
    return {
        "mesh": (r, c),
        "panels": len(bounds),
        "panel_widths": tuple(b[2] for b in bounds),
        "hops": hops,
        "exact_wire_bytes": exact,
        "wire_bytes": wire,
        "bytes_ratio": round(wire / exact, 4) if exact else None,
        "peak_live_bytes": peak,
        "critical_path_ms": {
            "serial": critical_path_ms(
                wire, hops, compute_ms_per_step, gbps=gbps, overlap=False
            ),
            "overlap": critical_path_ms(
                wire, hops, compute_ms_per_step, gbps=gbps, overlap=True
            ),
        },
    }


def qdwh_svd_model(
    m: int,
    n: int,
    mesh_shape: Tuple[int, int],
    *,
    iterations: int = 12,
    mode: Optional[str] = None,
    compute_ms_per_step: float = 0.0,
    gbps: float = DEFAULT_ICI_GBPS,
) -> dict:
    """Per-device wire model of the QDWH polar-decomposition SVD (``m >=
    n``, operand splits ``(0, 1)`` on an ``r×c`` mesh).

    Components, mirroring the kernel's collectives exactly:

    * init — the Frobenius-norm scale: two scalar all-gathers (down the
      mesh rows, then along the columns) with ordered local sums;
    * per Halley iteration (``iterations`` is the static trip cap the
      telemetry is credited for — the on-device ``while_loop`` may stop
      earlier, and the model documents the worst case): one grid blocked
      QR of the stacked ``(m + n, n)`` operand (:func:`grid_qr_model` on
      the row-augmented shape), the identity-block Q2 gathered down the
      mesh rows, ``c`` panel steps of the Q1·Q2ᵀ combine (two masked
      psums along the mesh columns each), and the convergence scalars;
    * epilogue — A gathered along the mesh columns, the Upᵀ·A partials
      gathered down the rows, the symmetric factor H replicated along the
      columns, and the U = Up·V partials gathered along the columns.
    """
    r, c = (max(int(s), 1) for s in mesh_shape)
    mloc = -(-int(m) // r)
    nloc = -(-int(n) // c)
    Np = c * nloc
    nploc = -(-Np // r)
    Npr = r * nploc

    def _steps(*steps):
        return (
            sum(s["ring_hops_per_device"] for s in steps),
            sum(s["exact_wire_bytes"] for s in steps),
            sum(s["wire_bytes"] for s in steps),
        )

    scalar = _steps(
        ring_wire_model(1, r, mode, op="allgather"),
        ring_wire_model(1, c, mode, op="allgather"),
    )
    qr_m = grid_qr_model(
        r * (mloc + nploc), Np, (r, c), mode=mode,
        compute_ms_per_step=compute_ms_per_step, gbps=gbps,
    )
    combine = _steps(
        ring_wire_model(nploc * nloc, r, mode, op="allgather"),
        *(
            [
                ring_wire_model(mloc * nloc, c, mode, op="allreduce"),
                ring_wire_model(Npr * nloc, c, mode, op="allreduce"),
            ]
            * c
        ),
    )
    per_iter = (
        qr_m["hops"] + combine[0] + scalar[0],
        qr_m["exact_wire_bytes"] + combine[1] + scalar[1],
        qr_m["wire_bytes"] + combine[2] + scalar[2],
    )
    epilogue = _steps(
        ring_wire_model(mloc * nloc, c, mode, op="allgather"),
        ring_wire_model(nloc * Np, r, mode, op="allgather"),
        ring_wire_model(nloc * Np, c, mode, op="allgather"),
        ring_wire_model(mloc * Np, c, mode, op="allgather"),
    )
    it = max(int(iterations), 1)
    hops = scalar[0] + it * per_iter[0] + epilogue[0]
    exact = scalar[1] + it * per_iter[1] + epilogue[1]
    wire = scalar[2] + it * per_iter[2] + epilogue[2]
    peak = qr_m["peak_live_bytes"] + 4 * (
        2 * mloc * nloc + Npr * nloc + mloc * Npr + 2 * Np * Np
    )
    return {
        "mesh": (r, c),
        "iterations": it,
        "per_iteration_wire_bytes": per_iter[2],
        "qr_wire_bytes": qr_m["wire_bytes"],
        "hops": hops,
        "exact_wire_bytes": exact,
        "wire_bytes": wire,
        "bytes_ratio": round(wire / exact, 4) if exact else None,
        "peak_live_bytes": peak,
        "critical_path_ms": {
            "serial": critical_path_ms(
                wire, hops, compute_ms_per_step, gbps=gbps, overlap=False
            ),
            "overlap": critical_path_ms(
                wire, hops, compute_ms_per_step, gbps=gbps, overlap=True
            ),
        },
    }
