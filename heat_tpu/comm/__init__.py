"""Compressed-collective and planned-redistribution layer under the comm seam.

``ht.comm.set_collective_precision("int8_block")`` flips every eligible
cross-device combine — the comm layer's ``allreduce``/``allgather``, the
``_operations`` reduce paths, statistics moments, and the GaussianNB /
Lasso / k-means fit loops — onto block-scaled quantized ring collectives
with no call-site changes.  See :mod:`heat_tpu.comm.compressed` for the
wire format and the error-feedback machinery.

``ht.comm.set_redistribution("planned")`` routes ``resplit`` /
``alltoall`` / ``commit_split`` through the redistribution planner
(:mod:`heat_tpu.comm.redistribute`): every eligible layout change
compiles to a minimal-traffic, bounded-memory schedule of
allgather / dynamic-slice / ppermute steps executed as one dispatch
(arXiv 2112.01075; docs/design.md §14).

``ht.comm.set_overlap("on")`` switches every hot ring — attention,
compressed allreduce/allgather, planned-redistribution rotations,
``ring_map`` — onto its double-buffered latency-hiding body, which
issues each round's ``ppermute`` while the previous round's operand is
consumed (:mod:`heat_tpu.comm.overlap`; docs/design.md §18).  Values
stay bitwise-identical to the serial bodies.
"""

from . import compressed, redistribute
from ._costs import stream_model
from .overlap import (
    get_overlap,
    overlap,
    overlap_enabled,
    set_overlap,
)
from .redistribute import (
    Plan,
    get_redistribution,
    get_redistribution_threshold,
    grid_redistribute_or_none,
    monolithic_model,
    plan,
    redistribution,
    set_redistribution,
    set_redistribution_threshold,
)
from .compressed import (
    BLOCK,
    allgather_q,
    allreduce_q,
    collective_precision,
    dequantize_blocks,
    get_collective_precision,
    get_collective_threshold,
    quantize_blocks,
    reduce_mode,
    ring_allgather_q,
    ring_allreduce_q,
    ring_allreduce_q_ef,
    set_collective_precision,
    set_collective_threshold,
)

__all__ = [
    "BLOCK",
    "Plan",
    "allgather_q",
    "allreduce_q",
    "collective_precision",
    "compressed",
    "dequantize_blocks",
    "get_collective_precision",
    "get_collective_threshold",
    "get_overlap",
    "get_redistribution",
    "get_redistribution_threshold",
    "grid_redistribute_or_none",
    "monolithic_model",
    "overlap",
    "overlap_enabled",
    "plan",
    "quantize_blocks",
    "redistribute",
    "redistribution",
    "reduce_mode",
    "ring_allgather_q",
    "ring_allreduce_q",
    "ring_allreduce_q_ef",
    "set_collective_precision",
    "set_collective_threshold",
    "set_overlap",
    "set_redistribution",
    "set_redistribution_threshold",
    "stream_model",
]
