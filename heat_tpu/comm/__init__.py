"""Compressed-collective layer under the comm seam.

``ht.comm.set_collective_precision("int8_block")`` flips every eligible
cross-device combine — the comm layer's ``allreduce``/``allgather``, the
``_operations`` reduce paths, statistics moments, and the GaussianNB /
Lasso / k-means fit loops — onto block-scaled quantized ring collectives
with no call-site changes.  See :mod:`heat_tpu.comm.compressed` for the
wire format and the error-feedback machinery.
"""

from . import compressed
from .compressed import (
    BLOCK,
    allgather_q,
    allreduce_q,
    collective_precision,
    dequantize_blocks,
    get_collective_precision,
    get_collective_threshold,
    quantize_blocks,
    reduce_mode,
    ring_allgather_q,
    ring_allreduce_q,
    ring_allreduce_q_ef,
    set_collective_precision,
    set_collective_threshold,
)

__all__ = [
    "BLOCK",
    "allgather_q",
    "allreduce_q",
    "collective_precision",
    "compressed",
    "dequantize_blocks",
    "get_collective_precision",
    "get_collective_threshold",
    "quantize_blocks",
    "reduce_mode",
    "ring_allgather_q",
    "ring_allreduce_q",
    "ring_allreduce_q_ef",
    "set_collective_precision",
    "set_collective_threshold",
]
